"""Training dashboard: StatsListener -> StatsStorage -> HTTP server (the
reference's `UIServer.getInstance().attach(statsStorage)` flow).

Open http://127.0.0.1:<port> while it trains; Ctrl-C to stop."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np

from deeplearning4j_tpu import DataSet, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import inputs
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats_listener import StatsListener
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def main(iterations: int = 200, serve_forever: bool = False):
    storage = InMemoryStatsStorage()
    server = UIServer(storage, port=0).start()

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater("adam").learning_rate(0.01)
            .activation("relu").weight_init("xavier").list()
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=5))
            .set_input_type(inputs.feed_forward(20))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, update_frequency=5))

    rng = np.random.RandomState(0)
    x = rng.randn(512, 20).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 512)]
    print(f"dashboard: http://127.0.0.1:{server.port}")
    for _ in range(iterations):
        net.fit(DataSet(x, y))
    print("final score:", net.score())
    if serve_forever:
        import threading
        threading.Event().wait()
    server.stop()
    return net.score()


if __name__ == "__main__":
    main(serve_forever=False)
