"""Keras-1 h5 model import: writes a Keras-1-format file the way Keras
1.x did (model_config attr + per-layer weight groups), imports it, and
predicts (the reference's KerasModelImport entry points)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import json
import tempfile

import numpy as np

from deeplearning4j_tpu.keras.keras_model_import import (
    import_keras_sequential_model_and_weights)


def main():
    import h5py
    rng = np.random.RandomState(0)
    W1, b1 = rng.randn(12, 8).astype(np.float32), np.zeros(8, np.float32)
    W2, b2 = rng.randn(8, 3).astype(np.float32), np.zeros(3, np.float32)
    conf = {"class_name": "Sequential", "config": [
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 8,
                    "activation": "relu",
                    "batch_input_shape": [None, 12]}},
        {"class_name": "Dense",
         "config": {"name": "dense_2", "output_dim": 3,
                    "activation": "softmax"}},
    ]}
    with tempfile.NamedTemporaryFile(suffix=".h5") as tmp:
        with h5py.File(tmp.name, "w") as f:
            f.attrs["model_config"] = json.dumps(conf).encode()
            g = f.create_group("model_weights")
            for name, W, b in (("dense_1", W1, b1), ("dense_2", W2, b2)):
                lg = g.create_group(name)
                lg.create_dataset(f"{name}_W", data=W)
                lg.create_dataset(f"{name}_b", data=b)
                lg.attrs["weight_names"] = [f"{name}_W".encode(),
                                            f"{name}_b".encode()]
        net = import_keras_sequential_model_and_weights(tmp.name)

    x = rng.randn(4, 12).astype(np.float32)
    probs = np.asarray(net.output(x))
    print("predictions:", probs.argmax(1), " row sums:", probs.sum(1))
    np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-5)
    return probs


if __name__ == "__main__":
    main()
