"""Character-level language modeling with GravesLSTM + truncated BPTT,
then sampling one character at a time with `rnn_time_step` (the
`dl4j-examples` GravesLSTMCharModellingExample)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np

from deeplearning4j_tpu import DataSet, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM, RnnOutputLayer

TEXT = ("the quick brown fox jumps over the lazy dog. " * 40
        + "pack my box with five dozen liquor jugs. " * 40)


def main(epochs: int = 30, hidden: int = 64, seq: int = 32):
    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    v = len(chars)

    conf = (NeuralNetConfiguration.builder()
            .seed(12).updater("rmsprop").learning_rate(0.05)
            .weight_init("xavier")
            .list()
            .backprop_type("tbptt")
            .t_bptt_forward_length(seq).t_bptt_backward_length(seq)
            .layer(GravesLSTM(n_in=v, n_out=hidden, activation="tanh"))
            .layer(RnnOutputLayer(n_in=hidden, n_out=v,
                                  activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    ids = np.array([idx[c] for c in TEXT])
    n = (len(ids) - 1) // seq
    x = np.eye(v, dtype=np.float32)[ids[:n * seq].reshape(n, seq)]
    y = np.eye(v, dtype=np.float32)[ids[1:n * seq + 1].reshape(n, seq)]
    ds = DataSet(x, y)                       # (batch, time, features)

    for _ in range(epochs):
        net.fit(ds)
    print("final score:", net.score())

    # sample: feed one character at a time, carrying the rnn state
    net.rnn_clear_previous_state()
    rng = np.random.RandomState(0)
    ch = idx["t"]
    out = ["t"]
    for _ in range(60):
        probs = np.asarray(net.rnn_time_step(
            np.eye(v, dtype=np.float32)[[ch]]))[0]
        ch = int(rng.choice(v, p=probs / probs.sum()))
        out.append(chars[ch])
    print("sample:", "".join(out))
    return net.score()


if __name__ == "__main__":
    main()
