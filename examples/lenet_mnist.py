"""LeNet-5 on MNIST: native prefetch ring, on-chip multi-step scan,
save/restore round trip (the `dl4j-examples` LenetMnistExample)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import tempfile

import numpy as np

from deeplearning4j_tpu import restore_multi_layer_network, write_model
from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def main(num_examples: int = 6400, epochs: int = 2) -> float:
    from deeplearning4j_tpu.nn.precision import default_compute_dtype
    net = MultiLayerNetwork(
        lenet(compute_dtype=default_compute_dtype())).init()

    # AsyncDataSetIterator rides the C++ prefetch ring when the native
    # lib builds (shuffle + batch gather off the GIL)
    it = AsyncDataSetIterator(MnistDataSetIterator(128, num_examples))
    print("native prefetch:", it.native)
    net.fit(it, epochs=epochs)
    it.close()

    ev = net.evaluate(MnistDataSetIterator(500, 2000, train=False))
    print("accuracy:", ev.accuracy())

    with tempfile.NamedTemporaryFile(suffix=".zip") as tmp:
        write_model(net, tmp.name)
        again = restore_multi_layer_network(tmp.name)
    x = np.zeros((1, 784), np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(again.output(x)), atol=1e-6)
    return ev.accuracy()


if __name__ == "__main__":
    acc = main()
    assert acc > 0.95, acc
