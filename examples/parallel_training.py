"""Data-parallel training with ParallelWrapper: local-SGD over the
device mesh with parameter + updater-state averaging (the reference's
ParallelWrapper usage pattern).

Run on a multi-chip TPU host to shard over real chips, or anywhere with
`XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu`
for a virtual 8-device mesh."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np

from deeplearning4j_tpu import DataSet, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import inputs
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper


def main(workers: int = None, rounds: int = 20):
    import jax
    workers = workers or min(4, len(jax.devices()))
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater("adam").learning_rate(0.01)
            .activation("tanh").weight_init("xavier").list()
            .layer(DenseLayer(n_out=24))
            .layer(OutputLayer(n_out=3))
            .set_input_type(inputs.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 3)
    pw = (ParallelWrapper.Builder(net)
          .workers(workers)
          .averaging_frequency(2)
          .report_score_after_averaging(True)
          .build())

    def batches(n):
        x = rng.randn(n * 2 * workers * 32, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.argmax(x @ w_true, 1)]
        return [DataSet(x[i * 32:(i + 1) * 32], y[i * 32:(i + 1) * 32])
                for i in range(n * 2 * workers)]

    s0 = None
    for _ in range(rounds):
        pw.fit(batches(1))
        if s0 is None:
            s0 = pw.last_score
    print(f"score over {workers} workers: {s0:.4f} -> "
          f"{pw.last_score:.4f}")
    assert pw.last_score < s0
    return pw.last_score


if __name__ == "__main__":
    main()
