"""Minimal classifier: builder config -> fit -> evaluate (the
`dl4j-examples` iris MLP)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.iris import iris_dataset
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import inputs
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer


def main(epochs: int = 60) -> float:
    conf = (NeuralNetConfiguration.builder()
            .seed(42).updater("adam").learning_rate(0.02)
            .activation("tanh").weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(inputs.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()

    ds = iris_dataset()
    it = ListDataSetIterator(ds, batch_size=30, shuffle=True, seed=0)
    net.fit(it, epochs=epochs)

    ev = net.evaluate(ListDataSetIterator(ds, batch_size=50))
    print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    acc = main()
    assert acc > 0.9, acc
