"""Transfer learning: train a base model, freeze its feature extractor,
swap the head for a new class count, fine-tune (the `dl4j-examples`
transfer-learning examples on the reference's TransferLearning API)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np

from deeplearning4j_tpu import DataSet, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import inputs
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transfer import TransferLearning


def main():
    rng = np.random.RandomState(0)

    # base task: 4 classes
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater("adam").learning_rate(0.02)
            .activation("relu").weight_init("xavier").list()
            .layer(DenseLayer(n_out=32))
            .layer(DenseLayer(n_out=16))
            .layer(OutputLayer(n_out=4))
            .set_input_type(inputs.feed_forward(10))
            .build())
    base = MultiLayerNetwork(conf).init()
    xb = rng.randn(256, 10).astype(np.float32)
    yb = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 256)]
    for _ in range(10):
        base.fit(DataSet(xb, yb))

    # new task: 2 classes; keep + freeze the trunk, replace the head
    net = (TransferLearning.builder(base)
           .fine_tune_learning_rate(0.01)
           .set_feature_extractor(1)          # freeze layers 0..1
           .remove_output_layer()
           .add_layer(OutputLayer(n_in=16, n_out=2))
           .build())

    x = rng.randn(256, 10).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    frozen_before = np.asarray(net.params[0]["W"])
    before = net.score(DataSet(x, y))
    for _ in range(30):
        net.fit(DataSet(x, y))
    after = net.score(DataSet(x, y))
    print(f"fine-tune score: {before:.4f} -> {after:.4f}")

    # the frozen trunk did not move
    np.testing.assert_array_equal(frozen_before,
                                  np.asarray(net.params[0]["W"]))
    assert after < before
    return after


if __name__ == "__main__":
    main()
