"""Serve a trained model through the C++ PJRT runtime: parameters upload
once into persistent device buffers, each request stages only the
activations, and executables are cached per input shape
(`nn/native_runtime.NativeModelRunner` — the cuDNN-helper/ND4J-backend
deployment role, with zero Python/JAX dispatch on the hot path)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np

from deeplearning4j_tpu import DataSet, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import inputs
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer


def main():
    conf = (NeuralNetConfiguration.builder()
            .seed(9).updater("adam").learning_rate(0.02)
            .activation("relu").weight_init("xavier").list()
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=4))
            .set_input_type(inputs.feed_forward(16))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    for _ in range(5):
        net.fit(DataSet(rng.randn(64, 16).astype(np.float32),
                        np.eye(4, dtype=np.float32)[
                            rng.randint(0, 4, 64)]))

    try:
        from deeplearning4j_tpu.nn.native_runtime import NativeModelRunner
        runner = NativeModelRunner(net)
    except RuntimeError as e:
        print(f"no PJRT plugin available ({e}); skipping native serve")
        return None

    with runner:
        for batch in (8, 8, 3):
            x = rng.randn(batch, 16).astype(np.float32)
            y = runner.output(x)
            # one compiled executable per distinct input shape; the
            # repeated batch-8 call reuses its entry (runner-side lookup
            # — the C++ cache's hit counter only moves on re-COMPILES)
            stats = runner.cache_stats()
            print(f"batch {batch}: native output {y.shape}, "
                  f"client cache {stats}")
        assert runner.cache_stats()["entries"] == 2   # 2 shapes, 3 calls
        jax_out = np.asarray(net.output(x))
        np.testing.assert_allclose(y, jax_out, rtol=2e-2, atol=2e-3)
    print("native output matches the JAX path")
    return True


if __name__ == "__main__":
    main()
