"""Sustained real-training proof through the PRODUCT API (not the bench
harness): LeNet-MNIST to high test accuracy, and a multi-thousand-step
ResNet-50 run — both with PerformanceListener + CheckpointListener +
StatsListener attached, so the full loop (listeners, checkpointing,
stats storage, eval) is exercised at real scale.

Reference analogue: the dl4j-examples training mains driving
``MultiLayerNetwork.fit`` with listeners attached
(``optimize/listeners/PerformanceListener.java:99-102`` is the metric
surface being exercised).

Prints one JSON line per config:
    {"config": ..., "epochs"/"steps": ..., "wall_s": ...,
     "samples_per_sec": ..., "accuracy": ..., "checkpoints": N,
     "stats_reports": N}
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

import numpy as np


def _bf16_if_tpu():
    # shared backend-default from the precision module (DL4J_TPU_PRECISION
    # aware) — see docs/PERFORMANCE.md
    from deeplearning4j_tpu.nn.precision import default_compute_dtype
    return default_compute_dtype()


def _listeners(ckpt_dir, every_iter, stats_freq=50):
    from deeplearning4j_tpu.optimize.listeners.listeners import (
        CheckpointListener, PerformanceListener)
    from deeplearning4j_tpu.ui.stats_listener import StatsListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    storage = InMemoryStatsStorage()
    stats = StatsListener(storage, update_frequency=stats_freq)
    perf = PerformanceListener(frequency=100)
    ckpt = CheckpointListener(ckpt_dir,
                              save_every_n_iterations=every_iter,
                              keep_last=3)
    return [perf, ckpt, stats], storage, ckpt


def sustained_lenet(epochs: int = 15, batch: int = 256,
                    examples: int = 60000, target_acc: float = 0.99,
                    ckpt_every: int = 500, stats_freq: int = 50):
    """Full-MNIST LeNet through fit(iterator) (device epoch cache) to
    >= target accuracy, with the listener stack attached."""
    from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(lenet(compute_dtype=_bf16_if_tpu())).init()
    with tempfile.TemporaryDirectory() as ckpt_dir:
        listeners, storage, ckpt = _listeners(ckpt_dir,
                                              every_iter=ckpt_every,
                                              stats_freq=stats_freq)
        net.set_listeners(*listeners)
        it = MnistDataSetIterator(batch, examples)
        test = MnistDataSetIterator(500, 10000, train=False)

        t0 = time.perf_counter()
        net.fit(it, epochs=epochs)
        wall = time.perf_counter() - t0
        acc = net.evaluate(test).accuracy()
        n_ckpt = len(ckpt.saved)
        n_reports = sum(storage.num_update_records(s)
                        for s in storage.list_session_ids())
    return {"config": "lenet_mnist_sustained", "epochs": epochs,
            "iterations": net.iteration, "wall_s": round(wall, 2),
            "samples_per_sec": round(epochs * examples / wall, 1),
            "accuracy": round(float(acc), 4),
            "target_acc": target_acc, "reached": bool(acc >= target_acc),
            "checkpoints": n_ckpt, "stats_updates": n_reports}


def sustained_resnet(steps: int = 3000, batch: int = 128,
                     examples: int = 1280):
    """Multi-thousand-step ResNet-50 on synthetic ImageNet-shaped data
    through the graph fit(iterator) epoch cache, listener stack
    attached.  Features are stored bf16 on host when the chip computes
    in bf16 — the step's first action is the same cast, and the corpus
    upload is the dominant cost over a thin tunnel (measured 13 MB/s
    windows: 1.5 GB of f32 took minutes; bf16 halves it and
    examples=1280 halves it again at 10 steps/epoch)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

    net = ComputationGraph(resnet50(compute_dtype=_bf16_if_tpu())).init()
    rng = np.random.RandomState(0)
    f = rng.rand(examples, 224, 224, 3).astype(np.float32)
    if _bf16_if_tpu():
        import ml_dtypes
        f = f.astype(ml_dtypes.bfloat16)
    l = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, examples)]
    it = ListDataSetIterator(DataSet(f, l), batch)
    steps_per_epoch = examples // batch
    epochs = max(1, steps // steps_per_epoch)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        # A stats post on ResNet costs ~14 s over this tunnel (102 MB
        # param fetch + histogram pass over 25.5M params); 500-iteration
        # frequency keeps the listener exercised without dominating wall
        listeners, storage, ckpt = _listeners(ckpt_dir, every_iter=1000,
                                              stats_freq=500)
        net.set_listeners(*listeners)
        print("# resnet warmup (upload + compile)...", file=sys.stderr,
              flush=True)
        net.fit(it, epochs=1)          # warmup epoch: compile + upload
        first_score = float(net.score())
        print("# resnet warmup done", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        net.fit(it, epochs=epochs)
        wall = time.perf_counter() - t0
        final_score = float(net.score())
        n_ckpt = len(ckpt.saved)
        n_reports = sum(storage.num_update_records(s)
                        for s in storage.list_session_ids())
    return {"config": "resnet50_sustained", "steps": net.iteration,
            "timed_steps": epochs * steps_per_epoch,
            "wall_s": round(wall, 2),
            "samples_per_sec": round(
                epochs * steps_per_epoch * batch / wall, 1),
            "first_score": round(first_score, 4),
            "final_score": round(final_score, 4),
            "score_decreased": bool(final_score < first_score),
            "checkpoints": n_ckpt, "stats_updates": n_reports}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    which = argv[0] if argv else "both"
    kwargs = dict(kv.split("=") for kv in argv[1:])
    kwargs = {k: int(v) for k, v in kwargs.items()}
    if which in ("lenet", "both"):
        print(json.dumps(sustained_lenet(
            **{k: v for k, v in kwargs.items()
               if k in ("epochs", "batch", "examples")})), flush=True)
    if which in ("resnet", "both"):
        print(json.dumps(sustained_resnet(
            **{k: v for k, v in kwargs.items()
               if k in ("steps", "batch", "examples")})), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
