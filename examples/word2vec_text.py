"""Word2Vec skip-gram with negative sampling on a toy corpus, then
nearest-neighbour and analogy queries (the `dl4j-examples`
Word2VecRawTextExample)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))   # run from anywhere

from deeplearning4j_tpu.nlp.word2vec import Word2Vec

SENTENCES = [
    "king man royal crown",
    "queen woman royal crown",
    "king rules the kingdom",
    "queen rules the kingdom",
    "the king is a man",
    "the queen is a woman",
    "a man walks the dog",
    "a woman walks the dog",
    "day sun bright light",
    "night moon dark light",
] * 60


def main():
    w2v = (Word2Vec.Builder()
           .min_word_frequency(2)
           .layer_size(24)
           .window_size(3)
           .seed(1)
           .epochs(80)
           .negative(5)
           .batch_size(128)     # small corpus -> more sequential steps
           .build())
    w2v.fit(SENTENCES)

    nearest = w2v.words_nearest("king", top_n=3)
    print("nearest to 'king':", nearest)
    print("king - man + woman ->",
          w2v.words_nearest(["king", "woman"], negative=["man"], top_n=3))
    assert "queen" in nearest, nearest
    return w2v


if __name__ == "__main__":
    main()
