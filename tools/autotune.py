"""Batch-size x steps-per-dispatch autotuner (the roofline-driven MFU
campaign, tentpole part 2).

Walks the (batch, ``DL4J_TPU_MAX_STEPS_PER_DISPATCH``) ladder for a
model and scores every rung with the compiler's own cost model — the
same ``xla_cost_flops`` / ``xla_cost_bytes_accessed`` /
``xla_cost_peak_hbm_bytes`` gauges the compile-watch publishes for every
executable — plus (in measured mode) wall-clock step time.  The winner
is the best samples/sec whose peak HBM fits the cap; the decision is
cached per (model-signature, backend, precision policy) so a training
process pays the ladder walk once per model per machine.

Modes
-----
- **measured** (default): AOT-compiles the steps-deep scan program per
  rung and times ``trials`` donated dispatches; best samples/sec wins.
- **deterministic** (``--deterministic`` or
  ``DL4J_TPU_AUTOTUNE_DETERMINISTIC=1``): no wall clock at all — rungs
  are ranked by cost-model bytes/sample ascending (tie: flops/sample,
  then the larger batch/deeper dispatch).  The cost model is a pure
  function of the compiled program, so two runs on the same backend emit
  byte-identical decisions — the CI perf-smoke job asserts exactly that.
  The scan body is charged once per program by the cost model, so deeper
  dispatch stacks amortize it in the score the same way they amortize
  real dispatch overhead.

The decision is exported to the runtime by :func:`apply_decision`, which
sets ``DL4J_TPU_MAX_STEPS_PER_DISPATCH`` (read by ``nn/ingest.py`` for
every fused-scan epoch dispatch) and returns the chosen batch size.
Resolved decisions are published as ``autotune_*`` gauges alongside the
training telemetry.

Usage: python tools/autotune.py [lenet|mlp] [--deterministic] [--smoke]
           [--no-cache] [--apply]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np

DET_ENV = "DL4J_TPU_AUTOTUNE_DETERMINISTIC"
CACHE_ENV = "DL4J_TPU_AUTOTUNE_CACHE"
CAP_ENV = "DL4J_TPU_AUTOTUNE_HBM_CAP_GB"
DISPATCH_ENV = "DL4J_TPU_MAX_STEPS_PER_DISPATCH"

# per-model (batches, steps_per_dispatch) ladders: full for a tuning
# run, tiny for --smoke / CI (rates are meaningless there; only the
# ranking and its determinism are exercised)
_LADDERS = {
    "full": ((64, 128, 256, 512), (8, 32, 128)),
    "smoke": ((16, 32), (2, 4)),
}


def _cache_path() -> str:
    p = os.environ.get(CACHE_ENV)
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deeplearning4j_tpu", "autotune.json")


def _load_cache() -> dict:
    try:
        with open(_cache_path()) as f:
            return json.load(f)
    except Exception:
        return {}


def _save_cache(cache: dict) -> None:
    path = _cache_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _backend() -> str:
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "") or ""
    return f"{d.platform}:{kind}" if kind else d.platform


def hbm_cap_bytes() -> float:
    """Rungs whose compiler-reported peak HBM exceeds this are skipped.
    Env override in GB; else the device's own bytes_limit; else 16 GB."""
    env = os.environ.get(CAP_ENV)
    if env:
        return float(env) * 1e9
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return 16e9


def model_signature(conf, policy) -> str:
    """Stable id for (model architecture, backend, precision policy):
    the autotuner's cache key and the gauges' label."""
    try:
        conf_txt = conf.to_json(indent=None)
    except Exception:
        conf_txt = repr(conf)
    payload = "|".join((conf_txt, _backend(), policy.describe()))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _lenet_spec():
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def make_net():
        return MultiLayerNetwork(lenet()).init()

    def make_data(jnp, steps, batch, fdt):
        return (jnp.zeros((steps, batch, 784), fdt),
                jnp.zeros((steps, batch, 10), jnp.float32))

    return make_net, make_data


def _mlp_spec(n_in: int = 32, hidden: int = 64, n_out: int = 10):
    # tiny dense net: the determinism tests' fast signature
    from deeplearning4j_tpu.nn.conf import inputs as _inputs
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def make_net():
        conf = (NeuralNetConfiguration.builder().seed(12)
                .updater("adam").learning_rate(1e-3)
                .list()
                .layer(DenseLayer(n_out=hidden))
                .layer(OutputLayer(n_out=n_out))
                .set_input_type(_inputs.feed_forward(n_in))
                .build())
        return MultiLayerNetwork(conf).init()

    def make_data(jnp, steps, batch, fdt):
        return (jnp.zeros((steps, batch, n_in), fdt),
                jnp.zeros((steps, batch, n_out), jnp.float32))

    return make_net, make_data


_MODELS = {"lenet": _lenet_spec, "mlp": _mlp_spec}


def _rung_cost(compiled) -> dict:
    out = {}
    try:
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        out["flops"] = float(c.get("flops", 0.0))
        out["bytes"] = float(c.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        m = compiled.memory_analysis()
        out["peak_hbm"] = (float(m.argument_size_in_bytes)
                           + float(m.output_size_in_bytes)
                           + float(m.temp_size_in_bytes)
                           - float(m.alias_size_in_bytes))
    except Exception:
        pass
    return out


def deterministic_mode(flag=None) -> bool:
    if flag is not None:
        return bool(flag)
    return os.environ.get(DET_ENV, "") not in ("", "0", "false")


def autotune(model: str = "lenet", batches=None, steps_ladder=None,
             deterministic=None, use_cache: bool = True,
             trials: int = 2, smoke: bool = False) -> dict:
    """Walk the ladder and return (and cache) the decision dict."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import monitor

    det = deterministic_mode(deterministic)
    lad_b, lad_s = _LADDERS["smoke" if smoke else "full"]
    batches = tuple(batches) if batches else lad_b
    steps_ladder = tuple(steps_ladder) if steps_ladder else lad_s

    make_net, make_data = _MODELS[model]()
    probe = make_net()
    pol = probe._pol()
    sig = model_signature(probe.conf, pol)

    cache = _load_cache() if use_cache else {}
    hit = cache.get(sig)
    if hit is not None and hit.get("mode") == (
            "deterministic" if det else "measured"):
        _publish(model, hit)
        return dict(hit, cached=True)

    cap = hbm_cap_bytes()
    rungs = []
    for batch in batches:
        for steps in steps_ladder:
            net = make_net()
            f, l = make_data(jnp, steps, batch,
                             jnp.dtype(net._pol().compute_dtype))
            args = (net.params, net.updater_state, net.net_state,
                    net.iteration, f, l, None, None, net._rng_key)
            rung = {"batch": int(batch), "steps": int(steps)}
            try:
                compiled = net._multi_train_step.lower(*args).compile()
            except Exception as e:
                rung["error"] = repr(e)[:200]
                rungs.append(rung)
                continue
            rung.update(_rung_cost(compiled))
            samples = steps * batch
            if rung.get("bytes"):
                rung["bytes_per_sample"] = round(rung["bytes"] / samples, 2)
            if rung.get("flops"):
                rung["flops_per_sample"] = round(rung["flops"] / samples, 2)
            peak = rung.get("peak_hbm")
            if peak and peak > cap:
                rung["skipped"] = "hbm_cap"
                rungs.append(rung)
                continue
            if not det:
                # donated state: re-feed what the program returns
                p, u, s, scores = compiled(*args)
                float(np.asarray(scores)[-1])        # warm + barrier
                t0 = time.perf_counter()
                for _ in range(trials):
                    p, u, s, scores = compiled(p, u, s, net.iteration,
                                               f, l, None, None,
                                               net._rng_key)
                float(np.asarray(scores)[-1])
                elapsed = time.perf_counter() - t0
                rung["samples_per_sec"] = round(
                    trials * samples / elapsed, 1)
            rungs.append(rung)

    ok = [r for r in rungs
          if "error" not in r and "skipped" not in r]
    if not ok:
        raise RuntimeError("autotune: every rung failed or exceeded the "
                           "HBM cap: %r" % rungs)
    if det:
        best = min(ok, key=lambda r: (r.get("bytes_per_sample",
                                            float("inf")),
                                      r.get("flops_per_sample",
                                            float("inf")),
                                      -r["batch"], -r["steps"]))
        score = {"bytes_per_sample": best.get("bytes_per_sample")}
    else:
        best = max(ok, key=lambda r: r.get("samples_per_sec", 0.0))
        score = {"samples_per_sec": best.get("samples_per_sec")}

    decision = {"model": model, "signature": sig, "backend": _backend(),
                "policy": pol.describe(),
                "mode": "deterministic" if det else "measured",
                "batch": best["batch"],
                "steps_per_dispatch": best["steps"],
                **score,
                "hbm_cap_bytes": cap, "rungs": rungs}
    if use_cache:
        cache[sig] = decision
        try:
            _save_cache(cache)
        except Exception:
            pass
    _publish(model, decision)
    return decision


def _publish(model: str, decision: dict) -> None:
    try:
        from deeplearning4j_tpu import monitor
        sig = decision.get("signature", "")
        monitor.gauge("autotune_batch",
                      "autotuned batch size").set(
            float(decision["batch"]), model=model, signature=sig)
        monitor.gauge("autotune_steps_per_dispatch",
                      "autotuned DL4J_TPU_MAX_STEPS_PER_DISPATCH").set(
            float(decision["steps_per_dispatch"]), model=model,
            signature=sig)
        if decision.get("bytes_per_sample"):
            monitor.gauge("autotune_bytes_per_sample",
                          "cost-model HBM bytes per sample at the chosen "
                          "rung").set(float(decision["bytes_per_sample"]),
                                      model=model, signature=sig)
        if decision.get("samples_per_sec"):
            monitor.gauge("autotune_samples_per_sec",
                          "measured samples/sec at the chosen rung").set(
                float(decision["samples_per_sec"]), model=model,
                signature=sig)
        monitor.gauge("autotune_rungs_evaluated",
                      "ladder rungs walked for the decision").set(
            float(len(decision.get("rungs", ()))), model=model,
            signature=sig)
    except Exception:
        pass


def apply_decision(decision: dict) -> int:
    """Export the decision to the runtime: the fused-scan dispatcher
    reads DL4J_TPU_MAX_STEPS_PER_DISPATCH on every epoch dispatch
    (nn/ingest.py), so setting it here retunes fit() without any API
    change.  Returns the chosen batch size for the caller's iterator."""
    os.environ[DISPATCH_ENV] = str(int(decision["steps_per_dispatch"]))
    return int(decision["batch"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model", nargs="?", default="lenet",
                    choices=sorted(_MODELS))
    ap.add_argument("--deterministic", action="store_true",
                    help="rank by cost model only (no wall clock)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI ladder")
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument("--apply", action="store_true",
                    help="print the env export line for the decision")
    args = ap.parse_args(argv)
    decision = autotune(args.model, deterministic=args.deterministic
                        or None, use_cache=not args.no_cache,
                        smoke=args.smoke)
    print(json.dumps(decision, sort_keys=True), flush=True)
    if args.apply:
        apply_decision(decision)
        print(f"export {DISPATCH_ENV}={decision['steps_per_dispatch']}",
              file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
