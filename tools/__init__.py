# repo tooling (hbm_profile, autotune); a package so bench.py and the
# tests can import the shared helpers without path games.
