"""Render a trace dump or flight-recorder bundle as a loadable Perfetto
file.

Inputs (auto-detected):

- a **flight-recorder bundle directory** (contains ``spans.json`` with
  ``{"complete": [...], "active": [...]}`` internal span events),
- a **spans.json** file from such a bundle,
- a ``/trace`` **JSONL dump** (one Chrome event per line) or a
  ``/trace?format=chrome`` **JSON array** (already Chrome events),
- a ``http://host:port/trace`` **URL** (fetched with stdlib urllib).

Output: a single JSON array of Chrome trace events — the format both
Perfetto (ui.perfetto.dev) and chrome://tracing load directly.  Spans
from different processes keep their recording ``pid`` so a merged
multi-process dump (e.g. serving front end + param-server worker)
separates into per-process tracks; still-open spans from a bundle are
rendered with an ``unfinished: true`` arg and the duration observed at
dump time.

Multiple sources merge into one timeline — each worker's events keep
their recording ``pid``, so a K-process async run (one ``--trace-out``
file per scaleout worker plus the server's ``D``-frame dump,
docs/SCALEOUT.md) renders as per-process tracks sharing trace ids.

Usage::

    python tools/trace_view.py <bundle-dir|spans.json|trace.jsonl|URL>...
        [-o out.trace.json]

Prints a one-line summary (events, traces, pids) on success and exits
non-zero on anything unparseable — CI uses that as the "bundle is
renderable" gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

_CHROME_KEYS = {"name", "ph", "ts", "pid", "tid"}


def _internal_to_chrome(ev: Dict, unfinished: bool = False,
                        dump_ts: float = 0.0) -> Dict:
    """One monitor-internal span event -> one Chrome complete event."""
    if unfinished:
        dur_ms = max(0.0, (dump_ts - float(ev["ts"])) * 1e3) \
            if dump_ts else 0.0
    else:
        dur_ms = float(ev.get("dur_ms", 0.0))
    args = dict(ev.get("attrs") or {}, span_id=ev.get("id"),
                parent=ev.get("parent"), trace_id=ev.get("trace"))
    if ev.get("links"):
        args["links"] = ev["links"]
    if unfinished:
        args["unfinished"] = True
    return {
        "name": ev.get("name", "?"),
        "ph": "X",
        "ts": round(float(ev["ts"]) * 1e6, 1),
        "dur": round(dur_ms * 1e3, 1),
        "pid": ev.get("pid", 0),
        "tid": ev.get("thread", 0),
        "args": args,
    }


def _looks_chrome(ev: Dict) -> bool:
    return _CHROME_KEYS <= set(ev)


def _from_events(events: List[Dict], active: List[Dict],
                 dump_ts: float = 0.0) -> List[Dict]:
    out = []
    for ev in events:
        out.append(ev if _looks_chrome(ev) else _internal_to_chrome(ev))
    for ev in active:
        out.append(_internal_to_chrome(ev, unfinished=True,
                                       dump_ts=dump_ts))
    return out


def _load_text(text: str) -> List[Dict]:
    """Parse a /trace body: JSON array, JSONL, or a spans.json object."""
    text = text.strip()
    if not text:
        return []
    try:
        obj = json.loads(text)
    except ValueError:
        # JSONL: one event per line
        events = [json.loads(line) for line in text.splitlines() if line]
        return _from_events(events, [])
    if isinstance(obj, list):
        return _from_events(obj, [])
    if isinstance(obj, dict) and "complete" in obj:
        dump_ts = max((float(e.get("ts", 0.0)) +
                       float(e.get("dur_ms", 0.0)) / 1e3
                       for e in obj.get("complete", [])), default=0.0)
        return _from_events(obj.get("complete", []),
                            obj.get("active", []), dump_ts)
    if isinstance(obj, dict) and "events" in obj:
        # a TcpParameterServerClient.dump_trace() payload
        return _from_events(obj["events"], [])
    raise ValueError("unrecognized trace JSON shape "
                     f"(top-level {type(obj).__name__})")


def load(source: str) -> List[Dict]:
    """Chrome events from any supported source (see module docstring)."""
    if source.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(source, timeout=10) as resp:
            return _load_text(resp.read().decode("utf-8", "replace"))
    if os.path.isdir(source):
        spans = os.path.join(source, "spans.json")
        if not os.path.exists(spans):
            raise FileNotFoundError(
                f"{source} is a directory but has no spans.json — "
                "not a flight-recorder bundle")
        with open(spans) as f:
            return _load_text(f.read())
    with open(source) as f:
        return _load_text(f.read())


def summarize(events: List[Dict]) -> str:
    traces = {e.get("args", {}).get("trace_id") for e in events}
    traces.discard(None)
    pids = {e.get("pid") for e in events}
    return (f"{len(events)} events, {len(traces)} traces, "
            f"{len(pids)} pids")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert a /trace dump or flight-recorder bundle "
                    "into a Perfetto/Chrome trace file.")
    ap.add_argument("sources", nargs="+", metavar="source",
                    help="bundle dirs, spans.json files, /trace dumps, "
                    "or URLs — all merged into one timeline")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <first-source>"
                    ".trace.json, or stdout with '-')")
    args = ap.parse_args(argv)
    events = []
    for source in args.sources:
        try:
            events.extend(load(source))
        except Exception as e:
            print(f"error: {source}: {e}", file=sys.stderr)
            return 1
    if not events:
        print("error: no trace events in source", file=sys.stderr)
        return 1
    body = json.dumps(events)
    if args.out == "-":
        sys.stdout.write(body + "\n")
    else:
        out = args.out or (args.sources[0].rstrip("/") + ".trace.json")
        with open(out, "w") as f:
            f.write(body)
        print(f"wrote {out}: {summarize(events)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
