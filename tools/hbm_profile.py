"""Op-level HBM profile of a compiled train step (round-4 verdict item
6: ResNet-50 sits at the HBM roofline — record WHICH ops stream the
bytes, and whether any traffic is avoidable).

Method: AOT-compile the 1-step train program (the same executable the
bench's roofline uses), then parse the optimized HLO.  At the
post-fusion level, every instruction's operands and outputs are real
buffers — intra-fusion temporaries have been fused away — so
bytes(instr) = output bytes + sum(operand bytes) approximates that
instruction's HBM traffic (upper bound: operands resident in VMEM
across consumers are charged to each).  This is the same accounting
XLA's own cost model uses for "bytes accessed", but per-op instead of
aggregate.

Reference analogue: the cuDNN tier's workspace/memory accounting
(``CudnnConvolutionHelper.java:64-140``) — the reference's only
memory-tuning surface.

Usage: python tools/hbm_profile.py
           [resnet|lenet|vgg|gather|glove|glove-naive] [top_n]

The audit defaults to the **TPU default precision policy**
(``mixed_bf16``: bf16 params + bf16 activations + fp32 masters in the
updater state) even on CPU, so the cost-model/HLO numbers reflect the
program the chip would actually run.  Set ``DL4J_TPU_PRECISION=fp32``
to audit the fp32 program instead and compare bytes side by side.

``gather`` profiles the epoch-cache v2 program
(``MultiLayerNetwork._gather_train_step``): on-device threefry epoch
permutation, per-step row gather from the resident uint8 cache, fused
decode to f32/bf16, scan over the epoch — the program whose HBM
behaviour the device-resident ingest rework is accountable for.
"""

import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u64": 8, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1}

_SHAPE_RE = re.compile(r"(f64|f32|bf16|f16|s64|s32|u64|u32|s16|u16|s8|u8"
                       r"|pred)\[([0-9,]*)\]")


def shape_bytes(shape_str: str, by_dtype=None) -> int:
    """Total bytes of every array shape mentioned in an HLO type string
    (handles tuples by summing members).  When ``by_dtype`` (a dict) is
    given, per-dtype byte totals are accumulated into it as well."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        b = n * _DTYPE_BYTES[dtype]
        total += b
        if by_dtype is not None:
            by_dtype[dtype] = by_dtype.get(dtype, 0) + b
    return total


_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\([^)]*\)|\S+)\s+"
    r"([\w\-]+)\((.*)$")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")


def profile_hlo(hlo_text: str):
    """Parse optimized HLO; return (rows, total_bytes, by_dtype) where
    rows are ALL (bytes, op_kind, name, out_shape) entries, largest
    first, and by_dtype maps HLO dtype tag -> traffic bytes.

    Computation-aware: instructions INSIDE fusion bodies
    (``%fused_computation*``) and scalar reducer/comparator regions are
    NOT HBM traffic — only the entry computation and control-flow
    bodies (while/cond) stream buffers.  Counting fusion-body
    instructions overstates traffic ~10x (measured vs the XLA cost
    model on ResNet-50).  Control-flow wrapper ops (while, tuple,
    get-tuple-element, parameter, constant) are skipped — their
    "operands" are whole state tuples, not streamed traffic."""
    shapes = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m:
            shapes[m.group(1)] = m.group(2)
    skip = {"parameter", "constant", "tuple", "get-tuple-element",
            "while", "conditional", "call", "bitcast", "copy-start",
            "copy-done", "after-all", "partition-id"}
    rows = []
    total = 0
    by_dtype = {}
    in_excluded = False
    depth = 0
    for line in hlo_text.splitlines():
        stripped = line.strip()
        # computation-block bookkeeping: a top-level "name (...) -> T {"
        # line opens a computation; exclude fusion bodies and scalar
        # regions (reducers, comparators, scatter combiners).
        if not line.startswith(" ") and stripped.endswith("{"):
            cname = stripped.split("(")[0].strip().lstrip("%")
            in_excluded = any(tag in cname for tag in
                              ("fused_computation", "region_",
                               "scatter_computation", "AddComputation",
                               "MaxComputation", "add_computation",
                               "max_computation", "and.reduce",
                               "or.reduce"))
            continue
        if not line.startswith(" ") and stripped == "}":
            in_excluded = False
            continue
        if in_excluded:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_shape, kind, rest = m.groups()
        if kind in skip or kind.endswith("-start"):
            continue      # -start halves pair with -done; count once
        out_b = shape_bytes(out_shape, by_dtype)
        if kind in ("slice", "dynamic-slice", "dynamic-update-slice",
                    "broadcast", "reshape", "transpose", "reverse"):
            # These read/write only the window/output, not the full
            # operand: charging operand bytes overstated slices to 42%
            # of ResNet's total.  (dynamic-update-slice writes a
            # window into an aliased buffer: window read + write.)
            shape_bytes(out_shape, by_dtype)
            b = 2 * out_b
        else:
            arg_str = rest.split(", calls=")[0].split(", metadata=")[0]
            b = out_b
            for op in _OPERAND_RE.findall(arg_str):
                if op in shapes:
                    b += shape_bytes(shapes[op], by_dtype)
        rows.append((b, kind, name, out_shape))
        total += b
    rows.sort(reverse=True)
    return rows, total, by_dtype


def _classify(kind: str, name: str, shape: str) -> str:
    if kind in ("convolution", "custom-call") and "conv" in name:
        return "conv"
    if kind == "fusion":
        return "fusion"
    if kind in ("dot",):
        return "matmul"
    if "scatter" in kind:
        return "scatter"
    return kind


def compiled_step(config: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.precision import default_compute_dtype
    cdt = default_compute_dtype()       # DL4J_TPU_PRECISION-aware

    if config == "resnet":
        from deeplearning4j_tpu.models.resnet import resnet50
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        net = ComputationGraph(resnet50(compute_dtype=cdt)).init()
        fdt = jnp.dtype(net._pol().compute_dtype)
        batch = 128
        f = [jnp.zeros((1, batch, 224, 224, 3), fdt)]
        l = [jnp.zeros((1, batch, 1000), jnp.float32)]
    elif config == "vgg":
        from deeplearning4j_tpu.keras.trained_models import vgg16
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(vgg16(compute_dtype=cdt)).init()
        fdt = jnp.dtype(net._pol().compute_dtype)
        batch = 256
        f = jnp.zeros((1, batch, 224, 224, 3), fdt)
        l = jnp.zeros((1, batch, 1000), jnp.float32)
    elif config == "gather":
        # epoch-cache v2: resident uint8 MNIST cache, device threefry
        # permutation, row gather + fused decode, one-epoch scan
        from deeplearning4j_tpu.models.lenet import lenet
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(lenet(compute_dtype=cdt)).init()
        n, batch = 60000, 256
        f = jnp.zeros((n, 784), jnp.uint8)
        l = jnp.zeros((n, 10), jnp.float32)
        shuffle_key = jax.random.fold_in(net._rng_key, 0xFFFFFFFF)
        steps = n // batch
        args = (net.params, net.updater_state, net.net_state,
                net.iteration, f, l, net._rng_key, shuffle_key, 0, 1,
                steps, batch, True, 0, (255.0, 1.0, 0.0), 0, steps)
        return net._gather_train_step.lower(*args).compile(), net
    elif config in ("glove", "glove-naive"):
        # scatter-row audit for the embedding economics work: compile a
        # 1-chunk GloVe epoch twin and count its scatter instructions.
        # The fused dual-buffer path must show TWO scatters (one per
        # packed side table, sorted-unique); the naive reference shows
        # EIGHT (W/b/hW/hb x2 sides), each a colliding duplicate-row
        # scatter.  Same audit surface as the ResNet conv rows.
        from deeplearning4j_tpu.nlp.glove import (_glove_epoch,
                                                  _glove_epoch_fused)
        V, D, B = 20000, 128, 8192
        rows = jnp.zeros((B,), jnp.int32)
        cols = jnp.zeros((B,), jnp.int32)
        logx = jnp.zeros((B,), jnp.float32)
        fx = jnp.zeros((B,), jnp.float32)
        order = jnp.zeros((1, B), jnp.int32)
        lr = jnp.float32(0.05)
        if config == "glove":
            Sr = jnp.zeros((V, 2 * D + 2), jnp.float32)
            Sc = jnp.zeros((V, 2 * D + 2), jnp.float32)
            return _glove_epoch_fused.lower(Sr, Sc, rows, cols, logx,
                                            fx, order, lr).compile(), None
        W = jnp.zeros((V, D), jnp.float32)
        tabs = (W, W + 0, jnp.zeros((V,)), jnp.zeros((V,)), W + 0,
                W + 0, jnp.zeros((V,)), jnp.zeros((V,)))
        return _glove_epoch.lower(*tabs, rows, cols, logx, fx,
                                  order, lr).compile(), None
    else:
        from deeplearning4j_tpu.models.lenet import lenet
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(lenet(compute_dtype=cdt)).init()
        fdt = jnp.dtype(net._pol().compute_dtype)
        batch = 256
        f = jnp.zeros((1, batch, 784), fdt)
        l = jnp.zeros((1, batch, 10), jnp.float32)
    args = (net.params, net.updater_state, net.net_state, net.iteration,
            f, l, None, None, net._rng_key)
    return net._multi_train_step.lower(*args).compile(), net


# The recorded fp32 LeNet row this campaign is measured against
# (BENCH_r05.json batch-256 hbm_bytes_per_step; ISSUE 7 acceptance).
BENCH_R05_LENET_BYTES = 117_648_384

# configs whose step comes from a network (fp32 twin is comparable)
_NET_CONFIGS = ("resnet", "lenet", "vgg", "gather")


def chip_posture_estimate(total_f32: float, f32_traffic: float,
                          moments_io: float, master_io: float,
                          masters: bool) -> float:
    """Project the fp32 program's traffic onto the chip under the bf16
    policy: every f32 buffer the fp32 program streams becomes bf16 on
    the TPU (activations, params, grads — x0.5) EXCEPT the updater
    moments, which the mixed policy keeps fp32 (restored at full width),
    plus one fp32 read + write of the master copies per step.  CPU-XLA
    cannot show this directly — it upcasts bf16 conv/dot to f32 through
    convert fusions, so the raw bf16-program cost model OVERSTATES chip
    traffic (measured: LeNet b256 366 MB bf16 vs 324 MB fp32)."""
    est = total_f32 - 0.5 * f32_traffic + 0.5 * moments_io
    if masters:
        est += master_io
    return est


def _policy_comparison(config: str, pol, cost_bytes_pol: float) -> None:
    """Compile the fp32 twin of ``config`` and print the CPU-posture
    bytes comparison (ISSUE 7 acceptance: LeNet bytes/step must
    measurably drop under the default TPU policy)."""
    import jax

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.nn import precision

    prev = os.environ.get(precision._ENV)
    os.environ[precision._ENV] = precision.FP32
    try:
        compiled32, net32 = compiled_step(config)
    finally:
        os.environ[precision._ENV] = prev
    cost32 = compiled32.cost_analysis()
    if isinstance(cost32, list):
        cost32 = cost32[0]
    cost32_b = float(cost32.get("bytes accessed", 0.0))
    _, total32, by_dtype32 = profile_hlo(compiled32.as_text())
    moments_io = 2 * sum(int(l.size) * l.dtype.itemsize
                         for l in jax.tree.leaves(net32.updater_state))
    master_io = 2 * 4 * sum(int(l.size)
                            for l in jax.tree.leaves(net32.params))
    est = chip_posture_estimate(total32, by_dtype32.get("f32", 0),
                                moments_io, master_io,
                                pol.master_weights)
    ratio = est / total32 if total32 else 1.0
    print(f"\n# precision comparison ({pol.name} vs fp32, CPU posture)")
    print(f"#   fp32 program:   cost model {cost32_b:,.0f} B/step; "
          f"parsed {total32/1e6:.0f} MB")
    print(f"#   {pol.name} program: cost model {cost_bytes_pol:,.0f} "
          f"B/step (CPU convert overhead included)")
    print(f"#   chip-posture estimate (f32 traffic at policy widths, "
          f"moments fp32, masters r/w): {est:,.0f} B "
          f"= x{ratio:.3f} vs fp32")
    print(f"#   projected xla_cost_bytes_accessed on chip: "
          f"{cost32_b * ratio:,.0f} B/step")
    if config == "lenet":
        print(f"#   projected BENCH_r05 LeNet row: "
              f"{BENCH_R05_LENET_BYTES:,} -> "
              f"{BENCH_R05_LENET_BYTES * ratio:,.0f} B/step")
    g = monitor.gauge("hbm_profile_policy_bytes",
                      "CPU-posture precision-policy bytes comparison "
                      "(parsed HLO traffic per train step)")
    g.set(float(total32), config=config, program="fp32")
    g.set(float(est), config=config, program="chip_estimate")


def register_monitor_gauges(config: str, by_class: dict,
                            total: int) -> None:
    """Publish the profile into the runtime telemetry registry so a
    /metrics scrape (ui server) or ``monitor.snapshot()`` carries the
    per-op-class HBM totals alongside the live training metrics."""
    from deeplearning4j_tpu import monitor
    for cls, b in by_class.items():
        monitor.gauge("hbm_profile_bytes",
                      "per-op-class HBM bytes per train step (parsed "
                      "from optimized HLO)").set(float(b), config=config,
                                                 op_class=cls)
    monitor.gauge("hbm_profile_total_bytes",
                  "total parsed HBM bytes per train step").set(
                      float(total), config=config)


def main() -> int:
    config = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 15
    # CPU-posture audit: compile the program the TPU default policy would
    # run unless the caller pinned a mode (DL4J_TPU_PRECISION=fp32 gives
    # the fp32 comparison row).
    os.environ.setdefault("DL4J_TPU_PRECISION", "mixed_bf16")
    from deeplearning4j_tpu.nn import precision
    pol = precision.named_policy(precision.env_mode())
    print(f"# precision policy: {pol.describe()}")
    compiled, _net = compiled_step(config)
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    hlo = compiled.as_text()
    all_rows, total, _by_dtype = profile_hlo(hlo)
    rows = all_rows[:top_n]
    print(f"# {config}: top {top_n} HBM-consuming ops "
          f"(parsed {total/1e6:.0f} MB/step; XLA cost model "
          f"{cost.get('bytes accessed', 0)/1e6:.0f} MB/step)")
    # compiler self-reported totals next to the parsed numbers — the
    # same xla_cost_* series the compile-watch publishes for every
    # executable (the AOT compile above already fed the gauges)
    try:
        mem = compiled.memory_analysis()
        peak = (mem.argument_size_in_bytes + mem.output_size_in_bytes
                + mem.temp_size_in_bytes - mem.alias_size_in_bytes)
        print(f"# cost model: {cost.get('flops', 0)/1e9:.2f} GFLOP/step; "
              f"memory_analysis peak HBM {peak/1e6:.0f} MB "
              f"(args {mem.argument_size_in_bytes/1e6:.0f} + outputs "
              f"{mem.output_size_in_bytes/1e6:.0f} + temps "
              f"{mem.temp_size_in_bytes/1e6:.0f} - aliased "
              f"{mem.alias_size_in_bytes/1e6:.0f})")
    except Exception:
        pass
    print(f"{'MB':>8}  {'%':>5}  {'class':<8} {'kind':<14} shape")
    by_class = defaultdict(int)
    for b, kind, name, shape in rows:
        cls = _classify(kind, name, shape)
        print(f"{b/1e6:8.1f}  {100*b/total:5.1f}  {cls:<8} {kind:<14} "
              f"{shape[:60]}  {name[:40]}")
    # class totals over ALL instructions, not just top-n
    for b, kind, name, shape in all_rows:
        by_class[_classify(kind, name, shape)] += b
    print("\n# traffic by op class (all instructions)")
    for cls, b in sorted(by_class.items(), key=lambda kv: -kv[1]):
        print(f"{b/1e6:8.1f} MB  {100*b/total:5.1f}%  {cls}")
    # the scatter-row audit line the embedding configs exist for: how
    # many distinct scatter-add sites the step issues (counted from HLO
    # metadata (op_name, source_line) — robust to CPU lowering scatters
    # into loop fusions), and whether the program carries the
    # sorted/unique promises that unlock the non-colliding path
    sites = set()
    for m in re.finditer(r"metadata=\{([^}]*)\}", hlo):
        md = m.group(1)
        op = re.search(r'op_name="([^" ]*)', md)
        if op and "scatter-add" in op.group(1):
            ln = re.search(r"source_line=(\d+)", md)
            sites.add((op.group(1), ln.group(1) if ln else "?"))
    if sites:
        print(f"\n# scatter audit: {len(sites)} scatter-add site(s) per "
              f"step; {hlo.count('unique_indices=true')} instruction(s) "
              f"marked unique_indices=true")
    register_monitor_gauges(config, by_class, total)
    if config in _NET_CONFIGS and pol.name != "fp32":
        _policy_comparison(config, pol,
                           float(cost.get("bytes accessed", 0.0)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
