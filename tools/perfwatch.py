"""Bench regression watch: ``python tools/perfwatch.py``.

The repo commits one ``BENCH_r<N>.json`` per growth round (throughput +
optional HBM bytes-per-step from the XLA cost analysis) and a static
``tools/perf_baseline.json``.  Nothing watched the trajectory: a 20%
throughput cliff only surfaced when a human diffed two bench logs.
This tool is the standing gate — stdlib-only so CI can run it before
any dependency install.

Checks (each failed check is one finding):

- **throughput drop** — the newest round's headline metric must not
  fall more than ``--tolerance`` (default 10%) below the *median of the
  trailing rounds* (default 4).  The trailing median, not the all-time
  high-water mark, is the reference: the committed history legitimately
  drifts as instrumentation grows, and a gate pinned to round 1 would
  be permanently red while a real cliff at head stayed invisible.
- **bytes-per-step growth** — ``hbm_bytes_per_step`` (when recorded)
  must not grow more than ``--bytes-tolerance`` (default 10%) over the
  smallest value in the history: memory per step creeping up is a
  regression even when throughput holds.
- **fleet throughput drop** — rounds whose parsed line carries
  ``fleet_requests_per_sec`` (the ``bench.py --fleet`` admitted
  open-loop rate, either as the headline metric or as an extra field)
  form their own series, gated the same way as the headline: newest
  vs the trailing median, ``--tolerance`` fractional drop.
- **decode throughput drop** — rounds carrying
  ``decode_tokens_per_sec`` (the ``bench.py --decode`` KV-ring
  one-dispatch-per-token rate, headline or extra field) form another
  sparse series with the same trailing-median gate.
- **traffic throughput drop** — rounds carrying
  ``traffic_admitted_rps`` (the ``bench.py --traffic`` open-loop
  fair-admission admitted rate under the seeded multi-tenant overload,
  headline or extra field) form a third sparse series with the same
  trailing-median gate.

Output: findings on stdout (``--json`` for machine-readable) and a
``PERF_REPORT.md`` snapshot of the trajectory + verdicts (suppress with
``--no-report``).

Exit status mirrors ``tools/analyze``'s contract so the same CI glue
works: **0** clean, **1** at least one finding, **2** the watcher
itself failed (unreadable history, internal crash) — "perf is dirty"
and "the gate did not run" must be distinguishable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
import traceback

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL_ERROR = 2

DEFAULT_TOLERANCE = 0.10
DEFAULT_BYTES_TOLERANCE = 0.10
DEFAULT_TRAILING = 4

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


class Finding:
    def __init__(self, check: str, message: str):
        self.check = check
        self.message = message

    def __str__(self) -> str:
        return f"[{self.check}] {self.message}"


def load_rounds(root: str) -> list:
    """The committed bench trajectory, oldest first: one record per
    ``BENCH_r*.json`` whose top-level ``parsed`` block carries a
    headline metric.  Unparseable files raise (internal error — the
    history itself is part of the contract)."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        with open(path) as fh:
            doc = json.load(fh)
        parsed = doc.get("parsed") or {}
        fleet_rps = parsed.get("fleet_requests_per_sec")
        if fleet_rps is None \
                and parsed.get("metric") == "fleet_requests_per_sec":
            fleet_rps = parsed.get("value")
        decode_tps = parsed.get("decode_tokens_per_sec")
        if decode_tps is None \
                and parsed.get("metric") == "decode_tokens_per_sec":
            decode_tps = parsed.get("value")
        traffic_rps = parsed.get("traffic_admitted_rps")
        if traffic_rps is None \
                and parsed.get("metric") == "traffic_admitted_rps":
            traffic_rps = parsed.get("value")
        rounds.append({
            "round": int(doc.get("n", m.group(1))),
            "file": os.path.basename(path),
            "rc": doc.get("rc"),
            "metric": parsed.get("metric"),
            "value": parsed.get("value"),
            "unit": parsed.get("unit"),
            "batch": parsed.get("batch"),
            "hbm_bytes_per_step": parsed.get("hbm_bytes_per_step"),
            "fleet_requests_per_sec": fleet_rps,
            "decode_tokens_per_sec": decode_tps,
            "traffic_admitted_rps": traffic_rps,
        })
    rounds.sort(key=lambda r: r["round"])
    return rounds


def load_baseline(root: str) -> dict:
    path = os.path.join(root, "tools", "perf_baseline.json")
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return json.load(fh)


def check_throughput(rounds: list, tolerance: float,
                     trailing: int) -> list:
    """Newest round vs the trailing median of its own metric."""
    usable = [r for r in rounds
              if r["value"] is not None and r["rc"] == 0]
    if len(usable) < 2:
        return []
    head = usable[-1]
    prior = [r["value"] for r in usable[:-1]
             if r["metric"] == head["metric"]][-trailing:]
    if not prior:
        return []
    base = statistics.median(prior)
    if base <= 0:
        return []
    drop = (base - head["value"]) / base
    head["throughput_drop_vs_trailing"] = round(drop, 4)
    if drop > tolerance:
        return [Finding(
            "throughput",
            f"{head['file']}: {head['metric']} = {head['value']:.1f} is "
            f"{drop * 100:.1f}% below the trailing median "
            f"{base:.1f} of the previous {len(prior)} round(s) "
            f"(tolerance {tolerance * 100:.0f}%)")]
    return []


def check_fleet_throughput(rounds: list, tolerance: float,
                           trailing: int) -> list:
    """Newest fleet admitted-throughput round vs its trailing median.

    The fleet series is sparser than the headline (only rounds where
    the driver ran ``bench.py --fleet`` carry it), so it gets its own
    check rather than riding the headline-metric match."""
    usable = [r for r in rounds
              if r["fleet_requests_per_sec"] is not None
              and r["rc"] == 0]
    if len(usable) < 2:
        return []
    head = usable[-1]
    prior = [r["fleet_requests_per_sec"] for r in usable[:-1]][-trailing:]
    base = statistics.median(prior)
    if base <= 0:
        return []
    drop = (base - head["fleet_requests_per_sec"]) / base
    head["fleet_drop_vs_trailing"] = round(drop, 4)
    if drop > tolerance:
        return [Finding(
            "fleet-throughput",
            f"{head['file']}: fleet_requests_per_sec = "
            f"{head['fleet_requests_per_sec']:.1f} is "
            f"{drop * 100:.1f}% below the trailing median {base:.1f} "
            f"of the previous {len(prior)} fleet round(s) "
            f"(tolerance {tolerance * 100:.0f}%)")]
    return []


def check_decode_throughput(rounds: list, tolerance: float,
                            trailing: int) -> list:
    """Newest decode tokens/sec round vs its trailing median.

    Like the fleet series, decode rounds are sparse (only rounds where
    the driver ran ``bench.py --decode`` carry the rate), so the
    KV-ring one-dispatch-per-token throughput gets its own
    trailing-median gate."""
    usable = [r for r in rounds
              if r["decode_tokens_per_sec"] is not None
              and r["rc"] == 0]
    if len(usable) < 2:
        return []
    head = usable[-1]
    prior = [r["decode_tokens_per_sec"] for r in usable[:-1]][-trailing:]
    base = statistics.median(prior)
    if base <= 0:
        return []
    drop = (base - head["decode_tokens_per_sec"]) / base
    head["decode_drop_vs_trailing"] = round(drop, 4)
    if drop > tolerance:
        return [Finding(
            "decode-throughput",
            f"{head['file']}: decode_tokens_per_sec = "
            f"{head['decode_tokens_per_sec']:.1f} is "
            f"{drop * 100:.1f}% below the trailing median {base:.1f} "
            f"of the previous {len(prior)} decode round(s) "
            f"(tolerance {tolerance * 100:.0f}%)")]
    return []


def check_traffic_throughput(rounds: list, tolerance: float,
                             trailing: int) -> list:
    """Newest open-loop fair-admission admitted rate vs its trailing
    median.

    Rounds carrying ``traffic_admitted_rps`` (the ``bench.py
    --traffic`` multi-tenant overload harness) are sparse like the
    fleet and decode series; the admitted rate under the seeded
    offender is the capacity the fair controller actually serves, so a
    drop here is an admission/batching regression even when the
    headline single-model rate holds."""
    usable = [r for r in rounds
              if r["traffic_admitted_rps"] is not None
              and r["rc"] == 0]
    if len(usable) < 2:
        return []
    head = usable[-1]
    prior = [r["traffic_admitted_rps"] for r in usable[:-1]][-trailing:]
    base = statistics.median(prior)
    if base <= 0:
        return []
    drop = (base - head["traffic_admitted_rps"]) / base
    head["traffic_drop_vs_trailing"] = round(drop, 4)
    if drop > tolerance:
        return [Finding(
            "traffic-throughput",
            f"{head['file']}: traffic_admitted_rps = "
            f"{head['traffic_admitted_rps']:.1f} is "
            f"{drop * 100:.1f}% below the trailing median {base:.1f} "
            f"of the previous {len(prior)} traffic round(s) "
            f"(tolerance {tolerance * 100:.0f}%)")]
    return []


def check_bytes(rounds: list, tolerance: float) -> list:
    """Newest recorded hbm_bytes_per_step vs the history minimum."""
    series = [(r["file"], r["hbm_bytes_per_step"]) for r in rounds
              if r["hbm_bytes_per_step"] is not None and r["rc"] == 0]
    if len(series) < 2:
        return []
    head_file, head = series[-1]
    best = min(v for _, v in series)
    if best <= 0:
        return []
    growth = (head - best) / best
    if growth > tolerance:
        return [Finding(
            "bytes-per-step",
            f"{head_file}: hbm_bytes_per_step = {head:.0f} grew "
            f"{growth * 100:.1f}% over the history minimum {best:.0f} "
            f"(tolerance {tolerance * 100:.0f}%)")]
    return []


def write_report(path: str, rounds: list, findings: list,
                 baseline: dict, args) -> None:
    lines = [
        "# Perf regression watch",
        "",
        "Generated by `python tools/perfwatch.py` over the committed",
        "`BENCH_r*.json` trajectory (see docs/OBSERVABILITY.md, \"Perf",
        "regression watch\").",
        "",
        f"- throughput tolerance: {args.tolerance * 100:.0f}% below the "
        f"trailing-{args.trailing} median",
        f"- bytes-per-step tolerance: "
        f"{args.bytes_tolerance * 100:.0f}% above the history minimum",
        "",
        "## Trajectory",
        "",
        "| round | metric | value | batch | hbm bytes/step "
        "| fleet req/s | decode tok/s | traffic req/s | rc |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rounds:
        value = "-" if r["value"] is None else f"{r['value']:.1f}"
        hbm = ("-" if r["hbm_bytes_per_step"] is None
               else f"{r['hbm_bytes_per_step']:.0f}")
        fleet = ("-" if r.get("fleet_requests_per_sec") is None
                 else f"{r['fleet_requests_per_sec']:.1f}")
        decode = ("-" if r.get("decode_tokens_per_sec") is None
                  else f"{r['decode_tokens_per_sec']:.1f}")
        traffic = ("-" if r.get("traffic_admitted_rps") is None
                   else f"{r['traffic_admitted_rps']:.1f}")
        lines.append(
            f"| r{r['round']:02d} | {r['metric'] or '-'} | {value} "
            f"| {r['batch'] or '-'} | {hbm} | {fleet} | {decode} "
            f"| {traffic} | {r['rc']} |")
    lines += ["", "## Verdict", ""]
    if findings:
        lines += [f"- **FAIL** {f}" for f in findings]
    else:
        lines.append("- **PASS** — no regression beyond tolerance")
    if baseline:
        lines += ["", "## Static baseline (tools/perf_baseline.json)",
                  ""]
        for name, vals in sorted(baseline.items()):
            if isinstance(vals, dict):
                detail = ", ".join(f"{k}={v:g}" if isinstance(v, float)
                                   else f"{k}={v}"
                                   for k, v in sorted(vals.items()))
            else:
                detail = str(vals)
            lines.append(f"- `{name}`: {detail}")
    lines.append("")
    with open(path, "w") as fh:
        fh.write("\n".join(lines))


def run(root: str, args) -> list:
    rounds = load_rounds(root)
    findings = []
    findings += check_throughput(rounds, args.tolerance, args.trailing)
    findings += check_fleet_throughput(rounds, args.tolerance,
                                       args.trailing)
    findings += check_decode_throughput(rounds, args.tolerance,
                                        args.trailing)
    findings += check_traffic_throughput(rounds, args.tolerance,
                                         args.trailing)
    findings += check_bytes(rounds, args.bytes_tolerance)
    if not args.no_report:
        write_report(args.report or os.path.join(root, "PERF_REPORT.md"),
                     rounds, findings, load_baseline(root), args)
    return findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/perfwatch.py",
        description="bench-trajectory regression watch "
                    "(BENCH_r*.json + tools/perf_baseline.json)")
    parser.add_argument("--root", default=None,
                        help="repo root holding BENCH_r*.json "
                             "(default: this file's repo)")
    parser.add_argument("--tolerance", type=float,
                        default=DEFAULT_TOLERANCE,
                        help="max fractional throughput drop vs the "
                             "trailing median (default 0.10)")
    parser.add_argument("--bytes-tolerance", type=float,
                        default=DEFAULT_BYTES_TOLERANCE,
                        help="max fractional hbm_bytes_per_step growth "
                             "vs the history minimum (default 0.10)")
    parser.add_argument("--trailing", type=int, default=DEFAULT_TRAILING,
                        help="rounds in the trailing median (default 4)")
    parser.add_argument("--report", default=None,
                        help="report path (default <root>/PERF_REPORT.md)")
    parser.add_argument("--no-report", action="store_true",
                        help="skip writing the markdown report")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    try:
        if not os.path.isdir(root):
            raise OSError(f"--root {root!r} is not a directory")
        findings = run(root, args)
    except Exception:
        print("perfwatch internal error:", file=sys.stderr)
        traceback.print_exc()
        return EXIT_INTERNAL_ERROR

    if args.json:
        print(json.dumps([{"check": f.check, "message": f.message}
                          for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print(f"{len(findings)} finding(s)")
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
