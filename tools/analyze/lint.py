"""Project-native invariant linter: AST rules for the correctness
conventions twelve PRs of runtime growth rely on.

Rules (see ``docs/ANALYSIS.md`` for rationale and before/after
examples from the fixes this tool forced):

- **R1 traced purity** — no host-clock/RNG calls (``time.*``,
  ``random.*``, ``np.random.*``, ``datetime.now``) and no host syncs
  (``.item()``, ``.block_until_ready()``, ``jax.device_get``,
  ``float(arg)``/``int(arg)`` on a traced parameter) inside functions
  reachable from a ``jit`` / ``watched_jit`` / ``lax.scan`` root.  A
  host call in traced code either freezes a trace-time value into the
  compiled program or forces a device sync in the middle of a fused
  dispatch.
- **R2 atomic writes** — in crash-safety-scoped paths (``resilience/``,
  ``deploy/``, ``earlystopping/``, the serializer, the flight recorder,
  checkpoint listeners, broker persistence), no bare
  ``open(path, "w"/"wb")`` or ``zipfile.ZipFile(path, "w")`` on a
  filesystem path: final files must go through
  ``deeplearning4j_tpu.utils.fileio.atomic_write`` so SIGKILL never
  leaves a torn file where a valid one lived.
- **R3 blocking under lock** — no socket/queue/subprocess/sleep/
  device-sync call lexically inside a ``with <lock>:`` body, including
  through local helper functions (an intra-module fixpoint marks
  helpers that transitively block).  Locks must cover shared-state
  mutation only.
- **R4 registry drift** — the ``DL4J_TPU_*`` env-var set and the metric
  name set registered in code must exactly match the generated
  inventory block in ``docs/OBSERVABILITY.md`` (and every env var named
  in any doc must exist in code).  ``--write-registry`` regenerates the
  block; the check replaces hand-maintained lists.
- **R5 donation safety** — a value passed in a donated position
  (``donate_argnums``) of a jitted dispatch must not be read after the
  call: donation invalidates the buffer, and XLA is free to overwrite
  it in place.
- **R6 retrace risk** — the statically-visible jit cache busters
  ``watched_jit`` can only report after the fact: a jit constructed and
  invoked in one expression (fresh empty cache per call), a jit factory
  called inside a loop body, a non-hashable literal passed in a
  ``static_argnums`` position (``TypeError`` at dispatch), a static
  argument fed from the enclosing loop variable (one compile per
  iteration), and a traced function closing over module-level mutable
  state that is mutated elsewhere (the trace freezes a stale value).
- **R7 hidden host<->device transfers** — ``float()``/``int()``/
  ``bool()``/``np.asarray()``/``np.array()`` applied to a value that
  data-flows from a jitted dispatch or a ``jnp.*`` computation, in
  host code outside the audited sink scope (eval fast path, metrics
  decode, checkpoint host-snapshot): each such cast is a blocking
  device->host round trip hiding in a hot path.
- **R8 lockset guarded-field drift** — within one class, a ``self._x``
  attribute written both inside a ``with <lock>:`` region and bare (in
  any method other than ``__init__``), or guarded by two *disjoint*
  locks: the unguarded (or differently-guarded) write races every
  reader that trusts the lock.  Methods named ``*_locked`` are
  guarded-by-convention (the caller holds the lock).

R1 reachability and R3's blocking fixpoint are **whole-program**: the
cross-module call graph (``tools.analyze.callgraph``) resolves the
repo's own imports, so a traced helper or blocking primitive defined a
module away is still caught (``run``/``lint_file`` thread the global
seeds through; ``lint_source`` on one blob stays intra-module).

Suppressions: ``# dl4j-lint: disable=R3 <reason>`` on the finding's
line or the line above.  The reason is mandatory and audited — a
reasonless or unused suppression is itself a finding, so the invariant
set can only grow.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

ALL_RULES = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8")

#: paths (relative, slash-normalized prefixes or exact files) under the
#: atomic-write contract (R2)
R2_SCOPE = (
    "deeplearning4j_tpu/resilience/",
    "deeplearning4j_tpu/deploy/",
    "deeplearning4j_tpu/earlystopping/",
    "deeplearning4j_tpu/utils/model_serializer.py",
    "deeplearning4j_tpu/monitor/flight_recorder.py",
    "deeplearning4j_tpu/optimize/listeners/listeners.py",
    "deeplearning4j_tpu/streaming/broker.py",
)

#: the one blessed implementation R2 routes everything through
R2_EXEMPT = ("deeplearning4j_tpu/utils/fileio.py",)

#: audited host-decode sink sites where R7 casts are the POINT — the
#: eval fast path decodes argmax indices, health/metrics decode the
#: packed stats vector, checkpoint/serializer snapshot params to host,
#: and the serving layer returns host arrays at the request boundary
R7_SINK_SCOPE = (
    "deeplearning4j_tpu/eval/",
    "deeplearning4j_tpu/monitor/health.py",
    "deeplearning4j_tpu/resilience/checkpoint.py",
    "deeplearning4j_tpu/utils/model_serializer.py",
    "deeplearning4j_tpu/deploy/store.py",
)

_SUPPRESS_RE = re.compile(
    r"#\s*dl4j-lint:\s*disable=([A-Za-z0-9,]+)\s*(.*?)\s*$")

_WRITE_MODES = {"w", "wb", "w+", "wb+", "wt"}

#: receiver names that mean "already a file object" for ZipFile(...)
_STREAM_HINTS = {"fh", "f", "fp", "buf", "buffer", "fileobj", "bio",
                 "stream", "out"}

#: dotted host calls banned in traced code (R1); prefixes match children
_R1_BANNED_PREFIXES = ("np.random.", "numpy.random.", "random.",
                      "jrandom.host_")
_R1_BANNED_EXACT = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep", "time.time_ns",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "os.urandom", "jax.device_get", "device_get", "uuid.uuid4",
}
_R1_BANNED_METHODS = {"item", "block_until_ready"}

#: attribute calls that block (R3); ``get``/``put`` count only on
#: queue-hinted receivers, ``join`` only on thread-hinted receivers
_R3_BLOCK_ATTRS = {"recv", "recv_into", "recvfrom", "accept", "sendall",
                   "connect", "block_until_ready", "select"}
_R3_BLOCK_DOTTED = {"time.sleep", "socket.create_connection",
                    "subprocess.run", "subprocess.call",
                    "subprocess.check_call", "subprocess.check_output",
                    "subprocess.Popen", "select.select"}
_R3_QUEUE_HINTS = ("queue", "_q", "jobs", "inbox")

#: jit-root factories (R1/R5)
_JIT_FACTORIES = {"jit", "watched_jit"}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Suppression:
    line: int           # line the directive covers (directive or next)
    rules: Tuple[str, ...]
    reason: str
    directive_line: int
    used: bool = False


# --------------------------------------------------------------- helpers

def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    return _dotted(call.func)


def _last_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def parse_suppressions(source: str) -> List[Suppression]:
    # tokenize so directives in docstrings/string literals (e.g. the
    # examples in this module's own docs) are not treated as live
    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenizeError, SyntaxError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        rules = tuple(r.strip().upper() for r in m.group(1).split(",")
                      if r.strip())
        reason = m.group(2).strip()
        own_line = tok.line[:tok.start[1]].strip() == ""
        covered = i + 1 if own_line else i  # own-line: covers next
        out.append(Suppression(line=covered, rules=rules, reason=reason,
                               directive_line=i))
    return out


# ------------------------------------------------------------ module IR

class _FunctionInfo:
    def __init__(self, node: ast.FunctionDef, cls: Optional[str]):
        self.node = node
        self.cls = cls
        self.name = node.name
        self.qname = f"{cls}.{node.name}" if cls else node.name
        self.calls: Set[str] = set()       # resolved callee qnames
        self.blocking_sites: List[Tuple[int, str]] = []


class _ModuleIndex:
    """Per-module tables: functions (keyed by CLASS-QUALIFIED name, so
    two classes' same-named methods never conflate), intra-module call
    edges, jit roots, and donated-jit bindings."""

    def __init__(self, tree: ast.Module):
        #: qualified name ("Cls.meth" or bare for module-level/nested)
        #: -> info; bare-name view in :attr:`by_bare`
        self.functions: Dict[str, _FunctionInfo] = {}
        self.by_bare: Dict[str, List[str]] = {}
        self.jit_roots: Set[str] = set()
        # binding name -> donate arg positions
        self.donated: Dict[str, Tuple[int, ...]] = {}
        # binding name -> static arg positions (R6)
        self.static_bindings: Dict[str, Tuple[int, ...]] = {}
        # every name bound to a jit/watched_jit factory result (R7)
        self.jit_bindings: Set[str] = set()
        self._collect(tree)

    # -- collection -----------------------------------------------------
    def _collect(self, tree: ast.Module) -> None:
        cls_stack: List[Optional[str]] = [None]

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.ClassDef):
                cls_stack.append(node.name)
                for child in node.body:
                    visit(child)
                cls_stack.pop()
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FunctionInfo(node, cls_stack[-1])
                self.functions[info.qname] = info
                self.by_bare.setdefault(node.name, []).append(info.qname)
                self._scan_decorators(node, info.qname)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            if isinstance(node, ast.Call):
                self._scan_call(node)
            if isinstance(node, ast.Assign):
                self._scan_assign(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(tree)
        # call edges, resolved class-aware once functions are known
        for info in self.functions.values():
            for sub in ast.walk(info.node):
                if isinstance(sub, ast.Call):
                    q = self.resolve_callee(info.cls, sub)
                    if q is not None:
                        info.calls.add(q)

    def resolve_callee(self, cls: Optional[str],
                       call: ast.Call) -> Optional[str]:
        """Qualified name of the local function a call hits, preferring
        the caller's own class for ``self.x(...)`` and module level for
        bare names; an ambiguous bare name resolves only when unique
        (conservative under-approximation)."""
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in self.functions:       # module-level / nested
                return func.id
            cands = self.by_bare.get(func.id, [])
            return cands[0] if len(cands) == 1 else None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id in ("self", "cls"):
            if cls is not None:
                q = f"{cls}.{func.attr}"
                if q in self.functions:
                    return q
            if func.attr in self.functions:
                return func.attr
            cands = self.by_bare.get(func.attr, [])
            return cands[0] if len(cands) == 1 else None
        return None

    def expand(self, names: Iterable[str]) -> Tuple[Set[str], Set[str]]:
        """Split seed names into (local qualified names, foreign bare
        names): a qname or bare name matching local functions expands to
        the matching qnames; anything else (imported helpers the global
        graph proved) stays bare for call-site matching."""
        local: Set[str] = set()
        foreign: Set[str] = set()
        for n in names:
            if n in self.functions:
                local.add(n)
            elif n in self.by_bare:
                local.update(self.by_bare[n])
            else:
                foreign.add(n)
        return local, foreign

    def _scan_decorators(self, node: ast.FunctionDef,
                         qname: str) -> None:
        for dec in node.decorator_list:
            name = _dotted(dec if not isinstance(dec, ast.Call)
                           else dec.func)
            if name and name.split(".")[-1] in _JIT_FACTORIES:
                self.jit_roots.add(qname)

    def _root_arg(self, call: ast.Call) -> Optional[str]:
        if call.args:
            return _last_attr(call.args[0])
        return None

    def _scan_call(self, call: ast.Call) -> None:
        name = _call_name(call)
        if name is None:
            return
        tail = name.split(".")[-1]
        if tail in _JIT_FACTORIES:
            root = self._root_arg(call)
            if root:
                self.jit_roots.add(root)
        elif tail == "scan" and name.split(".")[-2:-1] == ["lax"]:
            root = self._root_arg(call)
            if root:
                self.jit_roots.add(root)

    def _kw_positions(self, call: ast.Call,
                      kwarg: str) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg == kwarg:
                return self._int_positions(kw.value)
        return None

    @staticmethod
    def _int_positions(node: ast.AST) -> Optional[Tuple[int, ...]]:
        """Evaluate a donate_argnums expression: an int, a literal
        tuple/list of ints, or ``[tuple(]range(...)[)]``."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            vals = tuple(e.value for e in node.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int))
            return vals or None
        if isinstance(node, ast.Call):
            name = _call_name(node) or ""
            if name in ("tuple", "list") and len(node.args) == 1:
                return _ModuleIndex._int_positions(node.args[0])
            if name == "range" and node.args and all(
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, int) for a in node.args):
                return tuple(range(*(a.value for a in node.args))) or None
        return None

    def _scan_assign(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        name = _call_name(node.value)
        if name is None or name.split(".")[-1] not in _JIT_FACTORIES:
            return
        donate = self._kw_positions(node.value, "donate_argnums")
        static = self._kw_positions(node.value, "static_argnums")
        for tgt in node.targets:
            bound = _last_attr(tgt)
            if bound:
                self.jit_bindings.add(bound)
                if donate:
                    self.donated[bound] = donate
                if static:
                    self.static_bindings[bound] = static

    # -- reachability ---------------------------------------------------
    def traced_functions(
            self, extra: Iterable[str] = ()) -> Dict[str, _FunctionInfo]:
        seen: Set[str] = set()
        roots, _ = self.expand(set(self.jit_roots) | set(extra))
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            frontier.extend(c for c in self.functions[cur].calls
                            if c not in seen)
        return {n: self.functions[n] for n in seen}


# ------------------------------------------------------------------ R1

def _walk_skipping_nested(fn: ast.FunctionDef) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function
    definitions (those are separately reachable if traced)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _check_r1(index: _ModuleIndex, path: str,
              extra_traced: Iterable[str] = ()) -> List[Finding]:
    out: List[Finding] = []
    for fname, info in index.traced_functions(extra_traced).items():
        params = {a.arg for a in info.node.args.args
                  + info.node.args.kwonlyargs
                  + info.node.args.posonlyargs}
        for node in _walk_skipping_nested(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _call_name(node)
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            msg = None
            if dotted in _R1_BANNED_EXACT or (
                    dotted and dotted.startswith(_R1_BANNED_PREFIXES)):
                msg = f"host call `{dotted}(...)`"
            elif attr in _R1_BANNED_METHODS and not node.args:
                msg = f"host-sync `.{attr}()`"
            elif (dotted in ("float", "int") and len(node.args) == 1
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id in params):
                msg = (f"`{dotted}({node.args[0].id})` host-syncs a "
                       "traced argument")
            if msg:
                out.append(Finding(
                    "R1", path, node.lineno,
                    f"traced purity: {msg} inside `{fname}`, which is "
                    "reachable from a jit/watched_jit/lax.scan root — "
                    "host calls freeze trace-time values or force a "
                    "device sync mid-dispatch"))
    return out


# ------------------------------------------------------------------ R2

def _write_mode_of(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _check_r2(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        mode = _write_mode_of(node)
        if mode is None or mode not in _WRITE_MODES:
            continue
        if name == "open":
            out.append(Finding(
                "R2", path, node.lineno,
                f"atomic writes: bare `open(..., {mode!r})` in a "
                "crash-safety-scoped path — route through "
                "utils.fileio.atomic_write (temp+fsync+rename) so a "
                "SIGKILL mid-write never leaves a torn file"))
        elif name and name.split(".")[-1] == "ZipFile":
            target = node.args[0] if node.args else None
            hint = _last_attr(target) if target is not None else None
            if hint is not None and hint.lower() in _STREAM_HINTS:
                continue     # already writing into a file object
            if isinstance(target, ast.Call):
                hint = _call_name(target) or ""
                if hint.split(".")[-1] in ("BytesIO", "StringIO"):
                    continue
            out.append(Finding(
                "R2", path, node.lineno,
                "atomic writes: `zipfile.ZipFile(path, 'w')` writes the "
                "final file in place — wrap utils.fileio.atomic_write "
                "and hand ZipFile the file object"))
    return out


# ------------------------------------------------------------------ R3

def _is_blocking_call(node: ast.Call, blocking_fns: Set[str],
                      xmod_fns: Set[str] = frozenset(),
                      cls: Optional[str] = None,
                      index: Optional[_ModuleIndex] = None
                      ) -> Optional[str]:
    """The blocking thing this call performs, or ``None``: a blocking
    primitive, a local function the fixpoint proved blocking (resolved
    class-aware through ``index`` — two classes' same-named methods
    never conflate), or an imported helper the whole-program graph
    proved blocking (``xmod_fns``, matched at module-alias call
    sites)."""
    dotted = _call_name(node)
    if dotted in _R3_BLOCK_DOTTED:
        return dotted
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        recv = _dotted(node.func.value) or ""
        if attr in _R3_BLOCK_ATTRS:
            return f"{recv}.{attr}" if recv else attr
        if attr in ("get", "put") and any(
                h in recv.lower() for h in _R3_QUEUE_HINTS):
            return f"{recv}.{attr}"
        if recv in ("self", "cls"):
            if index is not None:
                q = index.resolve_callee(cls, node)
                if q is not None and q in blocking_fns:
                    return f"self.{attr}"
            elif attr in blocking_fns:       # no index: bare matching
                return f"self.{attr}"
            return None      # self-calls never match imported names
        # imported blocking helper called through a module alias
        # (``wire._recv_exact(...)``) — names come from the global graph
        if attr in xmod_fns:
            return f"{recv}.{attr}" if recv else attr
    if isinstance(node.func, ast.Name):
        if index is not None:
            q = index.resolve_callee(cls, node)
            if q is not None and q in blocking_fns:
                return node.func.id
        elif node.func.id in blocking_fns:
            return node.func.id
        if node.func.id in xmod_fns:
            return node.func.id
    return None


def _blocking_fixpoint(index: _ModuleIndex,
                       extra: Iterable[str] = ()) -> Tuple[Set[str],
                                                           Set[str]]:
    """(qualified names of module functions that transitively perform a
    blocking call, foreign bare names) — so R3 sees through local
    helpers like ``_recv_exact``.  ``extra`` seeds names the
    WHOLE-PROGRAM graph already proved blocking: local qnames from the
    cross-module fixpoint plus bare names of imported wire helpers."""
    blocking, xmod = index.expand(extra)
    changed = True
    while changed:
        changed = False
        for qname, info in index.functions.items():
            if qname in blocking:
                continue
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call) and \
                        _is_blocking_call(node, blocking, xmod,
                                          cls=info.cls, index=index):
                    blocking.add(qname)
                    changed = True
                    break
    return blocking, xmod


def _lockish(expr: ast.AST) -> Optional[str]:
    name = _dotted(expr)
    if name is None:
        return None
    tail = name.split(".")[-1].lower()
    return name if "lock" in tail or tail in ("_mu", "_meta") else None


def _check_r3(tree: ast.Module, index: _ModuleIndex, path: str,
              extra_blocking: Iterable[str] = ()) -> List[Finding]:
    out: List[Finding] = []
    blocking_fns, xmod = _blocking_fixpoint(index, extra_blocking)
    # With sites paired with their enclosing class so self-calls
    # resolve against the right class's methods
    sites: List[Tuple[ast.With, Optional[str]]] = []
    seen_withs: Set[int] = set()
    for info in index.functions.values():
        for node in _walk_skipping_nested(info.node):
            if isinstance(node, ast.With):
                sites.append((node, info.cls))
                seen_withs.add(id(node))
    for node in ast.walk(tree):      # module/class-level With blocks
        if isinstance(node, ast.With) and id(node) not in seen_withs:
            sites.append((node, None))
    for node, cls in sites:
        lock_names = [n for n in
                      (_lockish(item.context_expr) for item in node.items)
                      if n]
        if not lock_names:
            continue
        for sub in node.body:
            for inner in ast.walk(sub):
                if isinstance(inner,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(inner, ast.Call):
                    what = _is_blocking_call(inner, blocking_fns, xmod,
                                             cls=cls, index=index)
                    if what:
                        out.append(Finding(
                            "R3", path, inner.lineno,
                            f"blocking under lock: `{what}(...)` runs "
                            f"while `{lock_names[0]}` is held — narrow "
                            "the lock to shared-state mutation; a "
                            "blocked holder stalls every other thread "
                            "on this lock"))
    return out


# ------------------------------------------------------------------ R5

def _check_r5(index: _ModuleIndex, tree: ast.Module,
              path: str) -> List[Finding]:
    out: List[Finding] = []
    if not index.donated:
        return out
    for info in index.functions.values():
        fn = info.node
        body_nodes = list(_walk_skipping_nested(fn))
        calls = []
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            callee = _last_attr(node.func)
            if callee in index.donated:
                calls.append((node, index.donated[callee], callee))
        for call, positions, callee in calls:
            rebound = _rebound_names(fn, call)
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound:
                    continue
                for node in body_nodes:
                    if isinstance(node, ast.Name) and \
                            isinstance(node.ctx, ast.Load) and \
                            node.id == arg.id and \
                            node.lineno > call.lineno:
                        out.append(Finding(
                            "R5", path, node.lineno,
                            f"donation safety: `{arg.id}` was donated "
                            f"to `{callee}` (donate_argnums position "
                            f"{pos}, line {call.lineno}) and is read "
                            "afterwards — the donated buffer may "
                            "already be overwritten in place"))
                        break
    return out


def _rebound_names(fn: ast.FunctionDef, call: ast.Call) -> Set[str]:
    """Names assigned from the donated call's result (``a, b = f(a, b)``
    rebinds a and b — reads after that are the NEW buffers)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node.value is call:
            names: Set[str] = set()
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            return names
    return set()


# ------------------------------------------------------------------ R6

#: mutating methods on module-level containers (R6 closure shape)
_R6_MUT_METHODS = {"append", "extend", "update", "setdefault", "pop",
                   "insert", "clear", "remove", "add", "popitem",
                   "discard", "appendleft"}

_R6_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                  ast.DictComp)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _static_calls(node: ast.AST,
                  index: _ModuleIndex) -> Iterable[
                      Tuple[ast.Call, str, Tuple[int, ...]]]:
    """Calls (anywhere under ``node``) whose callee is a known
    ``static_argnums`` jit binding, with the static positions."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        callee = _last_attr(sub.func)
        if callee in index.static_bindings:
            yield sub, callee, index.static_bindings[callee]


def _check_r6(index: _ModuleIndex, tree: ast.Module, path: str,
              extra_traced: Iterable[str] = ()) -> List[Finding]:
    out: List[Finding] = []
    flagged_factory_lines: Set[int] = set()

    # -- R6 shape 1: a jit constructed and invoked in one expression —
    # the returned callable (and its compile cache) dies with the
    # statement, so every execution recompiles from scratch
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Call):
            continue
        inner = _call_name(node.func)
        if inner and inner.split(".")[-1] in _JIT_FACTORIES:
            flagged_factory_lines.add(node.func.lineno)
            out.append(Finding(
                "R6", path, node.lineno,
                f"retrace risk: `{inner}(...)(...)` constructs and "
                "invokes a jit in one expression — the compile cache "
                "is thrown away with the callable, so this recompiles "
                "on every execution; bind the jitted function once and "
                "reuse it"))

    # -- R6 shape 2: a jit factory called inside a loop body — one
    # fresh cache (and compile) per iteration
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for body_stmt in loop.body + loop.orelse:
            for sub in ast.walk(body_stmt):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    continue
                if not isinstance(sub, ast.Call):
                    continue
                name = _call_name(sub)
                if name and name.split(".")[-1] in _JIT_FACTORIES and \
                        sub.lineno not in flagged_factory_lines:
                    flagged_factory_lines.add(sub.lineno)
                    out.append(Finding(
                        "R6", path, sub.lineno,
                        f"retrace risk: `{name}(...)` is called inside "
                        "a loop body — each iteration builds a fresh "
                        "jit with an empty cache; hoist the factory "
                        "out of the loop"))

    # -- R6 shape 3: non-hashable literal in a static_argnums position
    # (TypeError at dispatch: static args are cache keys)
    for call, callee, positions in _static_calls(tree, index):
        for pos in positions:
            if pos < len(call.args) and \
                    isinstance(call.args[pos], _R6_UNHASHABLE):
                out.append(Finding(
                    "R6", path, call.lineno,
                    f"retrace risk: call to `{callee}` passes a "
                    f"non-hashable literal at static_argnums position "
                    f"{pos} — static args are hashed as cache keys, "
                    "this raises TypeError at dispatch; pass a tuple "
                    "or hoist to a hashable constant"))

    # -- R6 shape 4: a static argument fed from the enclosing loop
    # variable — every iteration is a new cache key, so the loop
    # compiles once per step
    for loop in ast.walk(tree):
        if not isinstance(loop, ast.For):
            continue
        loop_vars = _names_in(loop.target)
        for call, callee, positions in _static_calls(loop, index):
            for pos in positions:
                if pos < len(call.args) and \
                        _names_in(call.args[pos]) & loop_vars:
                    out.append(Finding(
                        "R6", path, call.lineno,
                        f"retrace risk: call to `{callee}` passes loop "
                        f"variable(s) "
                        f"{sorted(_names_in(call.args[pos]) & loop_vars)}"
                        f" at static_argnums position {pos} — every "
                        "iteration is a new cache key, compiling once "
                        "per step; make the argument traced or hoist "
                        "it out of the loop"))

    # -- R6 shape 5: traced function closing over module-level mutable
    # state that is mutated elsewhere — the trace freezes the value it
    # saw at compile time, silently ignoring later mutation
    mutables: Dict[str, int] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and (
                isinstance(stmt.value, (ast.List, ast.Dict, ast.Set))
                or (isinstance(stmt.value, ast.Call)
                    and _call_name(stmt.value) in ("list", "dict",
                                                   "set"))):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    mutables[tgt.id] = stmt.lineno
    mutated: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _R6_MUT_METHODS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in mutables:
            mutated.add(node.func.value.id)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in tgts:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in mutables:
                    mutated.add(tgt.value.id)
    if mutated:
        for fname, info in index.traced_functions(extra_traced).items():
            shadowed = {a.arg for a in info.node.args.args
                        + info.node.args.kwonlyargs
                        + info.node.args.posonlyargs}
            for node in _walk_skipping_nested(info.node):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Store):
                    shadowed.add(node.id)
            seen_here: Set[str] = set()
            for node in _walk_skipping_nested(info.node):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in mutated and \
                        node.id not in shadowed and \
                        node.id not in seen_here:
                    seen_here.add(node.id)
                    out.append(Finding(
                        "R6", path, node.lineno,
                        f"retrace risk: traced `{fname}` reads "
                        f"module-level mutable `{node.id}` (defined "
                        f"line {mutables[node.id]}) which is mutated "
                        "elsewhere in this module — the trace freezes "
                        "the value seen at compile time and ignores "
                        "the mutation; pass it as an argument"))
    return out


# ------------------------------------------------------------------ R7

_R7_CASTS = {"float", "int", "bool"}
_R7_NP_MODULES = {"np", "numpy", "onp"}
_R7_NP_FUNCS = {"asarray", "array"}


def _r7_device_source(call: ast.Call, index: _ModuleIndex) -> bool:
    """Does this call produce a device value: a ``jnp.*``/``jax.numpy.*``
    computation or an invocation of a jit/watched_jit binding (including
    ``self._step(...)``)?"""
    name = _call_name(call)
    if name is None:
        return False
    if name.startswith(("jnp.", "jax.numpy.")):
        return True
    return name.split(".")[-1] in index.jit_bindings


#: attribute accesses that read array METADATA, not array data — no
#: transfer happens (``int(x.shape[0])`` is host-side bookkeeping)
_R7_META_ATTRS = {"shape", "ndim", "size", "dtype", "sharding",
                  "itemsize", "nbytes"}


def _r7_base_name(node: ast.AST) -> Optional[ast.AST]:
    """Strip subscripts/attributes: ``out[0].loss`` -> ``out``; a chain
    through a metadata attribute (``x.shape[0]``) carries no device
    data, so it strips to nothing."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) and \
                node.attr in _R7_META_ATTRS:
            return None
        node = node.value
    return node


def _check_r7(index: _ModuleIndex, tree: ast.Module, path: str,
              extra_traced: Iterable[str] = ()) -> List[Finding]:
    out: List[Finding] = []
    traced = set(index.traced_functions(extra_traced))
    for fname, info in index.functions.items():
        if fname in traced:
            continue          # host syncs in traced code are R1's domain
        fn = info.node
        tainted: Set[str] = set()
        for node in _walk_skipping_nested(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _r7_device_source(node.value, index):
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for elt in elts:
                        base = _r7_base_name(elt)
                        if isinstance(base, ast.Name):
                            tainted.add(base.id)
        for node in _walk_skipping_nested(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in _R7_CASTS and len(node.args) == 1 \
                    and not node.keywords:
                arg = node.args[0]
                what = f"{name}(...)"
            elif name and "." in name and \
                    name.split(".")[0] in _R7_NP_MODULES and \
                    name.split(".")[-1] in _R7_NP_FUNCS and node.args:
                arg = node.args[0]
                what = f"{name}(...)"
            else:
                continue
            base = _r7_base_name(arg)
            hit = None
            if isinstance(base, ast.Name) and base.id in tainted:
                hit = base.id
            elif isinstance(base, ast.Call) and \
                    _r7_device_source(base, index):
                hit = _call_name(base)
            if hit:
                out.append(Finding(
                    "R7", path, node.lineno,
                    f"hidden transfer: `{what}` on `{hit}`, which "
                    f"data-flows from a jitted dispatch/device "
                    f"computation in `{fname}` — each cast is a "
                    "blocking device->host round trip; batch the "
                    "decode into an audited sink (eval fast path, "
                    "metrics decode) or keep the value on device"))
    return out


# ------------------------------------------------------------------ R8

_R8_SENTINEL = "<locked-method>"


def _check_r8(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out += _check_r8_class(node, path)
    return out


def _check_r8_class(cls: ast.ClassDef, path: str) -> List[Finding]:
    # (lineno, lockset, method, attr) for every ``self.<attr> = ...``
    writes: List[Tuple[int, frozenset, str, str]] = []

    def visit(node: ast.AST, locks: frozenset, method: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return            # nested defs: their own scope
        if isinstance(node, ast.With):
            held = [n for n in (_lockish(i.context_expr)
                                for i in node.items) if n]
            inner = locks | frozenset(held) if held else locks
            for item in node.items:
                if item.optional_vars is not None:
                    visit(item.optional_vars, locks, method)
            for child in node.body:
                visit(child, inner, method)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in tgts:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and \
                        _lockish(tgt) is None:
                    writes.append((node.lineno, locks, method, tgt.attr))
        for child in ast.iter_child_nodes(node):
            visit(child, locks, method)

    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__":
            continue          # construction races nothing
        base = frozenset((_R8_SENTINEL,)) \
            if item.name.endswith("_locked") else frozenset()
        for child in item.body:
            visit(child, base, item.name)

    by_attr: Dict[str, List[Tuple[int, frozenset, str]]] = {}
    for lineno, locks, method, attr in writes:
        by_attr.setdefault(attr, []).append((lineno, locks, method))

    out: List[Finding] = []
    for attr, sites in sorted(by_attr.items()):
        guarded = [s for s in sites if s[1]]
        bare = [s for s in sites if not s[1]]
        if guarded and bare:
            g_line, g_locks, g_method = guarded[0]
            lock_name = next((n for n in sorted(g_locks)
                              if n != _R8_SENTINEL), _R8_SENTINEL)
            for lineno, _, method in bare:
                out.append(Finding(
                    "R8", path, lineno,
                    f"lockset drift: `self.{attr}` is written bare in "
                    f"`{cls.name}.{method}` but under `{lock_name}` in "
                    f"`{g_method}` (line {g_line}) — the unguarded "
                    "write races every reader that trusts the lock; "
                    "guard it or rename the method `*_locked`"))
        # disjoint real locksets: two writers each think they hold THE
        # lock, but they hold different ones
        real = [s for s in guarded if _R8_SENTINEL not in s[1]]
        for i in range(1, len(real)):
            if not (real[i][1] & real[0][1]):
                out.append(Finding(
                    "R8", path, real[i][0],
                    f"lockset drift: `self.{attr}` is written under "
                    f"`{sorted(real[i][1])[0]}` in "
                    f"`{cls.name}.{real[i][2]}` but under "
                    f"`{sorted(real[0][1])[0]}` in `{real[0][2]}` "
                    f"(line {real[0][0]}) — disjoint locks guard "
                    "nothing; pick one lock for this field"))
                break
    return out


# ----------------------------------------------------------- file driver

def _in_scope(path: str, scope: Sequence[str]) -> bool:
    norm = path.replace(os.sep, "/")
    return any(norm.endswith(s) if s.endswith(".py")
               else (s in norm) for s in scope)


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable[str]] = None,
                collect_suppressions: bool = False,
                extra_traced: Iterable[str] = (),
                extra_blocking: Iterable[str] = ()):
    """Lint one source blob.  ``rules`` defaults to every per-file rule
    (R4 is repo-level).  ``extra_traced``/``extra_blocking`` seed the
    intra-module reachability/fixpoint with names the whole-program
    graph proved traced/blocking (``run`` supplies them; a bare
    ``lint_source`` stays intra-module).  Returns findings, or
    ``(findings, suppressions)`` when ``collect_suppressions`` —
    already filtered through the suppression directives, with
    reasonless/unused directives reported as ``SUP`` findings."""
    active = set(rules) if rules is not None else {
        "R1", "R2", "R3", "R5", "R6", "R7", "R8"}
    tree = ast.parse(source)
    index = _ModuleIndex(tree)
    findings: List[Finding] = []
    if "R1" in active:
        findings += _check_r1(index, path, extra_traced)
    if "R2" in active:
        findings += _check_r2(tree, path)
    if "R3" in active:
        findings += _check_r3(tree, index, path, extra_blocking)
    if "R5" in active:
        findings += _check_r5(index, tree, path)
    if "R6" in active:
        findings += _check_r6(index, tree, path, extra_traced)
    if "R7" in active:
        findings += _check_r7(index, tree, path, extra_traced)
    if "R8" in active:
        findings += _check_r8(tree, path)

    sups = parse_suppressions(source)
    kept: List[Finding] = []
    for f in findings:
        suppressed = False
        for s in sups:
            if f.line == s.line and f.rule in s.rules:
                s.used = True
                suppressed = bool(s.reason)
                # a reasonless directive does NOT suppress: the reason
                # is the audited artifact
        if not suppressed:
            kept.append(f)
    for s in sups:
        if not s.reason:
            kept.append(Finding(
                "SUP", path, s.directive_line,
                "suppression without a reason — write `# dl4j-lint: "
                "disable=<rule> <why this is safe>`; the reason is the "
                "audited artifact"))
        elif not s.used and not any(r not in ALL_RULES for r in s.rules):
            kept.append(Finding(
                "SUP", path, s.directive_line,
                f"unused suppression for {','.join(s.rules)} — the "
                "finding it silenced is gone; delete the directive"))
    if collect_suppressions:
        return kept, sups
    return kept


def lint_file(path: str, repo_root: str,
              extra_traced: Iterable[str] = (),
              extra_blocking: Iterable[str] = ()) -> List[Finding]:
    rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rules = {"R1", "R3", "R5", "R6", "R8"}
    if not _in_scope(rel, R7_SINK_SCOPE):
        rules.add("R7")
    if _in_scope(rel, R2_SCOPE) and not _in_scope(rel, R2_EXEMPT):
        rules.add("R2")
    try:
        return lint_source(source, rel, rules,
                           extra_traced=extra_traced,
                           extra_blocking=extra_blocking)
    except SyntaxError as exc:
        return [Finding("SYN", rel, exc.lineno or 0,
                        f"syntax error: {exc.msg}")]


# ------------------------------------------------------------------- R4

REGISTRY_DOC = "docs/OBSERVABILITY.md"
REGISTRY_BEGIN = "<!-- dl4j-registry:begin -->"
REGISTRY_END = "<!-- dl4j-registry:end -->"

_ENV_RE = re.compile(r"DL4J_TPU_[A-Z0-9_]*[A-Z0-9](?![A-Z0-9_])")
#: quoted constants ending in "_" are env-name PREFIXES (e.g.
#: ``ENV_PREFIX = "DL4J_TPU_FAULT_"`` concatenated at runtime): doc
#: references to names under such a prefix are considered code-backed
_ENV_PREFIX_RE = re.compile(r"[\"'](DL4J_TPU_[A-Z0-9_]*_)[\"']")
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _code_files(root: str) -> List[str]:
    out: List[str] = []
    for base in ("deeplearning4j_tpu", "tools"):
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, base)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def _doc_files(root: str) -> List[str]:
    docs = [os.path.join(root, "README.md")]
    docdir = os.path.join(root, "docs")
    if os.path.isdir(docdir):
        docs += [os.path.join(docdir, f)
                 for f in sorted(os.listdir(docdir)) if f.endswith(".md")]
    return [d for d in docs if os.path.exists(d)]


def _metric_pattern(node: ast.Call,
                    consts: Dict[str, str]) -> Optional[str]:
    """Metric name (or ``<hole>`` pattern for f-strings) of a
    counter/gauge/histogram registration, resolving module-level string
    constants."""
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.Name) and arg.id in consts:
        return consts[arg.id]
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("<*>")
        return "".join(parts)
    return None


def collect_code_registry(
        root: str) -> Tuple[Set[str], Set[str], Set[str]]:
    """(env var names, metric name patterns, env-name prefixes)
    read/registered in code."""
    envs: Set[str] = set()
    metrics: Set[str] = set()
    prefixes: Set[str] = set()
    for path in _code_files(root):
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        envs.update(_ENV_RE.findall(source))
        prefixes.update(_ENV_PREFIX_RE.findall(source))
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        consts = {
            tgt.id: node.value.value
            for node in ast.walk(tree) if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            for tgt in node.targets if isinstance(tgt, ast.Name)}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name and name.split(".")[-1] in _METRIC_FACTORIES:
                pat = _metric_pattern(node, consts)
                if pat and re.fullmatch(r"[a-z][a-z0-9_<*>]*", pat):
                    metrics.add(pat)
    return envs, metrics, prefixes


def _registry_block(envs: Set[str], metrics: Set[str]) -> str:
    lines = [REGISTRY_BEGIN,
             "<!-- generated by `python -m tools.analyze "
             "--write-registry`; edits are overwritten and drift fails "
             "R4 -->",
             "", "| kind | name |", "|------|------|"]
    lines += [f"| env | `{e}` |" for e in sorted(envs)]
    lines += [f"| metric | `{m}` |" for m in sorted(metrics)]
    lines.append(REGISTRY_END)
    return "\n".join(lines)


def _parse_registry_block(text: str) -> Tuple[Set[str], Set[str]]:
    envs: Set[str] = set()
    metrics: Set[str] = set()
    for m in re.finditer(r"\|\s*(env|metric)\s*\|\s*`([^`]+)`\s*\|",
                         text):
        (envs if m.group(1) == "env" else metrics).add(m.group(2))
    return envs, metrics


def check_registry(root: str, write: bool = False) -> List[Finding]:
    """R4: code inventory vs the generated doc block, both directions,
    plus stale ``DL4J_TPU_*`` references anywhere in the docs."""
    findings: List[Finding] = []
    envs, metrics, prefixes = collect_code_registry(root)
    doc_path = os.path.join(root, REGISTRY_DOC)
    text = ""
    if os.path.exists(doc_path):
        with open(doc_path, encoding="utf-8") as fh:
            text = fh.read()
    begin, end = text.find(REGISTRY_BEGIN), text.find(REGISTRY_END)
    if write:
        block = _registry_block(envs, metrics)
        if begin != -1 and end != -1:
            new = text[:begin] + block + text[end + len(REGISTRY_END):]
        else:
            new = text.rstrip() + "\n\n## Registry inventory\n\n" \
                + block + "\n"
        # plain write: docs are not crash-safety scoped, and importing
        # utils.fileio would drag the whole (jax-importing) package into
        # what is otherwise a stdlib-only CI gate
        with open(doc_path, "w", encoding="utf-8") as fh:
            fh.write(new)
        text, begin = new, new.find(REGISTRY_BEGIN)
        end = new.find(REGISTRY_END)
    if begin == -1 or end == -1:
        findings.append(Finding(
            "R4", REGISTRY_DOC, 1,
            "registry drift: no generated inventory block — run "
            "`python -m tools.analyze --write-registry`"))
        return findings
    doc_envs, doc_metrics = _parse_registry_block(text[begin:end])
    line_of = text[:begin].count("\n") + 1
    for missing in sorted(envs - doc_envs):
        findings.append(Finding(
            "R4", REGISTRY_DOC, line_of,
            f"registry drift: env var `{missing}` is read in code but "
            "missing from the inventory — run --write-registry"))
    for stale in sorted(doc_envs - envs):
        if any(stale.startswith(p) for p in prefixes):
            continue
        findings.append(Finding(
            "R4", REGISTRY_DOC, line_of,
            f"registry drift: inventory names env var `{stale}` which "
            "nothing in code reads — run --write-registry"))
    for missing in sorted(metrics - doc_metrics):
        findings.append(Finding(
            "R4", REGISTRY_DOC, line_of,
            f"registry drift: metric `{missing}` is registered in code "
            "but missing from the inventory — run --write-registry"))
    for stale in sorted(doc_metrics - metrics):
        findings.append(Finding(
            "R4", REGISTRY_DOC, line_of,
            f"registry drift: inventory names metric `{stale}` which "
            "nothing in code registers — run --write-registry"))
    # stale env references in prose, any doc
    for doc in _doc_files(root):
        rel = os.path.relpath(doc, root).replace(os.sep, "/")
        with open(doc, encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, start=1):
                for tok in set(_ENV_RE.findall(line)):
                    if tok not in envs and not any(
                            tok.startswith(p) for p in prefixes):
                        findings.append(Finding(
                            "R4", rel, lineno,
                            f"registry drift: doc references env var "
                            f"`{tok}` which nothing in code reads"))
    return findings


# ------------------------------------------------------------ repo runs

def run(root: str, rules: Optional[Iterable[str]] = None,
        write_registry: bool = False) -> List[Finding]:
    """Lint the whole repo.  Builds the cross-module call graph once so
    every per-file check sees the whole-program traced/blocking sets.
    Returns every surviving finding."""
    active = set(rules) if rules is not None else set(ALL_RULES)
    findings: List[Finding] = []
    if active & {"R1", "R2", "R3", "R5", "R6", "R7", "R8"}:
        from tools.analyze import callgraph
        prog = callgraph.load(root)
        g_traced = prog.traced()
        g_blocking = prog.blocking()
        g_block_imports = prog.blocking_imports(g_blocking)
        for path in _code_files(root):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            mod = prog.by_path.get(rel)
            extra_traced: Set[str] = set()
            extra_blocking: Set[str] = set()
            if mod is not None:
                extra_traced = g_traced.get(mod.name, set())
                extra_blocking = (
                    set(g_blocking.get(mod.name, set()))
                    | g_block_imports.get(mod.name, set()))
            file_findings = lint_file(path, root,
                                      extra_traced=extra_traced,
                                      extra_blocking=extra_blocking)
            findings += [f for f in file_findings
                         if f.rule in active or f.rule in ("SUP", "SYN")]
    if "R4" in active:
        findings += check_registry(root, write=write_registry)
    return findings
