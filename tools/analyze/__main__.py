"""CLI for the invariant linter: ``python -m tools.analyze``.

Exit status contract (asserted by tests/test_analyze.py):

- **0** — no findings survive suppression; ``--strict`` (the CI mode)
  is the same check with the contract spelled out in the name.
- **1** — at least one finding.
- **2** — the analyzer itself failed (bad ``--root``, unreadable tree,
  internal crash): CI must distinguish "the code is dirty" from "the
  gate did not run".

``--write-registry`` regenerates the env/metric inventory block in
``docs/OBSERVABILITY.md`` instead of failing R4 on drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

from tools.analyze import lint

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL_ERROR = 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="project-native whole-program invariant linter "
                    "(rules R1-R8)")
    parser.add_argument("--strict", action="store_true",
                        help="exit nonzero on any finding (CI gate)")
    parser.add_argument("--write-registry", action="store_true",
                        help="regenerate the docs/OBSERVABILITY.md "
                             "env/metric inventory block")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--rules", default=",".join(lint.ALL_RULES),
                        help="comma-separated rule subset "
                             "(default: all)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetect from this "
                             "file's location)")
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
    try:
        if not os.path.isdir(root):
            raise OSError(f"--root {root!r} is not a directory")
        findings = lint.run(root, rules=rules,
                            write_registry=args.write_registry)
    except Exception:
        print("analyzer internal error:", file=sys.stderr)
        traceback.print_exc()
        return EXIT_INTERNAL_ERROR

    if args.json:
        print(json.dumps([{
            "rule": f.rule, "path": f.path, "line": f.line,
            "message": f.message} for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print(f"{len(findings)} finding(s)")
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
