"""CLI for the invariant linter: ``python -m tools.analyze``.

Exit status 0 when no findings survive suppression; ``--strict`` (the
CI mode) is the same check with the contract spelled out in the name.
``--write-registry`` regenerates the env/metric inventory block in
``docs/OBSERVABILITY.md`` instead of failing R4 on drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.analyze import lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="project-native invariant linter (rules R1-R5)")
    parser.add_argument("--strict", action="store_true",
                        help="exit nonzero on any finding (CI gate)")
    parser.add_argument("--write-registry", action="store_true",
                        help="regenerate the docs/OBSERVABILITY.md "
                             "env/metric inventory block")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--rules", default=",".join(lint.ALL_RULES),
                        help="comma-separated rule subset "
                             "(default: all)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetect from this "
                             "file's location)")
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
    findings = lint.run(root, rules=rules,
                        write_registry=args.write_registry)

    if args.json:
        print(json.dumps([{
            "rule": f.rule, "path": f.path, "line": f.line,
            "message": f.message} for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
