"""Runtime lock-order race detector: instrumented locks + cycle check.

The repo's serving/scaleout/streaming/deploy tiers hold ~46 lock sites
across 24 files.  Deadlock hazards there are ORDER bugs: thread 1 takes
A then B while thread 2 takes B then A.  Nothing static proves ordering
discipline (locks are passed through callbacks, sharded per chunk, and
nested across subsystems), so this module proves it at runtime instead:

- :func:`instrumented_lock` wraps ``threading.Lock``/``RLock`` with
  per-thread **held-lock tracking**.  Acquiring B while holding A adds
  the directed edge ``A -> B`` to a process-global graph keyed by the
  lock's *site name* (e.g. ``"serving.engine.placed"``), not the
  instance — lock-order discipline is a property of code sites.
- Every new edge runs a DFS **cycle check**; a cycle is a deadlock
  hazard (two threads interleaving the cycle's edges can deadlock even
  if this run got lucky).  Detection increments
  ``lockgraph_cycles_total``, records the cycle path, and dumps a
  ``lock_cycle`` flight-recorder bundle for the post-mortem.
- **Long holds** (release more than ``DL4J_TPU_LOCK_HOLD_MS``, default
  200 ms, after acquire) and **blocked acquires under a held lock**
  (waiting more than the same threshold for B while holding A — the
  runtime shadow of lint rule R3) are counted per lock name on
  ``lockgraph_long_holds_total`` / ``lockgraph_blocked_acquires_total``.

Opt-in and zero-overhead off: production constructors go through
``deeplearning4j_tpu.monitor.locks.make_lock``, which returns a plain
``threading.Lock`` unless ``DL4J_TPU_LOCK_DEBUG=1`` — the wrapper never
exists on the hot path unless armed.  Reentrant acquires of one RLock
instance do not create self-edges (reentrancy is not an ordering
hazard); nesting two *different* instances under one name is ignored
for ordering (same-site shards, e.g. per-chunk locks, are acquired
sequentially by design and a name-level self-edge would be
unfalsifiable).

Test/CI surface: :func:`graph` -> :class:`LockGraph` with
``edges()``, ``cycles()``, ``assert_acyclic()``, ``snapshot()``,
``reset()``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "DL4J_TPU_LOCK_DEBUG"
ENV_HOLD_MS = "DL4J_TPU_LOCK_HOLD_MS"
DEFAULT_HOLD_MS = 200.0

_TRUE = ("1", "true", "yes")


def enabled() -> bool:
    """Whether lock instrumentation is armed (``DL4J_TPU_LOCK_DEBUG=1``)."""
    return os.environ.get(ENV_FLAG, "") in _TRUE


def hold_threshold_s() -> float:
    """Long-hold / blocked-acquire threshold in seconds."""
    try:
        return float(os.environ.get(ENV_HOLD_MS, DEFAULT_HOLD_MS)) / 1e3
    except ValueError:
        return DEFAULT_HOLD_MS / 1e3


def _metrics():
    """The monitor registry, or ``None`` when unimportable (the detector
    must work in stripped-down subprocesses too)."""
    try:
        from deeplearning4j_tpu import monitor as _monitor
        return _monitor
    except Exception:
        return None


def _flight(kind: str, detail: dict) -> None:
    try:
        from deeplearning4j_tpu.monitor import record_incident
        record_incident(kind, detail)
    except Exception:
        pass


class LockGraph:
    """Process-global lock-acquisition-order graph (see module doc)."""

    def __init__(self) -> None:
        # the graph's own mutex is a plain lock, never instrumented —
        # instrumenting it would recurse
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], int] = {}
        self._adj: Dict[str, set] = {}
        self._cycles: List[Tuple[str, ...]] = []
        self._cycle_keys: set = set()
        self._tls = threading.local()

    # ------------------------------------------------------- thread state
    def _held(self) -> List[dict]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # ---------------------------------------------------------- recording
    def on_acquired(self, name: str, instance: int, wait_s: float) -> None:
        held = self._held()
        for entry in held:
            if entry["instance"] == instance:
                entry["depth"] += 1      # RLock reentry: no new node
                return
        mon = _metrics()
        if wait_s > hold_threshold_s() and held and mon is not None:
            mon.counter(
                "lockgraph_blocked_acquires_total",
                "lock acquires that blocked past the hold threshold "
                "while the thread already held another lock").inc(
                lock=name)
        new_edges = []
        with self._mu:
            for entry in held:
                a = entry["name"]
                if a == name:
                    continue             # same-site shards: not an order
                key = (a, name)
                if key not in self._edges:
                    new_edges.append(key)
                    self._adj.setdefault(a, set()).add(name)
                self._edges[key] = self._edges.get(key, 0) + 1
            cycles = [self._find_cycle_locked(a, b)
                      for a, b in new_edges]
        held.append({"name": name, "instance": instance,
                     "depth": 1, "t0": time.perf_counter()})
        if mon is not None and new_edges:
            mon.gauge("lockgraph_edges",
                      "distinct lock-order edges observed").set(
                len(self._edges))
        for cyc in cycles:
            if cyc is not None:
                self._report_cycle(cyc)

    def on_released(self, instance: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry["instance"] == instance:
                entry["depth"] -= 1
                if entry["depth"] == 0:
                    held.pop(i)
                    held_s = time.perf_counter() - entry["t0"]
                    if held_s > hold_threshold_s():
                        mon = _metrics()
                        if mon is not None:
                            mon.counter(
                                "lockgraph_long_holds_total",
                                "lock holds longer than the hold "
                                "threshold").inc(lock=entry["name"])
                return

    # ------------------------------------------------------ cycle finding
    def _find_cycle_locked(self, a: str,
                           b: str) -> Optional[Tuple[str, ...]]:
        """A new edge ``a -> b`` closes a cycle iff ``b`` reaches ``a``;
        returns the cycle path ``(a, b, ..., a)`` or ``None``.  Caller
        holds ``_mu``."""
        path = self._dfs_path(b, a, frozenset((b,)))
        if path is None:
            return None
        return (a,) + path

    def _dfs_path(self, src: str, dst: str,
                  seen: frozenset) -> Optional[Tuple[str, ...]]:
        if src == dst:
            return (src,)
        for nxt in self._adj.get(src, ()):
            if nxt in seen:
                continue
            sub = self._dfs_path(nxt, dst, seen | {nxt})
            if sub is not None:
                return (src,) + sub
        return None

    def _report_cycle(self, cycle: Tuple[str, ...]) -> None:
        # canonical key: rotation-invariant so A->B->A and B->A->B
        # report once
        body = cycle[:-1]
        k = min(range(len(body)), key=lambda i: body[i:] + body[:i])
        key = body[k:] + body[:k]
        with self._mu:
            if key in self._cycle_keys:
                return
            self._cycle_keys.add(key)
            self._cycles.append(cycle)
        mon = _metrics()
        if mon is not None:
            mon.counter(
                "lockgraph_cycles_total",
                "lock-order cycles (deadlock hazards) detected").inc()
        _flight("lock_cycle", {
            "cycle": " -> ".join(cycle),
            "edges": {f"{a} -> {b}": n
                      for (a, b), n in self.edges().items()},
        })

    # ------------------------------------------------------------ reading
    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> List[Tuple[str, ...]]:
        with self._mu:
            return list(self._cycles)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "edges": {f"{a} -> {b}": n
                          for (a, b), n in sorted(self._edges.items())},
                "cycles": [" -> ".join(c) for c in self._cycles],
            }

    def assert_acyclic(self) -> None:
        """Raise ``AssertionError`` naming every detected cycle (the
        regression-test gate)."""
        cycles = self.cycles()
        if cycles:
            raise AssertionError(
                "lock-order cycles detected: "
                + "; ".join(" -> ".join(c) for c in cycles))

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._adj.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
        # per-thread held stacks are left alone: live threads still hold
        # what they hold; tests reset between quiescent phases


_GRAPH = LockGraph()


def graph() -> LockGraph:
    return _GRAPH


def reset() -> None:
    _GRAPH.reset()


class InstrumentedLock:
    """``threading.Lock``/``RLock`` wrapper feeding the global
    :class:`LockGraph`.  Duck-types the full lock surface (``acquire`` /
    ``release`` / context manager / ``locked``) so it drops into every
    constructor-swap site unchanged."""

    __slots__ = ("_inner", "_name")

    def __init__(self, name: str, rlock: bool = False):
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._name = str(name)

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _GRAPH.on_acquired(self._name, id(self),
                               time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._inner.release()
        _GRAPH.on_released(id(self))

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock has no locked(); probe without blocking
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self._name!r} {self._inner!r}>"


def instrumented_lock(name: str, rlock: bool = False) -> InstrumentedLock:
    """An instrumented lock registered under ``name`` (dotted site name,
    e.g. ``"streaming.broker.state"``)."""
    return InstrumentedLock(name, rlock=rlock)
