"""Whole-program, import-resolved call graph for the analyzer v2.

The v1 linter (PR 13) computed jit-reachability and the blocking
fixpoint per module, so a traced helper imported from another module —
``ingest.device_decode`` called inside both containers' scan bodies —
or a blocking primitive wrapped one module away was invisible to R1/R3.
This module parses every code file once, resolves the repo's own
imports (absolute, aliased, and relative ``from ..monitor import x``
forms), and builds one directed call graph whose nodes are
``(module, function)`` pairs:

- **jit roots** are collected repo-wide (decorator form, assignment
  form, ``lax.scan`` bodies) with the root argument resolved through
  import aliases, then traced-ness propagates forward over the global
  edges;
- **blocking-ness** (R3's fixpoint) propagates backward from the
  blocking primitives over the same edges, so ``_recv_exact`` defined
  in a wire-utils module is caught at a ``with lock:`` site in another.

Like the rest of ``tools.analyze``, this is stdlib-only (``ast`` +
``os``): importing it pulls neither jax nor numpy, so the CI gate stays
pre-pip-install.  Resolution is name-based and deliberately
conservative — a call that cannot be resolved simply contributes no
edge (the linter under-approximates rather than guessing).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

FuncId = Tuple[str, str]   # (dotted module name, QUALIFIED function
                           # name: "fn" at module level, "Cls.meth" for
                           # methods — two classes never conflate)

_JIT_FACTORIES = {"jit", "watched_jit"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleNode:
    """One parsed code file: its functions, import aliases, and the
    (unresolved) jit-root argument expressions found in it."""

    def __init__(self, name: str, path: str, tree: ast.Module):
        self.name = name                 # dotted module name
        self.path = path                 # repo-relative path
        self.tree = tree
        self.package = name.rsplit(".", 1)[0] if "." in name else ""
        #: local alias -> dotted module name (``import x.y as m``,
        #: ``from pkg import submodule``)
        self.mod_aliases: Dict[str, str] = {}
        #: local alias -> (module, function) (``from pkg.mod import fn``)
        self.func_aliases: Dict[str, FuncId] = {}
        #: QUALIFIED function name ("fn" / "Cls.meth") -> FunctionDef
        self.functions: Dict[str, ast.FunctionDef] = {}
        #: bare name -> qualified names (collision-aware resolution)
        self.by_bare: Dict[str, List[str]] = {}
        #: raw ``from X import a [as b]`` entries kept for second-pass
        #: resolution once the full module set is known
        self._from_imports: List[Tuple[str, str, str]] = []
        self._collect()

    # ------------------------------------------------------- collection
    def _collect(self) -> None:
        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qname = f"{cls}.{child.name}" if cls else child.name
                    self.functions[qname] = child
                    self.by_bare.setdefault(child.name,
                                            []).append(qname)
                    # nested defs keep the class context, mirroring the
                    # per-module index so qnames agree across layers
                    visit(child, cls)
                else:
                    visit(child, cls)

        visit(self.tree, None)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.mod_aliases[local] = target
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from_base(node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self._from_imports.append((local, base, alias.name))

    def resolve_local(self, name: str, cls: Optional[str] = None,
                      via_self: bool = False) -> Optional[str]:
        """Qualified local function for a referenced name.  Bare-name
        references prefer module level; ``self.x`` references prefer
        the caller's own class; either falls back to a UNIQUE bare
        match (ambiguity resolves to nothing — conservative)."""
        if via_self and cls is not None:
            q = f"{cls}.{name}"
            if q in self.functions:
                return q
        if not via_self and name in self.functions:
            return name
        cands = self.by_bare.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def _resolve_from_base(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative: strip (level - 1) trailing components off the package
        parts = self.package.split(".") if self.package else []
        up = node.level - 1
        if up > len(parts):
            return None
        base = parts[:len(parts) - up] if up else parts
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base) if base else None


class Program:
    """The whole-program graph over every analyzer-scoped code file."""

    def __init__(self, root: str,
                 files: Optional[Sequence[str]] = None) -> None:
        self.root = root
        self.modules: Dict[str, ModuleNode] = {}
        self.by_path: Dict[str, ModuleNode] = {}
        from tools.analyze import lint as _lint
        paths = list(files) if files is not None else _lint._code_files(root)
        for path in paths:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read())
            except (SyntaxError, OSError):
                continue        # per-file syntax errors surface as SYN
            mod = ModuleNode(_module_name(rel), rel, tree)
            self.modules[mod.name] = mod
            self.by_path[rel] = mod
        self._finish_imports()
        self._edges: Dict[FuncId, Set[FuncId]] = {}
        self._build_edges()

    # ------------------------------------------------ import resolution
    def _finish_imports(self) -> None:
        for mod in self.modules.values():
            for local, base, name in mod._from_imports:
                as_module = f"{base}.{name}"
                if as_module in self.modules:
                    mod.mod_aliases[local] = as_module
                elif base in self.modules and \
                        name in self.modules[base].functions:
                    mod.func_aliases[local] = (base, name)

    def _resolve_attr_base(self, mod: ModuleNode,
                           base: str) -> Optional[str]:
        """Map a dotted receiver (``_monitor``, ``jax.lax``,
        ``deeplearning4j_tpu.nn.ingest``) to a known module name."""
        parts = base.split(".")
        if parts[0] in mod.mod_aliases:
            cand = ".".join([mod.mod_aliases[parts[0]]] + parts[1:])
            return cand if cand in self.modules else None
        return base if base in self.modules else None

    def resolve_call(self, mod: ModuleNode, call: ast.Call,
                     caller_cls: Optional[str] = None
                     ) -> Optional[FuncId]:
        """The (module, function) a call resolves to, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            local = mod.resolve_local(func.id)
            if local is not None:
                return (mod.name, local)
            return mod.func_aliases.get(func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id in ("self", "cls"):
                local = mod.resolve_local(func.attr, caller_cls,
                                          via_self=True)
                return (mod.name, local) if local is not None else None
            base = _dotted(func.value)
            if base is None:
                return None
            target = self._resolve_attr_base(mod, base)
            if target is not None and \
                    func.attr in self.modules[target].functions:
                return (target, func.attr)
        return None

    def _resolve_root_arg(self, mod: ModuleNode, arg: ast.AST,
                          caller_cls: Optional[str] = None
                          ) -> Optional[FuncId]:
        """Resolve a jit factory's function argument to a FuncId."""
        if isinstance(arg, ast.Name):
            local = mod.resolve_local(arg.id)
            if local is not None:
                return (mod.name, local)
            return mod.func_aliases.get(arg.id)
        if isinstance(arg, ast.Attribute):
            if isinstance(arg.value, ast.Name) and \
                    arg.value.id in ("self", "cls"):
                local = mod.resolve_local(arg.attr, caller_cls,
                                          via_self=True)
                return (mod.name, local) if local is not None else None
            base = _dotted(arg.value)
            if base is not None:
                target = self._resolve_attr_base(mod, base)
                if target is not None and \
                        arg.attr in self.modules[target].functions:
                    return (target, arg.attr)
        return None

    @staticmethod
    def _cls_of(qname: str) -> Optional[str]:
        return qname.split(".", 1)[0] if "." in qname else None

    # ----------------------------------------------------- graph build
    def _build_edges(self) -> None:
        for mod in self.modules.values():
            for qname, fnode in mod.functions.items():
                src = (mod.name, qname)
                cls = self._cls_of(qname)
                edges = self._edges.setdefault(src, set())
                for sub in ast.walk(fnode):
                    if isinstance(sub, ast.Call):
                        dst = self.resolve_call(mod, sub, cls)
                        if dst is not None and dst != src:
                            edges.add(dst)

    def jit_roots(self) -> Set[FuncId]:
        roots: Set[FuncId] = set()
        for mod in self.modules.values():
            for qname, fnode in mod.functions.items():
                cls = self._cls_of(qname)
                for dec in fnode.decorator_list:
                    name = _dotted(dec if not isinstance(dec, ast.Call)
                                   else dec.func)
                    if name and name.split(".")[-1] in _JIT_FACTORIES:
                        roots.add((mod.name, qname))
                for node in ast.walk(fnode):
                    root = self._factory_root(mod, node, cls)
                    if root is not None:
                        roots.add(root)
            for node in ast.walk(mod.tree):   # module-scope factories
                root = self._factory_root(mod, node, None)
                if root is not None:
                    roots.add(root)
        return roots

    def _factory_root(self, mod: ModuleNode, node: ast.AST,
                      caller_cls: Optional[str]) -> Optional[FuncId]:
        if not isinstance(node, ast.Call) or not node.args:
            return None
        name = _dotted(node.func)
        if name is None:
            return None
        tail = name.split(".")[-1]
        is_scan = (tail == "scan" and name.split(".")[-2:-1] == ["lax"])
        if tail not in _JIT_FACTORIES and not is_scan:
            return None
        return self._resolve_root_arg(mod, node.args[0], caller_cls)

    def traced(self) -> Dict[str, Set[str]]:
        """module name -> bare names of jit-reachable functions, via the
        GLOBAL graph (the cross-module extension of R1 reachability)."""
        seen: Set[FuncId] = set()
        frontier = list(self.jit_roots())
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            frontier.extend(d for d in self._edges.get(cur, ())
                            if d not in seen)
        out: Dict[str, Set[str]] = {}
        for m, f in seen:
            out.setdefault(m, set()).add(f)
        return out

    def blocking(self) -> Dict[str, Set[str]]:
        """module name -> bare names of functions that (transitively,
        across modules) perform a blocking call — R3's global fixpoint."""
        from tools.analyze import lint as _lint
        blocking: Set[FuncId] = set()
        # seed: functions with a DIRECT blocking primitive call
        for mod in self.modules.values():
            for fname, fnode in mod.functions.items():
                for sub in ast.walk(fnode):
                    if isinstance(sub, ast.Call) and \
                            _lint._is_blocking_call(sub, set()):
                        blocking.add((mod.name, fname))
                        break
        # reverse propagation over resolved edges to fixpoint
        changed = True
        while changed:
            changed = False
            for src, dsts in self._edges.items():
                if src not in blocking and dsts & blocking:
                    blocking.add(src)
                    changed = True
        out: Dict[str, Set[str]] = {}
        for m, f in blocking:
            out.setdefault(m, set()).add(f)
        return out

    def blocking_imports(
            self, blocking: Optional[Dict[str, Set[str]]] = None
    ) -> Dict[str, Set[str]]:
        """module name -> bare callable names VISIBLE in that module
        through its own imports (``from wire import _recv_exact``,
        ``from .. import wire``) that resolve to a blocking function
        defined elsewhere.  These feed R3's intra-module matcher so
        ``wire._recv_exact(...)`` under a lock is caught at the call
        site; only names a module actually imports are matched, keeping
        the attr-call match precise."""
        if blocking is None:
            blocking = self.blocking()
        bset = {(m, f) for m, fs in blocking.items() for f in fs}
        out: Dict[str, Set[str]] = {}
        for mod in self.modules.values():
            names: Set[str] = set()
            for local, fid in mod.func_aliases.items():
                if fid in bset:
                    names.add(local)
            for target in mod.mod_aliases.values():
                tm = self.modules.get(target)
                if tm is not None:
                    # only module-level names are reachable through a
                    # module alias (methods carry a "Cls." prefix)
                    names.update(f for f in tm.functions
                                 if "." not in f and (target, f) in bset)
            out[mod.name] = names
        return out


def _module_name(rel: str) -> str:
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def load(root: str, files: Optional[Sequence[str]] = None) -> Program:
    return Program(root, files=files)
