"""Runtime dispatch sanitizer: the dynamic half of analyzer v2.

The static rules (R6/R7/R8) prove what the AST can prove; this module
enforces at runtime the contracts the repo's perf work depends on but
nothing asserted — armed by ``DL4J_TPU_SANITIZE=1`` and wired into
``monitor.jit_watch`` (every ``watched_jit`` dispatch reports here) and
the fit/serving scenario sites:

- **zero recompiles after warmup** — once the driver calls
  :func:`end_warmup` (bench warmup loop done, serving engine primed), a
  ``watched_jit`` seeing a NEW abstract signature on an
  already-compiled function is a violation: shape churn turned
  "compiled once" into compile-per-step.
- **dispatch-count ceilings per scenario** — :func:`scenario` brackets
  one logical unit of work (one fused-epoch fit group, one RNN serving
  step) and counts the ``watched_jit`` dispatches inside it against the
  per-scenario budget declared in ``tools/analyze/budgets.json``
  (ceiling = ``units * max_dispatches_per_unit + extra``).  The
  one-dispatch-per-epoch and one-dispatch-per-RNN-step contracts stop
  being "tested once" and become asserted on every armed run.  The
  FIRST occurrence of each scenario name is warmup (compile probes and
  cost-analysis lowering inflate it) — recorded, not enforced.
- **donation verification** — every ``donate_argnums`` input buffer
  must actually report deleted after dispatch.  jax silently skips
  donation it cannot use (no aliasable output slot), which un-halves
  the fused step's HBM high-water mark without a single warning;
  ``sanitizer_donation_misses_total`` catches it.
  ``DL4J_TPU_SANITIZE_DONATION=off`` disables the audit on platforms
  that do not implement donation.

Violations increment ``sanitizer_violations_total{kind=...}`` (plus the
per-kind counters), drop a ``sanitizer_violation`` flight-recorder
bundle, and — under ``DL4J_TPU_SANITIZE_STRICT=1`` — raise
:class:`SanitizerViolation` at the detection site.

Like the rest of ``tools.analyze`` this module imports neither jax nor
the monitor package at import time (the monitor resolves lazily, the
jax-touching audit lives in ``jit_watch`` which already imports jax),
so ``python -m tools.analyze`` stays a pre-pip-install CI gate.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

ENV_FLAG = "DL4J_TPU_SANITIZE"
ENV_STRICT = "DL4J_TPU_SANITIZE_STRICT"
ENV_BUDGETS = "DL4J_TPU_SANITIZE_BUDGETS"
ENV_DONATION = "DL4J_TPU_SANITIZE_DONATION"

_TRUE = ("1", "true", "yes")

DEFAULT_BUDGETS_PATH = os.path.join(os.path.dirname(__file__),
                                    "budgets.json")

VIOLATIONS_TOTAL = "sanitizer_violations_total"
RECOMPILES_TOTAL = "sanitizer_recompiles_after_warmup_total"
DONATION_MISSES_TOTAL = "sanitizer_donation_misses_total"
BUDGET_EXCEEDED_TOTAL = "sanitizer_dispatch_budget_exceeded_total"


class SanitizerViolation(RuntimeError):
    """A dispatch-discipline contract was broken under strict mode."""


def enabled() -> bool:
    """Whether the sanitizer is armed (``DL4J_TPU_SANITIZE=1``)."""
    return os.environ.get(ENV_FLAG, "") in _TRUE


def strict() -> bool:
    return os.environ.get(ENV_STRICT, "") in _TRUE


def donation_audit() -> bool:
    """Whether donated-buffer verification is on (default yes; set
    ``DL4J_TPU_SANITIZE_DONATION=off`` on platforms without donation)."""
    return os.environ.get(ENV_DONATION, "auto").lower() != "off"


def _metrics():
    try:
        from deeplearning4j_tpu import monitor as _monitor
        return _monitor
    except Exception:
        return None


def _flight(kind: str, detail: dict) -> None:
    try:
        from deeplearning4j_tpu.monitor import record_incident
        record_incident(kind, detail)
    except Exception:
        pass


def load_budgets(path: Optional[str] = None) -> Dict[str, dict]:
    """Per-scenario budgets: ``{name: {"max_dispatches_per_unit": n}}``.
    ``DL4J_TPU_SANITIZE_BUDGETS`` overrides the packaged file."""
    path = path or os.environ.get(ENV_BUDGETS) or DEFAULT_BUDGETS_PATH
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    return {k: v for k, v in data.items()
            if isinstance(v, dict) and not k.startswith("_")}


class _Scenario:
    __slots__ = ("name", "units", "extra", "dispatches")

    def __init__(self, name: str, units: int, extra: int):
        self.name = name
        self.units = max(1, int(units))
        self.extra = max(0, int(extra))
        self.dispatches = 0


class Sanitizer:
    """Process-global violation collector (see module doc)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._warmup_over = False
        self._seen_scenarios: Dict[str, int] = {}
        self._violations: List[dict] = []
        self._budgets: Optional[Dict[str, dict]] = None

    # ------------------------------------------------------------ state
    def budgets(self) -> Dict[str, dict]:
        with self._mu:
            if self._budgets is None:
                self._budgets = load_budgets()
            return self._budgets

    def end_warmup(self) -> None:
        """From here on, any recompile is a violation."""
        with self._mu:
            self._warmup_over = True

    def warmed_up(self) -> bool:
        with self._mu:
            return self._warmup_over

    def violations(self) -> List[dict]:
        with self._mu:
            return list(self._violations)

    def violation_count(self) -> int:
        with self._mu:
            return len(self._violations)

    def reset(self) -> None:
        with self._mu:
            self._warmup_over = False
            self._seen_scenarios.clear()
            self._violations.clear()
            self._budgets = None

    def _stack(self) -> List[_Scenario]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -------------------------------------------------------- recording
    def record_dispatch(self, fn: str, compiled: bool,
                        recompile: bool) -> None:
        """Every ``watched_jit`` dispatch lands here when armed."""
        for scen in self._stack():
            scen.dispatches += 1
        if recompile and self.warmed_up():
            mon = _metrics()
            if mon is not None:
                mon.counter(
                    RECOMPILES_TOTAL,
                    "recompiles observed after sanitize_end_warmup "
                    "(each one is shape/static-arg churn)").inc(fn=fn)
            self._violate("recompile_after_warmup", {
                "fn": fn,
                "hint": "a new abstract signature reached an "
                        "already-compiled function after warmup; see "
                        "the jit/compile trace span for the signature",
            })

    def record_donation(self, fn: str, missed: int, total: int) -> None:
        """Post-dispatch donated-buffer audit result from jit_watch."""
        if missed <= 0:
            return
        mon = _metrics()
        if mon is not None:
            mon.counter(
                DONATION_MISSES_TOTAL,
                "donated input buffers still live after dispatch "
                "(donation silently unusable)").inc(missed, fn=fn)
        self._violate("donation_miss", {
            "fn": fn, "missed": missed, "total": total,
            "hint": "a donate_argnums buffer was not consumed — the "
                    "output has no aliasable slot of that shape/dtype, "
                    "so the step's HBM high-water mark doubled "
                    "silently",
        })

    # -------------------------------------------------------- scenarios
    def scenario(self, name: str, units: int = 1, extra: int = 0):
        """Context manager bracketing one unit of budgeted work."""
        return _ScenarioContext(self, name, units, extra)

    def _enter(self, scen: _Scenario) -> None:
        self._stack().append(scen)

    def _exit(self, scen: _Scenario) -> None:
        stack = self._stack()
        if scen in stack:
            stack.remove(scen)
        with self._mu:
            occurrence = self._seen_scenarios.get(scen.name, 0)
            self._seen_scenarios[scen.name] = occurrence + 1
        budget = self.budgets().get(scen.name)
        if budget is None or occurrence == 0:
            return          # unbudgeted, or warmup occurrence
        per_unit = int(budget.get("max_dispatches_per_unit", 0))
        if per_unit <= 0:
            return
        ceiling = scen.units * per_unit + scen.extra
        if scen.dispatches > ceiling:
            mon = _metrics()
            if mon is not None:
                mon.counter(
                    BUDGET_EXCEEDED_TOTAL,
                    "scenarios whose dispatch count exceeded the "
                    "budgets.json ceiling").inc(scenario=scen.name)
            self._violate("dispatch_budget", {
                "scenario": scen.name, "dispatches": scen.dispatches,
                "ceiling": ceiling, "units": scen.units,
                "extra": scen.extra,
                "hint": "more jitted dispatches than the declared "
                        "contract (e.g. one dispatch per fused epoch "
                        "group) — a fused path degraded to per-step "
                        "dispatch",
            })

    # -------------------------------------------------------- violations
    def _violate(self, kind: str, detail: dict) -> None:
        entry = dict(detail, kind=kind)
        with self._mu:
            self._violations.append(entry)
        mon = _metrics()
        if mon is not None:
            mon.counter(
                VIOLATIONS_TOTAL,
                "sanitizer contract violations by kind").inc(kind=kind)
        _flight("sanitizer_violation", entry)
        if strict():
            raise SanitizerViolation(f"{kind}: {detail}")


class _ScenarioContext:
    __slots__ = ("_san", "_scen")

    def __init__(self, san: Sanitizer, name: str, units: int,
                 extra: int):
        self._san = san
        self._scen = _Scenario(name, units, extra)

    def __enter__(self) -> _Scenario:
        self._san._enter(self._scen)
        return self._scen

    def __exit__(self, exc_type, exc, tb) -> None:
        # budget enforcement only on the clean path: an exception mid-
        # scenario already surfaces louder than a budget count would
        if exc_type is None:
            self._san._exit(self._scen)
        else:
            stack = self._san._stack()
            if self._scen in stack:
                stack.remove(self._scen)


_SANITIZER = Sanitizer()


def state() -> Sanitizer:
    return _SANITIZER


def end_warmup() -> None:
    _SANITIZER.end_warmup()


def scenario(name: str, units: int = 1, extra: int = 0):
    return _SANITIZER.scenario(name, units=units, extra=extra)


def record_dispatch(fn: str, compiled: bool, recompile: bool) -> None:
    _SANITIZER.record_dispatch(fn, compiled, recompile)


def record_donation(fn: str, missed: int, total: int) -> None:
    _SANITIZER.record_donation(fn, missed, total)


def violations() -> List[dict]:
    return _SANITIZER.violations()


def violation_count() -> int:
    return _SANITIZER.violation_count()


def reset() -> None:
    _SANITIZER.reset()
