"""Project-native correctness tooling: invariant linter + whole-program
call graph + runtime sanitizers.

- ``tools.analyze.lint`` — AST rules R1 (traced purity), R2 (atomic
  writes), R3 (blocking under lock), R4 (registry drift), R5 (donation
  safety), R6 (retrace risk), R7 (hidden host<->device transfers), R8
  (lockset guarded-field drift), with audited inline suppressions.
- ``tools.analyze.callgraph`` — import-resolved cross-module call
  graph; makes R1 reachability and R3's blocking fixpoint
  whole-program.
- ``tools.analyze.lockgraph`` — runtime lock-order cycle detector,
  armed by ``DL4J_TPU_LOCK_DEBUG=1``.
- ``tools.analyze.sanitizer`` — runtime dispatch sanitizer (recompile /
  dispatch-budget / donation contracts), armed by
  ``DL4J_TPU_SANITIZE=1``.

CI gate: ``python -m tools.analyze --strict`` (zero findings).  See
``docs/ANALYSIS.md``.
"""

from tools.analyze.lint import (  # noqa: F401
    ALL_RULES,
    Finding,
    check_registry,
    lint_file,
    lint_source,
    run,
)
