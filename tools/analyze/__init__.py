"""Project-native correctness tooling: invariant linter + lock-order
race detector.

- ``tools.analyze.lint`` — AST rules R1 (traced purity), R2 (atomic
  writes), R3 (blocking under lock), R4 (registry drift), R5 (donation
  safety), with audited inline suppressions.
- ``tools.analyze.lockgraph`` — runtime lock-order cycle detector,
  armed by ``DL4J_TPU_LOCK_DEBUG=1``.

CI gate: ``python -m tools.analyze --strict`` (zero findings).  See
``docs/ANALYSIS.md``.
"""

from tools.analyze.lint import (  # noqa: F401
    ALL_RULES,
    Finding,
    check_registry,
    lint_file,
    lint_source,
    run,
)
