"""Curves dataset iterator.

TPU-native equivalent of the reference's
``datasets/iterator/impl/CurvesDataSetIterator.java`` +
``datasets/fetchers/CurvesDataFetcher.java``: the classic 28x28 "curves"
benchmark (random smooth strokes) used for unsupervised pretraining of
autoencoders/RBMs/deep-belief stacks.

The reference downloads a serialized dataset; this build generates the
curves procedurally and deterministically: each example is a random cubic
Bezier stroke rasterized with a soft pen onto a 28x28 canvas.  As in the
reference's usage (autoencoder pretraining), ``labels == features`` — the
reconstruction target."""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator

SIZE = 28


def _render_curve(rng: np.random.RandomState) -> np.ndarray:
    """Rasterize one random cubic Bezier stroke with a 2-px soft pen.

    Max of equal-sigma Gaussians == Gaussian of the min squared distance,
    so one exp over the per-pixel nearest sample point suffices."""
    pts = rng.uniform(3, SIZE - 3, (4, 2))
    t = np.linspace(0.0, 1.0, 120)[:, None]
    # cubic Bezier interpolation
    b = ((1 - t) ** 3 * pts[0] + 3 * (1 - t) ** 2 * t * pts[1]
         + 3 * (1 - t) * t ** 2 * pts[2] + t ** 3 * pts[3])
    yy, xx = np.mgrid[0:SIZE, 0:SIZE].astype(np.float64)
    d2 = ((yy[:, :, None] - b[:, 0]) ** 2
          + (xx[:, :, None] - b[:, 1]) ** 2).min(axis=-1)
    img = np.exp(-d2 / (2 * 0.8 ** 2)).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def curves_arrays(num_examples: int = 1000,
                  seed: int = 17) -> Tuple[np.ndarray, np.ndarray]:
    """(features, labels) with labels == features (reconstruction)."""
    rng = np.random.RandomState(seed)
    x = np.empty((num_examples, SIZE * SIZE), np.float32)
    for i in range(num_examples):
        x[i] = _render_curve(rng).ravel()
    return x, x.copy()


class CurvesDataSetIterator(ListDataSetIterator):
    """Reference signature ``CurvesDataSetIterator(batch, numSamples)``:
    flat 784-vector features in [0,1], labels = features."""

    def __init__(self, batch: int, num_samples: int = 1000,
                 shuffle: bool = False, seed: int = 17):
        x, y = curves_arrays(num_samples, seed)
        super().__init__(DataSet(x, y), batch, shuffle, seed)
