"""Record readers + the record→DataSet ETL bridge.

TPU-native equivalent of the DataVec bridge the reference trains from:
``datasets/datavec/RecordReaderDataSetIterator.java`` (records → feature
matrix + one-hot/regression labels) and
``datasets/datavec/SequenceRecordReaderDataSetIterator.java`` (paired
feature/label sequence readers, EQUAL_LENGTH / ALIGN_START / ALIGN_END
alignment with masks), plus the minimal reader SPI they consume
(DataVec's ``CSVRecordReader`` / ``CSVSequenceRecordReader`` /
``CollectionRecordReader``).

Host-side ETL; batches come out as numpy DataSets ready to donate into the
jitted train step.  Whole-batch assembly is vectorised (one ``np.asarray``
per batch, not per record).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator

Record = List[Union[float, int, str]]


def _read_csv_records(path: str, skip_num_lines: int,
                      delimiter: str) -> List[Record]:
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln.rstrip("\n") for ln in f]
    return [ln.split(delimiter) for ln in lines[skip_num_lines:] if ln]


# ------------------------------------------------------------------ readers

class RecordReader:
    """Minimal reader SPI (DataVec ``RecordReader``): a resettable stream
    of records, each a list of values."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> Record:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_record()


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec ``CollectionRecordReader``)."""

    def __init__(self, records: Sequence[Record]):
        self._records = [list(r) for r in records]
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._records)

    def next_record(self) -> Record:
        r = self._records[self._pos]
        self._pos += 1
        return list(r)

    def reset(self) -> None:
        self._pos = 0


class CSVRecordReader(RecordReader):
    """CSV line reader (DataVec ``CSVRecordReader``): ``initialize(path)``
    then stream one record per line, with ``skip_num_lines`` header skip."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self._records: List[Record] = []
        self._pos = 0

    def initialize(self, path: str) -> "CSVRecordReader":
        self._records = _read_csv_records(path, self.skip_num_lines,
                                          self.delimiter)
        self._pos = 0
        return self

    has_next = CollectionRecordReader.has_next
    next_record = CollectionRecordReader.next_record
    reset = CollectionRecordReader.reset


class SequenceRecordReader:
    """Sequence reader SPI (DataVec ``SequenceRecordReader``): a stream of
    sequences, each a list of records (time steps)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sequence(self) -> List[Record]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSequenceRecordReader(SequenceRecordReader):
    """In-memory sequences (DataVec ``CollectionSequenceRecordReader``)."""

    def __init__(self, sequences: Sequence[Sequence[Record]]):
        self._seqs = [[list(r) for r in s] for s in sequences]
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._seqs)

    def next_sequence(self) -> List[Record]:
        s = self._seqs[self._pos]
        self._pos += 1
        return [list(r) for r in s]

    def reset(self) -> None:
        self._pos = 0


class CSVSequenceRecordReader(CollectionSequenceRecordReader):
    """One CSV file per sequence (DataVec ``CSVSequenceRecordReader``);
    ``initialize`` takes a list of file paths or a directory."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        super().__init__([])
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter

    def initialize(self, paths: Union[str, Sequence[str]]
                   ) -> "CSVSequenceRecordReader":
        if isinstance(paths, str):
            paths = sorted(
                os.path.join(paths, n) for n in os.listdir(paths)
                if not n.startswith("."))
        self._seqs = [_read_csv_records(p, self.skip_num_lines,
                                        self.delimiter) for p in paths]
        self._pos = 0
        return self


# ------------------------------------------------------- records → DataSet

def _one_hot(values: np.ndarray, num_classes: int) -> np.ndarray:
    idx = values.astype(np.int64)
    if (idx < 0).any() or (idx >= num_classes).any():
        raise ValueError(f"label out of range [0,{num_classes})")
    return np.eye(num_classes, dtype=np.float32)[idx]


class RecordReaderDataSetIterator(DataSetIterator):
    """Records → minibatch DataSets (reference
    ``RecordReaderDataSetIterator.java``).

    ``label_index``: column holding the label (-1 = no labels, features
    only — labels mirror features like the reference's unsupervised path).
    ``num_possible_labels`` one-hots an integer class column;
    ``regression=True`` keeps label columns as real values, with
    ``label_index_to`` for multi-column regression targets (reference
    labelIndexTo).  ``max_num_batches`` truncates the pass.
    """

    def __init__(self, record_reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False, label_index_to: int = -1,
                 max_num_batches: int = -1):
        self.reader = record_reader
        self._batch = batch_size
        self.label_index = label_index
        self.label_index_to = (label_index_to if label_index_to >= 0
                               else label_index)
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.max_num_batches = max_num_batches
        self._batch_num = 0
        if not regression and label_index >= 0 and num_possible_labels <= 0:
            raise ValueError("classification needs num_possible_labels")

    def batch(self) -> int:
        return self._batch

    def reset(self) -> None:
        self.reader.reset()
        self._batch_num = 0

    def __next__(self) -> DataSet:
        if (self.max_num_batches >= 0
                and self._batch_num >= self.max_num_batches):
            raise StopIteration
        rows: List[Record] = []
        while self.reader.has_next() and len(rows) < self._batch:
            rows.append(self.reader.next_record())
        if not rows:
            raise StopIteration
        self._batch_num += 1
        mat = np.asarray(rows, dtype=np.float32)
        if self.label_index < 0:
            return self._pre(DataSet(mat, mat))
        li, lt = self.label_index, self.label_index_to
        feat = np.concatenate([mat[:, :li], mat[:, lt + 1:]], axis=1)
        if self.regression:
            labels = mat[:, li:lt + 1]
        else:
            labels = _one_hot(mat[:, li], self.num_possible_labels)
        return self._pre(DataSet(feat, labels))


class AlignmentMode:
    """Sequence alignment modes (reference
    ``SequenceRecordReaderDataSetIterator.AlignmentMode``)."""
    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Paired feature/label sequence readers → padded+masked time-series
    DataSets (reference ``SequenceRecordReaderDataSetIterator.java``).

    Layout is TPU-native (batch, time, features) — the reference emits
    (batch, features, time); the recurrent tier here scans over axis 1.
    Under ``ALIGN_START`` shorter sequences occupy leading steps with a
    trailing mask; under ``ALIGN_END`` they occupy trailing steps —
    i.e. labels at the final step stay aligned for seq-classification.
    """

    def __init__(self, features_reader: SequenceRecordReader,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 mini_batch_size: int = 10,
                 num_possible_labels: int = -1,
                 regression: bool = False,
                 alignment_mode: str = AlignmentMode.EQUAL_LENGTH,
                 label_index: int = -1):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self._batch = mini_batch_size
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.alignment_mode = alignment_mode
        self.label_index = label_index  # single-reader mode
        if labels_reader is None and label_index < 0:
            raise ValueError("need a labels reader or a label_index")

    def batch(self) -> int:
        return self._batch

    def reset(self) -> None:
        self.features_reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def _label_steps(self, seq: List[Record]) -> np.ndarray:
        arr = np.asarray(seq, dtype=np.float32)
        if self.regression:
            return arr
        if arr.shape[1] != 1:
            raise ValueError("classification label records must have one "
                             "column")
        return _one_hot(arr[:, 0], self.num_possible_labels)

    def __next__(self) -> DataSet:
        fseqs, lseqs = [], []
        while (self.features_reader.has_next()
               and len(fseqs) < self._batch):
            fs = self.features_reader.next_sequence()
            if self.labels_reader is not None:
                ls = self.labels_reader.next_sequence()
            else:
                li = self.label_index
                ls = [[r[li]] for r in fs]
                fs = [r[:li] + r[li + 1:] for r in fs]
            fseqs.append(np.asarray(fs, dtype=np.float32))
            lseqs.append(self._label_steps(ls))
        if not fseqs:
            raise StopIteration
        n = len(fseqs)
        flens = [s.shape[0] for s in fseqs]
        llens = [s.shape[0] for s in lseqs]
        if self.alignment_mode == AlignmentMode.EQUAL_LENGTH:
            if len(set(flens)) > 1 or flens != llens:
                raise ValueError(
                    "EQUAL_LENGTH alignment requires equal sequence "
                    f"lengths, got features {flens} labels {llens}")
        T = max(max(flens), max(llens))
        fdim = fseqs[0].shape[1]
        ldim = lseqs[0].shape[1]
        feats = np.zeros((n, T, fdim), np.float32)
        labels = np.zeros((n, T, ldim), np.float32)
        fmask = np.zeros((n, T), np.float32)
        lmask = np.zeros((n, T), np.float32)
        align_end = self.alignment_mode == AlignmentMode.ALIGN_END
        for i, (fs, ls) in enumerate(zip(fseqs, lseqs)):
            fo = T - fs.shape[0] if align_end else 0
            lo = T - ls.shape[0] if align_end else 0
            feats[i, fo:fo + fs.shape[0]] = fs
            fmask[i, fo:fo + fs.shape[0]] = 1.0
            labels[i, lo:lo + ls.shape[0]] = ls
            lmask[i, lo:lo + ls.shape[0]] = 1.0
        if self.alignment_mode == AlignmentMode.EQUAL_LENGTH:
            return self._pre(DataSet(feats, labels))
        return self._pre(DataSet(feats, labels, fmask, lmask))
