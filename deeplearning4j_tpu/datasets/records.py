"""Record readers + the record→DataSet ETL bridge.

TPU-native equivalent of the DataVec bridge the reference trains from:
``datasets/datavec/RecordReaderDataSetIterator.java`` (records → feature
matrix + one-hot/regression labels) and
``datasets/datavec/SequenceRecordReaderDataSetIterator.java`` (paired
feature/label sequence readers, EQUAL_LENGTH / ALIGN_START / ALIGN_END
alignment with masks), plus the minimal reader SPI they consume
(DataVec's ``CSVRecordReader`` / ``CSVSequenceRecordReader`` /
``CollectionRecordReader``).

Host-side ETL; batches come out as numpy DataSets ready to donate into the
jitted train step.  Whole-batch assembly is vectorised (one ``np.asarray``
per batch, not per record).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from .dataset import DataSet, MultiDataSet
from .iterators import DataSetIterator

Record = List[Union[float, int, str]]


def _read_csv_records(path: str, skip_num_lines: int,
                      delimiter: str) -> List[Record]:
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln.rstrip("\n") for ln in f]
    return [ln.split(delimiter) for ln in lines[skip_num_lines:] if ln]


# ------------------------------------------------------------------ readers

class RecordReader:
    """Minimal reader SPI (DataVec ``RecordReader``): a resettable stream
    of records, each a list of values."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> Record:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_record()


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec ``CollectionRecordReader``)."""

    def __init__(self, records: Sequence[Record]):
        self._records = [list(r) for r in records]
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._records)

    def next_record(self) -> Record:
        r = self._records[self._pos]
        self._pos += 1
        return list(r)

    def reset(self) -> None:
        self._pos = 0


class CSVRecordReader(RecordReader):
    """CSV line reader (DataVec ``CSVRecordReader``): ``initialize(path)``
    then stream one record per line, with ``skip_num_lines`` header skip."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self._records: List[Record] = []
        self._pos = 0

    def initialize(self, path: str) -> "CSVRecordReader":
        self._records = _read_csv_records(path, self.skip_num_lines,
                                          self.delimiter)
        self._pos = 0
        return self

    has_next = CollectionRecordReader.has_next
    next_record = CollectionRecordReader.next_record
    reset = CollectionRecordReader.reset


class SequenceRecordReader:
    """Sequence reader SPI (DataVec ``SequenceRecordReader``): a stream of
    sequences, each a list of records (time steps)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sequence(self) -> List[Record]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSequenceRecordReader(SequenceRecordReader):
    """In-memory sequences (DataVec ``CollectionSequenceRecordReader``)."""

    def __init__(self, sequences: Sequence[Sequence[Record]]):
        self._seqs = [[list(r) for r in s] for s in sequences]
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._seqs)

    def next_sequence(self) -> List[Record]:
        s = self._seqs[self._pos]
        self._pos += 1
        return [list(r) for r in s]

    def reset(self) -> None:
        self._pos = 0


class CSVSequenceRecordReader(CollectionSequenceRecordReader):
    """One CSV file per sequence (DataVec ``CSVSequenceRecordReader``);
    ``initialize`` takes a list of file paths or a directory."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        super().__init__([])
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter

    def initialize(self, paths: Union[str, Sequence[str]]
                   ) -> "CSVSequenceRecordReader":
        if isinstance(paths, str):
            paths = sorted(
                os.path.join(paths, n) for n in os.listdir(paths)
                if not n.startswith("."))
        self._seqs = [_read_csv_records(p, self.skip_num_lines,
                                        self.delimiter) for p in paths]
        self._pos = 0
        return self


# ------------------------------------------------------- records → DataSet

def _one_hot(values: np.ndarray, num_classes: int) -> np.ndarray:
    idx = values.astype(np.int64)
    if (idx < 0).any() or (idx >= num_classes).any():
        raise ValueError(f"label out of range [0,{num_classes})")
    return np.eye(num_classes, dtype=np.float32)[idx]


def _pad_sequences(steps: List[np.ndarray], T: int, align_end: bool):
    """Variable-length (T_i, dim) matrices → ((n, T, dim), (n, T) mask),
    occupying leading steps (trailing mask) or trailing steps under
    ALIGN_END."""
    n = len(steps)
    arr = np.zeros((n, T, steps[0].shape[1]), np.float32)
    mask = np.zeros((n, T), np.float32)
    for i, s in enumerate(steps):
        off = T - s.shape[0] if align_end else 0
        arr[i, off:off + s.shape[0]] = s
        mask[i, off:off + s.shape[0]] = 1.0
    return arr, mask


class RecordReaderDataSetIterator(DataSetIterator):
    """Records → minibatch DataSets (reference
    ``RecordReaderDataSetIterator.java``).

    ``label_index``: column holding the label (-1 = no labels, features
    only — labels mirror features like the reference's unsupervised path).
    ``num_possible_labels`` one-hots an integer class column;
    ``regression=True`` keeps label columns as real values, with
    ``label_index_to`` for multi-column regression targets (reference
    labelIndexTo).  ``max_num_batches`` truncates the pass.
    """

    def __init__(self, record_reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False, label_index_to: int = -1,
                 max_num_batches: int = -1):
        self.reader = record_reader
        self._batch = batch_size
        self.label_index = label_index
        self.label_index_to = (label_index_to if label_index_to >= 0
                               else label_index)
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.max_num_batches = max_num_batches
        self._batch_num = 0
        if not regression and label_index >= 0 and num_possible_labels <= 0:
            raise ValueError("classification needs num_possible_labels")

    def batch(self) -> int:
        return self._batch

    def reset(self) -> None:
        self.reader.reset()
        self._batch_num = 0

    def __next__(self) -> DataSet:
        if (self.max_num_batches >= 0
                and self._batch_num >= self.max_num_batches):
            raise StopIteration
        rows: List[Record] = []
        while self.reader.has_next() and len(rows) < self._batch:
            rows.append(self.reader.next_record())
        if not rows:
            raise StopIteration
        self._batch_num += 1
        mat = np.asarray(rows, dtype=np.float32)
        if self.label_index < 0:
            return self._pre(DataSet(mat, mat))
        li, lt = self.label_index, self.label_index_to
        feat = np.concatenate([mat[:, :li], mat[:, lt + 1:]], axis=1)
        if self.regression:
            labels = mat[:, li:lt + 1]
        else:
            labels = _one_hot(mat[:, li], self.num_possible_labels)
        return self._pre(DataSet(feat, labels))


class AlignmentMode:
    """Sequence alignment modes (reference
    ``SequenceRecordReaderDataSetIterator.AlignmentMode``)."""
    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Paired feature/label sequence readers → padded+masked time-series
    DataSets (reference ``SequenceRecordReaderDataSetIterator.java``).

    Layout is TPU-native (batch, time, features) — the reference emits
    (batch, features, time); the recurrent tier here scans over axis 1.
    Under ``ALIGN_START`` shorter sequences occupy leading steps with a
    trailing mask; under ``ALIGN_END`` they occupy trailing steps —
    i.e. labels at the final step stay aligned for seq-classification.
    """

    def __init__(self, features_reader: SequenceRecordReader,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 mini_batch_size: int = 10,
                 num_possible_labels: int = -1,
                 regression: bool = False,
                 alignment_mode: str = AlignmentMode.EQUAL_LENGTH,
                 label_index: int = -1):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self._batch = mini_batch_size
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.alignment_mode = alignment_mode
        self.label_index = label_index  # single-reader mode
        if labels_reader is None and label_index < 0:
            raise ValueError("need a labels reader or a label_index")

    def batch(self) -> int:
        return self._batch

    def reset(self) -> None:
        self.features_reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def _label_steps(self, seq: List[Record]) -> np.ndarray:
        arr = np.asarray(seq, dtype=np.float32)
        if self.regression:
            return arr
        if arr.shape[1] != 1:
            raise ValueError("classification label records must have one "
                             "column")
        return _one_hot(arr[:, 0], self.num_possible_labels)

    def __next__(self) -> DataSet:
        fseqs, lseqs = [], []
        while (self.features_reader.has_next()
               and len(fseqs) < self._batch):
            fs = self.features_reader.next_sequence()
            if self.labels_reader is not None:
                ls = self.labels_reader.next_sequence()
            else:
                li = self.label_index
                ls = [[r[li]] for r in fs]
                fs = [r[:li] + r[li + 1:] for r in fs]
            fseqs.append(np.asarray(fs, dtype=np.float32))
            lseqs.append(self._label_steps(ls))
        if not fseqs:
            raise StopIteration
        n = len(fseqs)
        flens = [s.shape[0] for s in fseqs]
        llens = [s.shape[0] for s in lseqs]
        if self.alignment_mode == AlignmentMode.EQUAL_LENGTH:
            if len(set(flens)) > 1 or flens != llens:
                raise ValueError(
                    "EQUAL_LENGTH alignment requires equal sequence "
                    f"lengths, got features {flens} labels {llens}")
        T = max(max(flens), max(llens))
        align_end = self.alignment_mode == AlignmentMode.ALIGN_END
        feats, fmask = _pad_sequences(fseqs, T, align_end)
        labels, lmask = _pad_sequences(lseqs, T, align_end)
        if self.alignment_mode == AlignmentMode.EQUAL_LENGTH:
            return self._pre(DataSet(feats, labels))
        return self._pre(DataSet(feats, labels, fmask, lmask))


# ----------------------------------------- multi-reader → MultiDataSet

class _SubsetDetails:
    """One input/output spec (reference
    ``RecordReaderMultiDataSetIterator.SubsetDetails``): the whole reader,
    a [first, last]-inclusive column subset, or a one-hot column."""

    def __init__(self, reader_name: str, entire: bool, one_hot: bool,
                 num_classes: int, col_first: int, col_last: int):
        self.reader_name = reader_name
        self.entire = entire
        self.one_hot = one_hot
        self.num_classes = num_classes
        self.col_first = col_first
        self.col_last = col_last

    def convert(self, mat: np.ndarray) -> np.ndarray:
        """(n, columns) record matrix → (n, dim) array for this subset."""
        if self.entire:
            return mat.astype(np.float32)
        if self.one_hot:
            return _one_hot(mat[:, self.col_first], self.num_classes)
        return mat[:, self.col_first:self.col_last + 1].astype(np.float32)


class RecordReaderMultiDataSetIterator:
    """Multiple named Record/SequenceRecordReaders → MultiDataSet batches
    (reference ``datasets/datavec/RecordReaderMultiDataSetIterator.java``:
    builder at ``:504-620``, per-subset conversion at ``:253-311``).

    Inputs and outputs are column subsets of any registered reader, so one
    CSV can feed several graph inputs and several one-hot outputs at once.
    Sequence readers emit (batch, time, dim) padded arrays with per-subset
    masks under ``ALIGN_START`` / ``ALIGN_END``; record readers emit
    (batch, dim) with no mask.  Built for ``ComputationGraph.fit``.
    """

    class Builder:
        def __init__(self, batch_size: int):
            if batch_size <= 0:
                raise ValueError("batch size must be positive")
            self._batch = batch_size
            self._readers = {}
            self._seq_readers = {}
            self._inputs: List[_SubsetDetails] = []
            self._outputs: List[_SubsetDetails] = []
            self._alignment = AlignmentMode.EQUAL_LENGTH

        def add_reader(self, name: str, reader: RecordReader):
            self._readers[name] = reader
            return self

        def add_sequence_reader(self, name: str, reader: SequenceRecordReader):
            self._seq_readers[name] = reader
            return self

        def sequence_alignment_mode(self, mode: str):
            valid = (AlignmentMode.EQUAL_LENGTH, AlignmentMode.ALIGN_START,
                     AlignmentMode.ALIGN_END)
            if mode not in valid:
                raise ValueError(f"unknown alignment mode {mode!r}; "
                                 f"use one of {valid}")
            self._alignment = mode
            return self

        @staticmethod
        def _subset(name, column_first, column_last):
            if column_first < 0:
                if column_last >= 0:
                    raise ValueError(
                        f"column_last={column_last} given without "
                        f"column_first for reader {name!r}")
                return _SubsetDetails(name, True, False, -1, -1, -1)
            if column_last < 0:
                column_last = column_first      # single-column subset
            if column_last < column_first:
                raise ValueError(
                    f"column_last {column_last} < column_first "
                    f"{column_first} for reader {name!r}")
            return _SubsetDetails(name, False, False, -1, column_first,
                                  column_last)

        def add_input(self, name: str, column_first: int = -1,
                      column_last: int = -1):
            self._inputs.append(self._subset(name, column_first, column_last))
            return self

        def add_input_one_hot(self, name: str, column: int, num_classes: int):
            self._inputs.append(_SubsetDetails(
                name, False, True, num_classes, column, -1))
            return self

        def add_output(self, name: str, column_first: int = -1,
                       column_last: int = -1):
            self._outputs.append(self._subset(name, column_first,
                                              column_last))
            return self

        def add_output_one_hot(self, name: str, column: int,
                               num_classes: int):
            self._outputs.append(_SubsetDetails(
                name, False, True, num_classes, column, -1))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self._readers and not self._seq_readers:
                raise ValueError("no readers registered")
            if not self._inputs and not self._outputs:
                raise ValueError("no inputs/outputs registered")
            dup = set(self._readers) & set(self._seq_readers)
            if dup:
                raise ValueError(
                    f"names registered as both record and sequence "
                    f"readers: {sorted(dup)}")
            known = set(self._readers) | set(self._seq_readers)
            for d in self._inputs + self._outputs:
                if d.reader_name not in known:
                    raise ValueError(
                        f"subset references unknown reader "
                        f"{d.reader_name!r}; registered: {sorted(known)}")
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self._batch = builder._batch
        self._readers = dict(builder._readers)
        self._seq_readers = dict(builder._seq_readers)
        self._inputs = list(builder._inputs)
        self._outputs = list(builder._outputs)
        self._alignment = builder._alignment
        self._preprocessor = None

    # reference MultiDataSetIterator.setPreProcessor
    def set_preprocessor(self, preprocessor) -> None:
        self._preprocessor = preprocessor

    def batch(self) -> int:
        return self._batch

    def reset(self) -> None:
        for r in self._readers.values():
            r.reset()
        for r in self._seq_readers.values():
            r.reset()

    def __iter__(self):
        self.reset()
        return self

    def _next_values(self):
        """Pull up to batch_size examples from every reader; truncate all
        to the minimum count so examples stay row-aligned (reference
        ``minExamples`` logic at ``next(int):...``)."""
        recs = {}
        for name, r in self._readers.items():
            rows = []
            while r.has_next() and len(rows) < self._batch:
                rows.append(r.next_record())
            recs[name] = rows
        seqs = {}
        for name, r in self._seq_readers.items():
            ss = []
            while r.has_next() and len(ss) < self._batch:
                ss.append(r.next_sequence())
            seqs[name] = ss
        counts = [len(v) for v in recs.values()] + \
                 [len(v) for v in seqs.values()]
        n = min(counts)
        if n == 0:
            raise StopIteration
        return ({k: v[:n] for k, v in recs.items()},
                {k: v[:n] for k, v in seqs.items()}, n)

    def _convert_seq(self, details: _SubsetDetails, seq_mats):
        """Per-sequence (T_i, columns) matrices → ((n, T, dim), mask).

        The mask is always an array (all-ones when every sequence is full
        length) so the MultiDataSet pytree structure — and therefore the
        jitted train step's signature — is identical across batches.
        """
        steps = [details.convert(mat) for mat in seq_mats]
        lens = [s.shape[0] for s in steps]
        T = max(lens)
        if self._alignment == AlignmentMode.EQUAL_LENGTH \
                and len(set(lens)) > 1:
            raise ValueError(
                f"EQUAL_LENGTH alignment requires equal sequence lengths, "
                f"got {lens} from reader {details.reader_name!r}")
        return _pad_sequences(
            steps, T, self._alignment == AlignmentMode.ALIGN_END)

    def __next__(self) -> MultiDataSet:
        recs, seqs, n = self._next_values()
        rec_mats = {k: np.asarray(v, dtype=np.float32)
                    for k, v in recs.items()}
        seq_mats = {k: [np.asarray(s, dtype=np.float32) for s in v]
                    for k, v in seqs.items()}

        def convert(details: _SubsetDetails):
            if details.reader_name in rec_mats:
                return details.convert(rec_mats[details.reader_name]), None
            return self._convert_seq(details, seq_mats[details.reader_name])

        feats, fmasks = zip(*[convert(d) for d in self._inputs]) \
            if self._inputs else ((), ())
        labels, lmasks = zip(*[convert(d) for d in self._outputs]) \
            if self._outputs else ((), ())
        # Mask presence depends only on static config (alignment mode +
        # whether any subset reads a sequence reader), never on this
        # batch's lengths — a flipping pytree structure would retrigger
        # jit compilation of the train step every time it changed.
        emit = (self._alignment != AlignmentMode.EQUAL_LENGTH
                and any(d.reader_name in self._seq_readers
                        for d in self._inputs + self._outputs))
        mds = MultiDataSet(
            features=list(feats), labels=list(labels),
            features_masks=list(fmasks) if emit else None,
            labels_masks=list(lmasks) if emit else None)
        if self._preprocessor is not None:
            self._preprocessor.preprocess(mds)
        return mds
