"""DataSet normalizers (fit/transform/revert preprocessors).

TPU-native equivalents of the ND4J normalizer API the reference trains
through (SURVEY.md §2.10 consumed surface): ``NormalizerStandardize``
(zero-mean/unit-variance per feature), ``NormalizerMinMaxScaler``
(range scaling), ``ImagePreProcessingScaler`` (pixel 0..255 → [a,b]) and
the ``VGG16ImagePreProcessor`` mean-subtraction living in
``keras/trained_models.py``.  All implement the ``DataSetPreProcessor``
shape (``preprocess(ds)`` mutating the batch) so they plug into
``DataSetIterator.set_preprocessor`` exactly like the reference's
``iterator.setPreProcessor(normalizer)`` path, and support ``save``/
``load`` round-trips (reference ``NormalizerSerializer``).

Statistics accumulate in one streaming pass over an iterator (per-batch
vectorised sums, not per-example), over all non-feature axes — so 2-D
(batch, features), image (batch, H, W, C... treated as flat features) and
time-series (batch, time, features) inputs all normalise per feature, with
``features_mask`` respected for padded time steps.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """Affine decode spec for a uint8 feature buffer shipped over the
    host->device wire: ``f32 = float32(u8) / denom * mult + add``.

    The op ORDER and dtypes are the contract — every host producer in
    ``datasets/`` computes its float32 features with exactly this
    expression (IEEE round-to-nearest-even at each step), so the fused
    on-device decode in the train step reproduces the host float32 path
    bit-for-bit and the bf16 compute cast that follows sees identical
    inputs on both wires.  Instances cover:

    - readers' ``u8 / 255`` pixel scaling: ``WireFormat(denom=255.0)``;
    - ``ImagePreProcessingScaler`` (``x / max_pixel * (b - a) + a``):
      ``WireFormat(denom=max_pixel, mult=b - a, add=a)``;
    - raw integer payloads (binarized {0,1} pixels): the identity
      default — dividing by 1.0, multiplying by 1.0 and adding 0.0 are
      all exact for the non-negative values a u8 cast produces, so the
      three ops are applied unconditionally on device (no data-dependent
      program shape).
    """

    denom: float = 1.0
    mult: float = 1.0
    add: float = 0.0

    def decode_host(self, u8: np.ndarray) -> np.ndarray:
        """Host (numpy) twin of the on-device decode — same expression,
        same f32 rounding at each op."""
        x = np.asarray(u8, np.float32)
        return x / np.float32(self.denom) * np.float32(self.mult) \
            + np.float32(self.add)

    def as_tuple(self):
        return (self.denom, self.mult, self.add)


#: The readers' canonical pixel format: features = u8 / 255.
U8_PIXEL = WireFormat(denom=255.0)


def wire_format_of(normalizer) -> Optional[WireFormat]:
    """WireFormat replicating ``normalizer.transform`` on u8 input, or
    None when the transform is not an affine-on-u8 (only the stateless
    :class:`ImagePreProcessingScaler` qualifies — statistics-bearing
    normalizers depend on fitted per-feature arrays)."""
    if isinstance(normalizer, ImagePreProcessingScaler):
        return WireFormat(denom=normalizer.max_pixel,
                          mult=normalizer.b - normalizer.a,
                          add=normalizer.a)
    return None


def _moments_axes(features: np.ndarray) -> tuple:
    """Axes to reduce: everything except the trailing feature axis for
    rank>=3 (time-series / images keep per-channel stats), or axis 0 for
    2-D design matrices."""
    if features.ndim <= 2:
        return (0,)
    return tuple(range(features.ndim - 1))


class AbstractNormalizer:
    """Shared fit/apply plumbing; subclasses define the statistics."""

    def __init__(self, fit_label: bool = False):
        self._fit_label = fit_label
        self.fitted = False

    def fit_label(self, fit: bool) -> None:
        self._fit_label = fit

    # -- fitting -----------------------------------------------------------

    def fit(self, data) -> "AbstractNormalizer":
        """Fit from a DataSet or a DataSetIterator (one streaming pass)."""
        self._begin()
        if hasattr(data, "reset"):
            data.reset()
            for ds in data:
                self._accumulate(ds)
            data.reset()
        else:
            self._accumulate(data)
        self._finish()
        self.fitted = True
        return self

    def _begin(self) -> None:
        raise NotImplementedError

    def _accumulate(self, ds) -> None:
        raise NotImplementedError

    def _finish(self) -> None:
        raise NotImplementedError

    # -- application -------------------------------------------------------

    def transform(self, features: np.ndarray,
                  mask: Optional[np.ndarray] = None) -> np.ndarray:
        raise NotImplementedError

    def revert_features(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_labels(self, labels: np.ndarray) -> np.ndarray:
        raise NotImplementedError("label stats not fitted")

    def revert_labels(self, labels: np.ndarray) -> np.ndarray:
        raise NotImplementedError("label stats not fitted")

    def preprocess(self, ds) -> None:
        """DataSetPreProcessor entry: mutate the batch in place."""
        if not self.fitted:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        ds.features = self.transform(np.asarray(ds.features),
                                     None if ds.features_mask is None
                                     else np.asarray(ds.features_mask))
        if self._fit_label:
            ds.labels = self.transform_labels(np.asarray(ds.labels))

    def revert(self, ds) -> None:
        ds.features = self.revert_features(np.asarray(ds.features))
        if self._fit_label:
            ds.labels = self.revert_labels(np.asarray(ds.labels))

    __call__ = preprocess


class NormalizerStandardize(AbstractNormalizer):
    """Zero-mean / unit-std per feature (ND4J ``NormalizerStandardize``)."""

    def __init__(self, fit_label: bool = False):
        super().__init__(fit_label)
        self.mean = self.std = None
        self.label_mean = self.label_std = None

    def _begin(self) -> None:
        self._sums = {}

    def _acc_one(self, key: str, x: np.ndarray,
                 mask: Optional[np.ndarray]) -> None:
        x = np.asarray(x, np.float64)
        axes = _moments_axes(x)
        if mask is not None and x.ndim >= 3:
            m = np.asarray(mask, np.float64)
            m = m.reshape(m.shape + (1,) * (x.ndim - m.ndim))
            s, sq, n = ((x * m).sum(axes), (x * x * m).sum(axes),
                        float(m.sum()))
        else:
            s, sq = x.sum(axes), (x * x).sum(axes)
            n = float(np.prod([x.shape[a] for a in axes]))
        acc = self._sums.setdefault(key, [0.0, 0.0, 0.0])
        acc[0] += s
        acc[1] += sq
        acc[2] += n

    def _accumulate(self, ds) -> None:
        self._acc_one("f", ds.features, ds.features_mask)
        if self._fit_label:
            self._acc_one("l", ds.labels, ds.labels_mask)

    def _finish(self) -> None:
        def _stats(acc):
            s, sq, n = acc
            mean = s / n
            var = np.maximum(sq / n - mean * mean, 0.0)
            return (mean.astype(np.float32),
                    np.sqrt(var).astype(np.float32))
        self.mean, self.std = _stats(self._sums["f"])
        if self._fit_label:
            self.label_mean, self.label_std = _stats(self._sums["l"])
        del self._sums

    def transform(self, features, mask=None):
        out = (np.asarray(features, np.float32) - self.mean) / \
            np.maximum(self.std, 1e-8)
        if mask is not None and out.ndim >= 3:
            m = np.asarray(mask, np.float32)
            out = out * m.reshape(m.shape + (1,) * (out.ndim - m.ndim))
        return out

    def revert_features(self, features):
        return np.asarray(features, np.float32) * \
            np.maximum(self.std, 1e-8) + self.mean

    def transform_labels(self, labels):
        return (np.asarray(labels, np.float32) - self.label_mean) / \
            np.maximum(self.label_std, 1e-8)

    def revert_labels(self, labels):
        return np.asarray(labels, np.float32) * \
            np.maximum(self.label_std, 1e-8) + self.label_mean

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            np.savez(f, kind="standardize", mean=self.mean, std=self.std,
                 fit_label=self._fit_label,
                 label_mean=(self.label_mean if self.label_mean is not None
                             else np.zeros(0)),
                 label_std=(self.label_std if self.label_std is not None
                            else np.zeros(0)))


class NormalizerMinMaxScaler(AbstractNormalizer):
    """Scale each feature to ``[min_range, max_range]`` (ND4J
    ``NormalizerMinMaxScaler``)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 fit_label: bool = False):
        super().__init__(fit_label)
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.min = self.max = None
        self.label_min = self.label_max = None

    def _begin(self) -> None:
        self._stats = {}

    def _acc_one(self, key: str, x: np.ndarray,
                 mask: Optional[np.ndarray]) -> None:
        x = np.asarray(x, np.float64)
        axes = _moments_axes(x)
        if mask is not None and x.ndim >= 3:
            m = np.asarray(mask, np.float64)
            m = m.reshape(m.shape + (1,) * (x.ndim - m.ndim)) > 0
            lo = np.where(m, x, np.inf).min(axes)
            hi = np.where(m, x, -np.inf).max(axes)
        else:
            lo, hi = x.min(axes), x.max(axes)
        if key in self._stats:
            plo, phi = self._stats[key]
            self._stats[key] = (np.minimum(plo, lo), np.maximum(phi, hi))
        else:
            self._stats[key] = (lo, hi)

    def _accumulate(self, ds) -> None:
        self._acc_one("f", ds.features, ds.features_mask)
        if self._fit_label:
            self._acc_one("l", ds.labels, ds.labels_mask)

    def _finish(self) -> None:
        self.min, self.max = [a.astype(np.float32)
                              for a in self._stats["f"]]
        if self._fit_label:
            self.label_min, self.label_max = [
                a.astype(np.float32) for a in self._stats["l"]]
        del self._stats

    def _scale(self, x, lo, hi):
        span = np.maximum(hi - lo, 1e-8)
        unit = (np.asarray(x, np.float32) - lo) / span
        return unit * (self.max_range - self.min_range) + self.min_range

    def _unscale(self, x, lo, hi):
        span = np.maximum(hi - lo, 1e-8)
        unit = (np.asarray(x, np.float32) - self.min_range) / \
            (self.max_range - self.min_range)
        return unit * span + lo

    def transform(self, features, mask=None):
        out = self._scale(features, self.min, self.max)
        if mask is not None and out.ndim >= 3:
            m = np.asarray(mask, np.float32)
            out = out * m.reshape(m.shape + (1,) * (out.ndim - m.ndim))
        return out

    def revert_features(self, features):
        return self._unscale(features, self.min, self.max)

    def transform_labels(self, labels):
        return self._scale(labels, self.label_min, self.label_max)

    def revert_labels(self, labels):
        return self._unscale(labels, self.label_min, self.label_max)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            np.savez(f, kind="minmax", min=self.min, max=self.max,
                 min_range=self.min_range, max_range=self.max_range,
                 fit_label=self._fit_label,
                 label_min=(self.label_min if self.label_min is not None
                            else np.zeros(0)),
                 label_max=(self.label_max if self.label_max is not None
                            else np.zeros(0)))


class ImagePreProcessingScaler(AbstractNormalizer):
    """Pixel scaler: uint8 0..255 → ``[a, b]`` (ND4J
    ``ImagePreProcessingScaler``).  Stateless — no fit required."""

    def __init__(self, a: float = 0.0, b: float = 1.0, max_bits: int = 8):
        super().__init__(fit_label=False)
        self.a = float(a)
        self.b = float(b)
        self.max_pixel = float(2 ** max_bits - 1)
        self.fitted = True

    def fit(self, data) -> "ImagePreProcessingScaler":
        return self

    def transform(self, features, mask=None):
        x = np.asarray(features, np.float32) / self.max_pixel
        return x * (self.b - self.a) + self.a

    def revert_features(self, features):
        x = (np.asarray(features, np.float32) - self.a) / (self.b - self.a)
        return x * self.max_pixel

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            np.savez(f, kind="image", a=self.a, b=self.b,
                     max_pixel=self.max_pixel)


def load_normalizer(path: str) -> AbstractNormalizer:
    """Restore a saved normalizer (reference ``NormalizerSerializer``)."""
    z = np.load(path, allow_pickle=False)
    kind = str(z["kind"])
    if kind == "standardize":
        n = NormalizerStandardize(fit_label=bool(z["fit_label"]))
        n.mean, n.std = z["mean"], z["std"]
        if n._fit_label:
            n.label_mean, n.label_std = z["label_mean"], z["label_std"]
    elif kind == "minmax":
        n = NormalizerMinMaxScaler(float(z["min_range"]),
                                   float(z["max_range"]),
                                   fit_label=bool(z["fit_label"]))
        n.min, n.max = z["min"], z["max"]
        if n._fit_label:
            n.label_min, n.label_max = z["label_min"], z["label_max"]
    elif kind == "image":
        n = ImagePreProcessingScaler(float(z["a"]), float(z["b"]))
        n.max_pixel = float(z["max_pixel"])
    else:
        raise ValueError(f"unknown normalizer kind {kind!r}")
    n.fitted = True
    return n
