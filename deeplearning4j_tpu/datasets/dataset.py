"""DataSet / MultiDataSet batch containers.

TPU-native equivalent of ND4J's ``DataSet`` / ``MultiDataSet`` (consumed
throughout the reference — SURVEY.md §2.10).  A batch is a pytree of device
arrays (features, labels, optional masks), so it can be donated into the
jitted train step and sharded with ``jax.sharding`` without conversion.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

Array = jax.Array


@dataclasses.dataclass
class DataSet:
    """One minibatch: features (batch, ...), one-hot/regression labels
    (batch, ...), optional per-timestep masks (batch, time)."""

    features: np.ndarray | Array
    labels: np.ndarray | Array
    features_mask: Optional[np.ndarray | Array] = None
    labels_mask: Optional[np.ndarray | Array] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def as_tuple(self):
        return (self.features, self.labels, self.features_mask,
                self.labels_mask)

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        def _slice(a, sl):
            return None if a is None else a[sl]
        tr = DataSet(*[_slice(a, slice(0, n_train)) for a in self.as_tuple()])
        te = DataSet(*[_slice(a, slice(n_train, None)) for a in self.as_tuple()])
        return tr, te

    def shuffle(self, seed: int = 0) -> "DataSet":
        perm = np.random.RandomState(seed).permutation(self.num_examples())
        def _take(a):
            return None if a is None else np.asarray(a)[perm]
        return DataSet(*[_take(a) for a in self.as_tuple()])

    def batch_by(self, batch_size: int):
        n = self.num_examples()
        for start in range(0, n, batch_size):
            sl = slice(start, min(start + batch_size, n))
            yield DataSet(*[None if a is None else a[sl]
                            for a in self.as_tuple()])


def attach_wire(ds: "DataSet", u8: np.ndarray, fmt) -> "DataSet":
    """Attach a uint8 wire twin to ``ds``: ``u8`` holds the same examples
    as ``ds.features`` in uint8, ``fmt`` is the
    :class:`~..normalizers.WireFormat` whose decode reproduces
    ``ds.features`` bit-exactly.  Carried as an instance attribute (not a
    dataclass field) so every existing (features, labels, masks) consumer
    is untouched; ``dataclasses.replace`` copies — e.g. the preprocessor
    path — deliberately DROP it, since a preprocessed batch no longer
    matches the wire decode."""
    ds._wire = (np.asarray(u8), fmt)
    return ds


def wire_of(ds) -> Optional[Tuple[np.ndarray, object]]:
    """The (uint8 buffer, WireFormat) twin attached by a reader, or
    None."""
    return getattr(ds, "_wire", None)


def wire_enabled() -> bool:
    """Whether the uint8 wire may be used for host→device staging.
    ``DL4J_TPU_WIRE_UINT8=0`` forces the float32 wire everywhere — the
    escape hatch (and the control arm of the parity tests).  Read at
    each staging decision, not cached, so tests can flip it."""
    return os.environ.get("DL4J_TPU_WIRE_UINT8", "1") != "0"


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output batch (reference ``MultiDataSet`` consumed by
    ``ComputationGraph.fit`` — SURVEY.md §3.2)."""

    features: Sequence[np.ndarray | Array]
    labels: Sequence[np.ndarray | Array]
    features_masks: Optional[Sequence[Optional[np.ndarray | Array]]] = None
    labels_masks: Optional[Sequence[Optional[np.ndarray | Array]]] = None

    def num_examples(self) -> int:
        arrs = self.features if len(self.features) else self.labels
        return int(arrs[0].shape[0])
