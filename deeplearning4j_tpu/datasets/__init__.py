"""Data tier: DataSet containers, canned datasets, record-reader ETL,
normalizers, async prefetch iterators.

Reference modules: ``deeplearning4j-core/src/main/java/org/deeplearning4j/
datasets/`` (fetchers + iterator impls + the DataVec bridge) and the ND4J
DataSet/normalizer API surface (SURVEY.md §2.2, §2.10).
"""

from .cifar import CifarDataSetIterator, cifar_arrays
from .dataset import DataSet, MultiDataSet
from .iris import IrisDataSetIterator
from .iterators import (AsyncDataSetIterator, DataSetIterator,
                        ExistingDataSetIterator, ListDataSetIterator,
                        MultipleEpochsIterator)
from .mnist import MnistDataSetIterator, mnist_arrays
from .normalizers import (ImagePreProcessingScaler, NormalizerMinMaxScaler,
                          NormalizerStandardize, load_normalizer)
from .records import (AlignmentMode, CollectionRecordReader,
                      CollectionSequenceRecordReader, CSVRecordReader,
                      CSVSequenceRecordReader, RecordReader,
                      RecordReaderDataSetIterator,
                      RecordReaderMultiDataSetIterator, SequenceRecordReader,
                      SequenceRecordReaderDataSetIterator)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ExistingDataSetIterator", "MultipleEpochsIterator",
    "AsyncDataSetIterator", "MnistDataSetIterator", "mnist_arrays",
    "IrisDataSetIterator", "CifarDataSetIterator", "cifar_arrays",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler", "load_normalizer", "RecordReader",
    "CollectionRecordReader", "CSVRecordReader", "SequenceRecordReader",
    "CollectionSequenceRecordReader", "CSVSequenceRecordReader",
    "RecordReaderDataSetIterator", "RecordReaderMultiDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
    "AlignmentMode",
]
