"""DataSet iterators: contracts + async prefetch.

TPU-native equivalents of the reference's
``datasets/iterator/AsyncDataSetIterator.java`` (background prefetch thread,
queue of 2 — used by ``MultiLayerNetwork.fit:980``),
``IteratorDataSetIterator``, ``ExistingDataSetIterator``,
``MultipleEpochsIterator`` and the ``DataSetIterator`` contract consumed
everywhere (SURVEY.md §2.10).

The iterator protocol is Python's: ``__iter__``/``__next__`` plus DL4J-style
``reset()``/``batch()``/``total_examples()``.  Host-side prefetch overlaps
numpy batch assembly with device execution — the same pipelining the
reference gets from its AsyncDataSetIterator, with the device transfer
handled by JAX's async dispatch.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np

from .dataset import DataSet, attach_wire, wire_enabled, wire_of


class DataSetIterator:
    """Base contract (reference ``DataSetIterator``, incl. its
    ``setPreProcessor`` — a ``DataSetPreProcessor`` applied to every batch
    the iterator emits)."""

    _preprocessor = None

    def set_preprocessor(self, preprocessor) -> None:
        self._preprocessor = preprocessor

    def get_preprocessor(self):
        return self._preprocessor

    def _pre(self, ds: DataSet) -> DataSet:
        if self._preprocessor is not None:
            # Preprocess a shallow copy: the source DataSet may be re-yielded
            # on reset/replay (Existing/MultipleEpochs), and preprocessing
            # the caller's object twice would double-normalize it.
            ds = dataclasses.replace(ds)
            self._preprocessor.preprocess(ds)
        return ds

    def reset(self) -> None:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Iterate minibatches from an in-memory list of examples (reference
    ``ListDataSetIterator``)."""

    def __init__(self, dataset: DataSet, batch_size: int = 32,
                 shuffle: bool = False, seed: int = 0):
        self._ds = dataset
        self._batch = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._order = np.arange(dataset.num_examples())
        self._pos = 0
        self.reset()

    def reset(self) -> None:
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            self._order = rng.permutation(self._ds.num_examples())
        self._pos = 0
        self._epoch += 1

    def batch(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return self._ds.num_examples()

    def __next__(self) -> DataSet:
        if self._pos >= self._ds.num_examples():
            raise StopIteration
        idx = self._order[self._pos:self._pos + self._batch]
        self._pos += self._batch

        def _take(a):
            return None if a is None else np.asarray(a)[idx]

        batch = DataSet(*[_take(a) for a in self._ds.as_tuple()])
        wire = wire_of(self._ds)
        if wire is not None:
            # slice the uint8 twin with the same rows; a preprocessor (if
            # any) drops it again in _pre, since preprocessed features no
            # longer match the wire decode
            attach_wire(batch, wire[0][idx], wire[1])
        return self._pre(batch)


class ExistingDataSetIterator(DataSetIterator):
    """Wrap a plain iterable of DataSets (reference
    ``ExistingDataSetIterator``)."""

    def __init__(self, source: Iterable[DataSet]):
        self._source = source
        self._it: Optional[Iterator[DataSet]] = None

    def reset(self) -> None:
        self._it = iter(self._source)

    def batch(self) -> int:
        return -1

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        return self._pre(next(self._it))


class MultipleEpochsIterator(DataSetIterator):
    """Replay an underlying iterator N times as one pass (reference
    ``MultipleEpochsIterator``)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self._epochs = epochs
        self._under = underlying
        self._epoch = 0

    def reset(self) -> None:
        self._epoch = 0
        self._under.reset()

    def batch(self) -> int:
        return self._under.batch()

    def __next__(self) -> DataSet:
        try:
            return self._pre(next(self._under))
        except StopIteration:
            self._epoch += 1
            if self._epoch >= self._epochs:
                raise
            self._under.reset()
            return self._pre(next(self._under))


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference
    ``AsyncDataSetIterator``: queue capacity 2, daemon thread).

    When the C++ tier is present (``native/dataloader.cc``) and the
    underlying iterator is a plain shuffled in-memory ``ListDataSetIterator``
    (dense float32, no masks, no preprocessor, batch divides n), the
    prefetch runs on a NATIVE thread via the pthread ring buffer — the
    per-epoch shuffle and batch gather never touch the GIL.  Anything
    else falls back to the Python thread, same contract (the reference's
    reflective-helper-with-fallback posture)."""

    _END = object()

    def __init__(self, underlying: DataSetIterator, queue_size: int = 2,
                 use_native: Optional[bool] = None):
        self._under = underlying
        self._size = queue_size
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._native_pf = None
        self._native_left = 0
        self._ring_epoch = 0
        self._ring_wire = None
        self._use_native_req = True if use_native is None else use_native
        self.native = self._use_native_req and self._native_eligible()

    def _native_eligible(self) -> bool:
        from .native_io import native_module
        if native_module() is None:
            return False
        u = self._under
        # Exact ListDataSetIterator semantics only: a subclass that
        # overrides iteration (per-batch augmentation etc.) must keep the
        # Python path or the override would be silently bypassed.
        if not isinstance(u, ListDataSetIterator):
            return False
        if (type(u).__next__ is not ListDataSetIterator.__next__
                or type(u).reset is not ListDataSetIterator.reset):
            return False
        ds = u._ds
        if (ds.features_mask is not None or ds.labels_mask is not None
                or ds.features is None or ds.labels is None):
            return False
        # float32 only — the ring stores f32, and silently downcasting
        # f64 data would make results depend on whether the lib built
        if (np.asarray(ds.features).dtype != np.float32
                or np.asarray(ds.labels).dtype != np.float32):
            return False
        if not u._shuffle or u.get_preprocessor() is not None:
            return False
        n = ds.num_examples()
        # the ring drops the tail; only take over when there is none
        return n % u._batch == 0

    def _batches_per_epoch(self) -> int:
        return self._under._ds.num_examples() // self._under._batch

    def _native_next(self) -> DataSet:
        from .native_io import native_module
        if self._native_pf is None:
            u = self._under
            feats_src = np.asarray(u._ds.features, np.float32)
            self._ring_wire = None
            wire = wire_of(u._ds)
            if wire is not None and wire_enabled():
                u8, fmt = wire
                flat = np.ascontiguousarray(u8.reshape(u8.shape[0], -1))
                if flat.shape[1] % 4 == 0:
                    # Ship bytes through the float32 ring by viewing each
                    # uint8 row as D/4 "floats": the ring's row gathers
                    # are dtype-blind, so the permuted rows view back to
                    # the exact source bytes.  4x less ring memory, and
                    # the consumer gets the uint8 wire attached for
                    # device-side decode.
                    feats_src = flat.view(np.float32)
                    self._ring_wire = (fmt, u8.shape[1:],
                                       np.asarray(u._ds.features).shape[1:])
            self._native_pf = native_module().NativePrefetcher(
                feats_src,
                np.asarray(u._ds.labels, np.float32),
                batch=u._batch, capacity=max(2, self._size),
                seed=u._seed + self._ring_epoch)
            if self._native_left <= 0:
                self._native_left = self._batches_per_epoch()
        if self._native_left <= 0:
            raise StopIteration
        self._native_left -= 1
        feats, labels = self._native_pf.next()
        if self._ring_wire is not None:
            fmt, u8_shape, f_shape = self._ring_wire
            u8 = feats.view(np.uint8).reshape((feats.shape[0],) + u8_shape)
            batch = DataSet(
                fmt.decode_host(u8).reshape((feats.shape[0],) + f_shape),
                labels)
            return self._pre(attach_wire(batch, u8, fmt))
        return self._pre(DataSet(feats, labels))

    def _worker(self) -> None:
        try:
            for ds in iter(self._under.__next__, None):
                # preprocess on the producer thread so the transform
                # overlaps device execution like the rest of the prefetch
                self._queue.put(self._pre(ds))
        except StopIteration:
            pass
        except BaseException as e:  # surfaced on the consumer thread
            self._error = e
        finally:
            self._queue.put(self._END)

    def _drain_python_worker(self) -> None:
        """Drain the bounded queue so a blocked producer can exit, then
        join it — otherwise switching paths leaks the thread (and its
        reference to the underlying iterator) for the process lifetime.

        Timed gets re-checking ``is_alive``: a plain ``get()`` could
        block forever in the race where the consumer already took the
        ``_END`` sentinel but the producer thread has not yet died."""
        t = self._thread
        if t is not None:
            while t.is_alive():
                try:
                    self._queue.get(timeout=0.05)
                except queue.Empty:
                    pass
            t.join()
        self._thread = None
        self._queue = queue.Queue(maxsize=self._size)
        self._error = None

    def reset(self) -> None:
        # conditions can change between epochs (preprocessor attached,
        # dataset swapped) — re-evaluate which path serves the next epoch
        was_native = self.native
        self.native = self._use_native_req and self._native_eligible()
        if was_native and not self.native:
            self.close()
            self._native_left = 0
        if self.native:
            # a Python-path epoch may have run before this native one:
            # retire its worker thread rather than leaking it
            self._drain_python_worker()
            full = self._batches_per_epoch()
            if self._native_pf is not None and self._native_left not in (
                    0, full):
                # mid-epoch reset: the ring is mid-permutation, so its
                # leftover batches would straddle two permutations —
                # rebuild it so the new epoch is one clean cover
                self.close()
                self._ring_epoch += 1
            self._native_left = full
            return
        self._drain_python_worker()
        self._under.reset()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def batch(self) -> int:
        return self._under.batch()

    def close(self) -> None:
        if self._native_pf is not None:
            self._native_pf.close()
            self._native_pf = None

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if self.native:
            return self._native_next()
        if self._thread is None:
            self.reset()
        item = self._queue.get()
        if item is self._END:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item
