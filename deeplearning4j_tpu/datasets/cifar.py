"""CIFAR-10 dataset iterator.

TPU-native equivalent of the reference's
``datasets/iterator/impl/CifarDataSetIterator.java`` +
``datasets/fetchers/CifarDataFetcher.java`` (binary-batch reader over the
canonical CIFAR-10 layout: each record is 1 label byte + 3072 pixel bytes,
R then G then B plane, 32x32 row-major).

Zero-egress environment, so (like the MNIST fetcher) two modes:

1. Real ``data_batch_*.bin`` / ``test_batch.bin`` files under
   ``~/.deeplearning4j_tpu/cifar10`` (or ``CIFAR_DIR``) are parsed with the
   canonical binary layout.
2. Otherwise a deterministic procedural CIFAR-alike: each of the 10 classes
   renders a distinct color/texture program (oriented gradient + class hue
   + blob pattern) with per-example jitter.  Learnable by the same conv
   stacks that fit real CIFAR, keeping smoke-train tests meaningful.

Features come out NHWC float32 in [0,1] — channels-last is the TPU-native
conv layout (``ops/convolution.py``), where the reference emits NCHW.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator

NUM_CLASSES = 10
HEIGHT = WIDTH = 32
CHANNELS = 3

LABELS = ["airplane", "automobile", "bird", "cat", "deer",
          "dog", "frog", "horse", "ship", "truck"]


def _read_cifar_bin_u8(path: str, max_records: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse one CIFAR-10 binary batch file into raw (NHWC uint8 images,
    int labels): records of ``[label u8][3072 x u8 pixels, planar RGB]``
    (the layout ``CifarDataFetcher`` reads).  Decodes natively
    (dataloader.cc) when the C++ tier is available; the native decoder
    emits [0,1] floats, which round-trip exactly back to the source
    bytes (the per-value relative error of ``u8 * (1/255f) * 255`` is
    ~2^-24, far inside the 0.5 rounding margin)."""
    from .native_io import native_module
    native = native_module()
    if native is not None:
        imgs, labels = native.cifar_decode(path)
        if max_records is not None:
            imgs, labels = imgs[:max_records], labels[:max_records]
        return (np.rint(imgs * 255.0).astype(np.uint8),
                labels.astype(np.int64))
    raw = np.fromfile(path, dtype=np.uint8)
    rec = 1 + CHANNELS * HEIGHT * WIDTH
    n = raw.size // rec
    if max_records is not None:
        n = min(n, max_records)
    raw = raw[:n * rec].reshape(n, rec)
    labels = raw[:, 0].astype(np.int64)
    # planar (C,H,W) -> NHWC
    imgs = np.ascontiguousarray(
        raw[:, 1:].reshape(n, CHANNELS, HEIGHT, WIDTH)
        .transpose(0, 2, 3, 1))
    return imgs, labels


def _read_cifar_bin(path: str, max_records: Optional[int] = None
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """(NHWC float32 [0,1] images, int labels) — the uint8 reader scaled
    by the canonical ``/255`` (``normalizers.U8_PIXEL``)."""
    imgs, labels = _read_cifar_bin_u8(path, max_records)
    return imgs.astype(np.float32) / 255.0, labels


def _load_real(data_dir: str, train: bool,
               num: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [os.path.join(data_dir, n) for n in names]
    paths = [p for p in paths if os.path.exists(p)]
    if not paths:
        return None
    imgs, labels = [], []
    remaining = num
    for p in paths:
        im, lb = _read_cifar_bin(p, remaining)
        imgs.append(im)
        labels.append(lb)
        remaining -= im.shape[0]
        if remaining <= 0:
            break
    x = np.concatenate(imgs)
    y = np.eye(NUM_CLASSES, dtype=np.float32)[np.concatenate(labels)]
    return x, y


# ---------------------------------------------------------------- synthetic

def _render_class(cls: int, rng: np.random.RandomState) -> np.ndarray:
    """One 32x32x3 image whose statistics depend on the class: class hue,
    gradient orientation, and blob count/size vary per class."""
    yy, xx = np.mgrid[0:HEIGHT, 0:WIDTH].astype(np.float32) / 31.0
    angle = cls * (2 * np.pi / NUM_CLASSES) + rng.uniform(-0.25, 0.25)
    grad = np.cos(angle) * xx + np.sin(angle) * yy
    base_hue = np.array([
        0.5 + 0.45 * np.cos(cls * 0.9 + c * 2.1) for c in range(3)],
        np.float32)
    img = grad[..., None] * 0.5 + base_hue * 0.5
    # class-dependent blob pattern
    n_blobs = 2 + cls % 4
    size = 3 + (cls // 2) % 4
    for _ in range(n_blobs):
        cy = rng.randint(0, HEIGHT - size)
        cx = rng.randint(0, WIDTH - size)
        tint = base_hue[::-1] * rng.uniform(0.6, 1.0)
        img[cy:cy + size, cx:cx + size] = \
            0.3 * img[cy:cy + size, cx:cx + size] + 0.7 * tint
    img += rng.uniform(-0.06, 0.06, img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def _generate_synthetic(num: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """(NHWC uint8 images, one-hot labels).  Pixels quantize to uint8 at
    generation — real CIFAR is 8-bit, and the uint8 source is what the
    ingest wire ships at 1 byte/pixel (``nn/ingest.py``)."""
    rng = np.random.RandomState(seed)
    x = np.empty((num, HEIGHT, WIDTH, CHANNELS), np.uint8)
    y = np.zeros((num, NUM_CLASSES), np.float32)
    classes = rng.randint(0, NUM_CLASSES, num)
    for i, c in enumerate(classes):
        x[i] = np.round(_render_class(int(c), rng) * 255.0).astype(np.uint8)
        y[i, c] = 1.0
    return x, y


def cifar_arrays_u8(train: bool = True, num_examples: int = 50000,
                    seed: int = 12) -> Tuple[np.ndarray, np.ndarray]:
    """(NHWC uint8 images, one-hot labels): real binary batches if
    present, else the deterministic procedural set."""
    data_dir = os.environ.get(
        "CIFAR_DIR", os.path.expanduser("~/.deeplearning4j_tpu/cifar10"))
    real = _load_real(data_dir, train, num_examples)
    if real is not None:
        return real
    offset = 0 if train else 7_000_019
    return _generate_synthetic(num_examples, seed + offset)


def cifar_arrays(train: bool = True, num_examples: int = 50000,
                 seed: int = 12) -> Tuple[np.ndarray, np.ndarray]:
    """(NHWC float32 images in [0,1], one-hot labels) — the uint8 source
    scaled by the canonical ``/255`` (``normalizers.U8_PIXEL``)."""
    x, y = cifar_arrays_u8(train, num_examples, seed)
    return x.astype(np.float32) / 255.0, y


class CifarDataSetIterator(ListDataSetIterator):
    """Reference signature ``CifarDataSetIterator(batch, numExamples,
    train)`` (``CifarDataSetIterator.java``).  Emits NHWC [0,1] features;
    pair with ``InputType.convolutional(32, 32, 3)``.  Batches carry a
    uint8 wire twin (``dataset.attach_wire``) for the ingest paths."""

    def __init__(self, batch: int, num_examples: int = 50000,
                 train: bool = True, shuffle: bool = True, seed: int = 12):
        from .dataset import attach_wire
        from .normalizers import U8_PIXEL
        u8, y = cifar_arrays_u8(train, num_examples, seed)
        x = u8.astype(np.float32) / 255.0
        super().__init__(attach_wire(DataSet(x, y), u8, U8_PIXEL),
                         batch, shuffle, seed)
