"""MNIST dataset iterator.

TPU-native equivalent of the reference's
``datasets/iterator/impl/MnistDataSetIterator.java`` +
``datasets/fetchers/MnistDataFetcher.java`` (IDX binary readers in
``datasets/mnist/MnistManager.java``).

The reference downloads the LeCun IDX files and caches them.  This build
environment has zero network egress, so the fetcher works in two modes:

1. If real IDX files exist under ``~/.deeplearning4j_tpu/mnist/`` (or
   ``MNIST_DIR``), they are parsed with the same IDX layout the reference
   reads (magic 2051 images / 2049 labels, big-endian).
2. Otherwise a *deterministic procedural* MNIST-alike is generated: each
   digit class renders from a glyph bitmap, then gets per-example random
   shift, scale jitter, elastic-ish noise and blur — plus three hardness
   sources that give the task a real error floor instead of perfect
   class separability (a sound LeNet lands ~97-99% held-out, in the real
   MNIST band, NOT 100%):

   - *confusable morphs*: a small fraction of examples render an
     interpolation between the class glyph and a visually confusable
     partner's (3<->8, 4<->9, 1<->7, ...); the mix coefficient can cross
     0.5, so the hardest of them genuinely resemble the partner class
     while keeping their own label — irreducible Bayes error by
     construction.
   - *stroke dropout*: per-example pixel dropout on the rendered strokes
     (broken/faint pen lines).
   - *occlusion*: a random blank patch over part of the canvas.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator, ListDataSetIterator

_GLYPHS = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11110", "00001", "00001", "01110", "00001", "00001", "11110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


#: visually confusable partner per class — the pairs real MNIST models
#: actually confuse (3<->8 closed loops, 4<->9 open top, 1<->7 stroke,
#: 5<->6 lower loop, 0<->8 double loop, 2<->3 top curve)
_CONFUSABLE = {0: 8, 1: 7, 2: 3, 3: 8, 4: 9, 5: 6, 6: 5, 7: 1, 8: 3, 9: 4}

#: hardness knobs (calibrated so a sound LeNet lands ~97-99% held-out:
#: the morph share with mix>0.5 is the designed Bayes floor)
_P_CONFUSE = 0.05      # examples rendered as a cross-class morph
_MIX_LO, _MIX_HI = 0.3, 0.7   # morph coefficient range (crosses 0.5)
_P_OCCLUDE = 0.25      # examples with a blank occlusion patch
_MAX_DROPOUT = 0.15    # per-example stroke-pixel dropout rate cap


def _glyph_array(digit: int) -> np.ndarray:
    return np.array([[float(c) for c in row] for row in _GLYPHS[digit]],
                    np.float32)  # (7, 5)


def _render_digit(digit: int, rng: np.random.RandomState) -> np.ndarray:
    """Render one 28x28 grayscale digit with random geometric jitter,
    plus the hardness sources documented in the module docstring
    (confusable morphs, stroke dropout, occlusion)."""
    glyph = _glyph_array(digit)
    if rng.rand() < _P_CONFUSE:
        # cross-class morph: mix can exceed 0.5, at which point the
        # image resembles the PARTNER class more than its own label —
        # these are the irreducibly ambiguous examples
        mix = rng.uniform(_MIX_LO, _MIX_HI)
        glyph = (1.0 - mix) * glyph + mix * _glyph_array(
            _CONFUSABLE[digit])
    # Random target size (thickness/scale jitter) then nearest upsample
    h = rng.randint(16, 22)
    w = rng.randint(10, 16)
    ys = (np.arange(h) * (glyph.shape[0] / h)).astype(int)
    xs = (np.arange(w) * (glyph.shape[1] / w)).astype(int)
    img_small = glyph[np.ix_(ys, xs)].copy()
    # stroke dropout: broken/faint pen lines
    drop = rng.uniform(0.0, _MAX_DROPOUT)
    img_small *= (rng.rand(h, w) >= drop).astype(np.float32)
    img = np.zeros((28, 28), np.float32)
    # Centered with +/-3px jitter, like real MNIST's centered digits
    cy, cx = (28 - h) // 2, (28 - w) // 2
    dy = np.clip(cy + rng.randint(-3, 4), 0, 28 - h)
    dx = np.clip(cx + rng.randint(-3, 4), 0, 28 - w)
    img[dy:dy + h, dx:dx + w] = img_small
    # shear: shift each row by a per-example slant
    slant = rng.uniform(-0.15, 0.15)
    out = np.zeros_like(img)
    for r in range(28):
        shift = int(round(slant * (r - 14)))
        out[r] = np.roll(img[r], shift)
    if rng.rand() < _P_OCCLUDE:
        # blank patch over part of the canvas (pre-blur so edges soften)
        oh, ow = rng.randint(4, 9), rng.randint(4, 9)
        oy = rng.randint(0, 28 - oh + 1)
        ox = rng.randint(0, 28 - ow + 1)
        out[oy:oy + oh, ox:ox + ow] = 0.0
    # box blur for soft pen strokes
    padded = np.pad(out, 1)
    blurred = (padded[:-2, :-2] + padded[:-2, 1:-1] + padded[:-2, 2:] +
               padded[1:-1, :-2] + padded[1:-1, 1:-1] + padded[1:-1, 2:] +
               padded[2:, :-2] + padded[2:, 1:-1] + padded[2:, 2:]) / 9.0
    blurred = np.clip(blurred * 1.8, 0.0, 1.0)
    noise = rng.uniform(0.0, 0.08, blurred.shape).astype(np.float32)
    return np.clip(blurred + noise, 0.0, 1.0)


def _generate_synthetic(num: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """(uint8 images, one-hot labels).  Pixels are quantized to uint8 at
    generation — real MNIST is 8-bit, and a uint8 source is what lets
    the ingest wire ship 1 byte/pixel with the ``/255`` scale fused into
    the device program (``nn/ingest.py``)."""
    rng = np.random.RandomState(seed)
    images = np.empty((num, 784), np.uint8)
    labels = np.zeros((num, 10), np.float32)
    digits = rng.randint(0, 10, num)
    for i, d in enumerate(digits):
        images[i] = np.round(
            _render_digit(int(d), rng).ravel() * 255.0).astype(np.uint8)
        labels[i, d] = 1.0
    return images, labels


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (reference ``MnistDbFile``/``MnistImageFile``
    layout: big-endian magic, dims, raw bytes)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">i", f.read(4))
        if magic == 2051:
            n, rows, cols = struct.unpack(">iii", f.read(12))
            data = np.frombuffer(f.read(n * rows * cols), np.uint8)
            return data.reshape(n, rows * cols)
        if magic == 2049:
            n, = struct.unpack(">i", f.read(4))
            return np.frombuffer(f.read(n), np.uint8)
        raise ValueError(f"Bad IDX magic {magic} in {path}")


def _decode_idx_images(path: str, num: int) -> np.ndarray:
    """(n, rows*cols) uint8 raw pixels: native decoder when the C++ tier
    is available and the file is raw IDX, Python reader otherwise.  Both
    paths emit the identical uint8 payload; the float32 ``/255`` scaling
    happens in ONE place (``mnist_arrays``) so the uint8 ingest wire's
    fused on-device decode is bit-exact against it."""
    from .native_io import native_module
    native = native_module()
    if native is not None and not path.endswith(".gz"):
        dec = native.idx_decode(path, normalize=False)
        return dec[:num].reshape(
            min(num, dec.shape[0]), -1).astype(np.uint8)
    return _read_idx(path)[:num]


def _decode_idx_labels(path: str, num: int) -> np.ndarray:
    from .native_io import native_module
    native = native_module()
    if native is not None and not path.endswith(".gz"):
        return native.idx_decode(path, normalize=False)[:num].astype(np.int64)
    return _read_idx(path)[:num]


def _load_real(data_dir: str, train: bool,
               num: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    stem = "train" if train else "t10k"
    for img_name, lbl_name in (
            (f"{stem}-images-idx3-ubyte", f"{stem}-labels-idx1-ubyte"),
            (f"{stem}-images-idx3-ubyte.gz", f"{stem}-labels-idx1-ubyte.gz")):
        img_path = os.path.join(data_dir, img_name)
        lbl_path = os.path.join(data_dir, lbl_name)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            images = _decode_idx_images(img_path, num)
            raw = _decode_idx_labels(lbl_path, num)
            labels = np.eye(10, dtype=np.float32)[raw]
            return images, labels
    return None


def mnist_arrays_u8(train: bool = True, num_examples: int = 60000,
                    seed: int = 6) -> Tuple[np.ndarray, np.ndarray]:
    """(uint8 images, one-hot labels): real IDX files if present, else
    the deterministic procedural set (see module docstring) — the wire
    form the ingest paths ship at 1 byte/pixel."""
    data_dir = os.environ.get(
        "MNIST_DIR", os.path.expanduser("~/.deeplearning4j_tpu/mnist"))
    real = _load_real(data_dir, train, num_examples)
    if real is not None:
        return real
    offset = 0 if train else 1_000_003
    return _generate_synthetic(num_examples, seed + offset)


def mnist_arrays(train: bool = True, num_examples: int = 60000,
                 seed: int = 6) -> Tuple[np.ndarray, np.ndarray]:
    """Raw (float32 [0,1] features, one-hot labels) arrays — the uint8
    source scaled by the canonical ``/255`` (``normalizers.U8_PIXEL``)."""
    images, labels = mnist_arrays_u8(train, num_examples, seed)
    return images.astype(np.float32) / 255.0, labels


class MnistDataSetIterator(ListDataSetIterator):
    """Reference signature:
    ``MnistDataSetIterator(batch, numExamples, binarize, train, shuffle,
    seed)``.  Features are flat 784-vectors in [0,1] (the reference's
    row-flattened images); pair with ``InputType.convolutionalFlat(28,28,1)``
    for CNNs.  Batches carry a uint8 wire twin (``dataset.attach_wire``)
    so the ingest paths can ship 1 byte/pixel and fuse the ``/255`` into
    the device program."""

    def __init__(self, batch: int, num_examples: int = 60000,
                 binarize: bool = False, train: bool = True,
                 shuffle: bool = True, seed: int = 6):
        from .dataset import attach_wire
        from .normalizers import U8_PIXEL, WireFormat
        u8, labels = mnist_arrays_u8(train, num_examples, seed)
        if binarize:
            # threshold on the scaled value (u8/255 > 0.3 == u8 >= 77);
            # the {0, 1} result is exactly uint8-representable, so the
            # wire format degrades to the identity cast.
            u8 = (u8 >= 77).astype(np.uint8)
            images = u8.astype(np.float32)
            fmt = WireFormat()
        else:
            images = u8.astype(np.float32) / 255.0
            fmt = U8_PIXEL
        super().__init__(attach_wire(DataSet(images, labels), u8, fmt),
                         batch, shuffle, seed)
