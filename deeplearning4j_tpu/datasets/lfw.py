"""LFW (Labeled Faces in the Wild) dataset iterator.

TPU-native equivalent of the reference's
``datasets/iterator/impl/LFWDataSetIterator.java`` +
``datasets/fetchers/LFWDataFetcher.java``: face images organized as one
directory per person, labels = person identity.

Zero-egress environment and no JPEG codec in the stdlib, so (like the
MNIST/CIFAR fetchers) two modes:

1. Real mode: a directory tree ``{root}/{person_name}/*.{pgm,ppm,npy}``
   (convert LFW's jpgs once with any external tool; PGM/PPM parse with
   stdlib, ``.npy`` loads directly).  The ``lfw_subset`` layout the
   reference tests use (one flat dir per person) is the same shape.
2. Procedural mode: a deterministic face-alike generator — each "person"
   is a parameter vector (face ellipse, eye spacing, brow slant, mouth
   curvature, skin tone) rendered with per-photo pose/lighting jitter.
   Identity classification is learnable by the same conv stacks that fit
   real LFW subsets.

Features are NHWC float32 in [0,1] (TPU-native channels-last)."""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator


# ------------------------------------------------------------- real loading
def _read_pnm(path: str) -> np.ndarray:
    """Parse binary PGM (P5) / PPM (P6) into (H, W, C) uint8."""
    with open(path, "rb") as f:
        data = f.read()
    parts: List[bytes] = []
    pos = 0
    while len(parts) < 4 and pos < len(data):
        # skip whitespace/comments
        while pos < len(data) and data[pos:pos + 1].isspace():
            pos += 1
        if data[pos:pos + 1] == b"#":
            while pos < len(data) and data[pos] != 0x0A:
                pos += 1
            continue
        start = pos
        while pos < len(data) and not data[pos:pos + 1].isspace():
            pos += 1
        parts.append(data[start:pos])
    magic, w, h, maxval = (parts[0], int(parts[1]), int(parts[2]),
                           int(parts[3]))
    pos += 1                                    # single whitespace after maxval
    if maxval > 255:
        raise ValueError(f"16-bit PNM unsupported: {path}")
    c = {b"P5": 1, b"P6": 3}.get(magic)
    if c is None:
        raise ValueError(f"Not a binary PGM/PPM: {path}")
    arr = np.frombuffer(data[pos:pos + h * w * c], np.uint8)
    return arr.reshape(h, w, c)


def _load_image(path: str) -> Optional[np.ndarray]:
    ext = os.path.splitext(path)[1].lower()
    if ext in (".pgm", ".ppm"):
        return _read_pnm(path)
    if ext == ".npy":
        arr = np.load(path)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    return None


def _resize_nearest(img: np.ndarray, h: int, w: int) -> np.ndarray:
    ys = (np.arange(h) * (img.shape[0] / h)).astype(int)
    xs = (np.arange(w) * (img.shape[1] / w)).astype(int)
    return img[np.ix_(ys, xs)]


def _load_real(root: str, num: int, shape: Tuple[int, int, int],
               num_labels: Optional[int] = None
               ) -> Optional[Tuple[np.ndarray, np.ndarray, List[str]]]:
    if not os.path.isdir(root):
        return None
    people = sorted(d for d in os.listdir(root)
                    if os.path.isdir(os.path.join(root, d)))
    if not people:
        return None
    if num_labels is not None and len(people) > num_labels:
        # keep the one-hot width consistent with the requested label count
        # (the reference's numLabels subset behavior)
        people = people[:num_labels]
    h, w, c = shape
    feats, labels = [], []
    for pid, person in enumerate(people):
        pdir = os.path.join(root, person)
        for fname in sorted(os.listdir(pdir)):
            img = _load_image(os.path.join(pdir, fname))
            if img is None:
                continue
            img = _resize_nearest(img, h, w)
            if img.shape[2] != c:               # gray<->color adaption
                img = (np.repeat(img, c, axis=2) if img.shape[2] == 1
                       else img.mean(axis=2, keepdims=True))
            feats.append(img.astype(np.float32) / 255.0)
            labels.append(pid)
            if len(feats) >= num:
                break
        if len(feats) >= num:
            break
    if not feats:
        return None
    x = np.stack(feats)
    y = np.eye(len(people), dtype=np.float32)[np.asarray(labels)]
    return x, y, people


# ------------------------------------------------------- procedural faces
def _render_face(person_rng: np.random.RandomState,
                 photo_rng: np.random.RandomState,
                 h: int, w: int) -> np.ndarray:
    """One grayscale face: identity params from ``person_rng`` (stable per
    person), pose/lighting jitter from ``photo_rng``."""
    # identity parameters
    face_ry = person_rng.uniform(0.32, 0.42) * h
    face_rx = person_rng.uniform(0.25, 0.36) * w
    eye_dx = person_rng.uniform(0.13, 0.2) * w
    eye_y = person_rng.uniform(-0.12, -0.04) * h
    eye_r = person_rng.uniform(0.035, 0.06) * min(h, w)
    mouth_w = person_rng.uniform(0.12, 0.22) * w
    mouth_y = person_rng.uniform(0.16, 0.26) * h
    mouth_curve = person_rng.uniform(-0.6, 0.6)
    tone = person_rng.uniform(0.45, 0.8)
    brow = person_rng.uniform(-0.3, 0.3)
    # photo jitter
    cy = h / 2 + photo_rng.uniform(-0.05, 0.05) * h
    cx = w / 2 + photo_rng.uniform(-0.05, 0.05) * w
    light = photo_rng.uniform(0.85, 1.15)
    tilt = photo_rng.uniform(-0.12, 0.12)

    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    # rotate coordinates by tilt around center
    ry = (yy - cy) * np.cos(tilt) - (xx - cx) * np.sin(tilt)
    rx = (yy - cy) * np.sin(tilt) + (xx - cx) * np.cos(tilt)
    img = np.zeros((h, w))
    face = ((ry / face_ry) ** 2 + (rx / face_rx) ** 2) <= 1.0
    img[face] = tone
    for side in (-1, 1):
        eye = ((ry - eye_y) ** 2
               + (rx - side * eye_dx) ** 2) <= eye_r ** 2
        img[eye] = 0.1
        brow_band = (np.abs(ry - (eye_y - 2.2 * eye_r)
                            - brow * (rx - side * eye_dx)) < 1.0) \
            & (np.abs(rx - side * eye_dx) < 1.8 * eye_r)
        img[brow_band & face] = 0.25
    mouth = (np.abs(ry - mouth_y
                    - mouth_curve * ((rx / mouth_w) ** 2) * 4.0) < 1.2) \
        & (np.abs(rx) < mouth_w)
    img[mouth & face] = 0.15
    nose = (np.abs(rx) < 0.02 * w) & (ry > eye_y) & (ry < mouth_y - 0.05 * h)
    img[nose & face] = tone * 0.8
    img = np.clip(img * light
                  + photo_rng.uniform(0, 0.05, img.shape), 0, 1)
    return img.astype(np.float32)


def _generate_synthetic(num: int, num_people: int, seed: int,
                        shape: Tuple[int, int, int],
                        identity_seed: int
                        ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    h, w, c = shape
    rng = np.random.RandomState(seed % (2 ** 31))
    x = np.empty((num, h, w, c), np.float32)
    ids = rng.randint(0, num_people, num)
    for i, pid in enumerate(ids):
        # Identity derives from identity_seed alone so a train iterator
        # and its test split render the SAME people (different photos).
        person_rng = np.random.RandomState(
            (identity_seed * 7919 + int(pid)) % (2 ** 31))
        img = _render_face(person_rng, rng, h, w)
        x[i] = img[:, :, None] if c == 1 else np.repeat(
            img[:, :, None], c, axis=2)
    y = np.eye(num_people, dtype=np.float32)[ids]
    names = [f"person_{i:03d}" for i in range(num_people)]
    return x, y, names


def lfw_arrays(num_examples: int = 1000, num_labels: int = 10,
               image_shape: Tuple[int, int, int] = (40, 40, 1),
               seed: int = 12, identity_seed: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """(features NHWC, one-hot labels, person names): real directory tree
    if present under ``LFW_DIR``/``~/.deeplearning4j_tpu/lfw``, else the
    procedural face-alike set.  ``identity_seed`` (default: ``seed``)
    controls WHO the people are; ``seed`` controls which photos are
    rendered — pass the same identity_seed with different seeds to get a
    train/test split over the same identities."""
    root = os.environ.get(
        "LFW_DIR", os.path.expanduser("~/.deeplearning4j_tpu/lfw"))
    real = _load_real(root, num_examples, image_shape, num_labels)
    if real is not None:
        return real
    return _generate_synthetic(
        num_examples, num_labels, seed, image_shape,
        seed if identity_seed is None else identity_seed)


class LFWDataSetIterator(ListDataSetIterator):
    """Reference signature (``LFWDataSetIterator(batchSize, numExamples,
    imgDim, numLabels, useSubset, train, ...)``), channels-last."""

    def __init__(self, batch: int, num_examples: int = 1000,
                 image_shape: Tuple[int, int, int] = (40, 40, 1),
                 num_labels: int = 10, train: bool = True,
                 shuffle: bool = True, seed: int = 12):
        x, y, self.people = lfw_arrays(
            num_examples, num_labels, image_shape,
            seed + (0 if train else 999_331), identity_seed=seed)
        super().__init__(DataSet(x, y), batch, shuffle, seed)

    def get_labels(self) -> List[str]:
        return self.people
