"""Gateway to the native (C++) data tier.

The reference's ETL bottoms out in native code (JavaCPP-wrapped readers;
SURVEY.md §2.11); here ``native/dataloader.cc`` plays that role.  Product
code asks this module for the native bindings and silently falls back to
the pure-Python readers when the shared library can't build (no g++ /
header) or when ``DL4J_TPU_NATIVE=0`` disables it — the same posture as
the reference's reflective cuDNN-helper load with an ND4J fallback
(``ConvolutionLayer.java:69-76``).
"""

from __future__ import annotations

import os
from typing import Optional

_native = None
_checked = False


def native_module() -> Optional[object]:
    """The ``nativeops`` module with a built+loaded shared library, or
    ``None`` when unavailable/disabled.  Probes once per process."""
    global _native, _checked
    if os.environ.get("DL4J_TPU_NATIVE", "1") == "0":
        return None
    if not _checked:
        _checked = True
        try:
            from .. import nativeops
            nativeops.load_native()
            _native = nativeops
        except Exception:
            _native = None
    return _native


def native_available() -> bool:
    return native_module() is not None
