"""Native inference runtime: serve a trained model through the C++ PJRT
client with zero Python/JAX dispatch on the hot path.

Reference role: the cuDNN helper tier + ND4J native backend.  The
reference's layers reflectively load a C++-backed helper at construction
and keep cuDNN descriptors/algos cached per shape
(``CudnnConvolutionHelper.java:64-140``); inference then runs through
native code with params resident on the device.  The TPU equivalent here:

- the model's jitted forward is lowered ONCE per input shape to StableHLO
  and compiled by ``native/pjrt_shim.cc`` into the C++ executable cache
  (keyed by program hash — shapes/dtypes are embedded in the program);
- parameters and model state upload ONCE into persistent PJRT device
  buffers (``dl4j_pjrt_buffer_from_host``);
- each ``output()`` call stages only the activations host→device and runs
  ``dl4j_pjrt_execute_mixed`` — C++ PJRT execution, no JAX in the loop.

JAX is used only at cold-start as the StableHLO *author* (tracing the
model's forward); all compilation and execution happens in the native
tier, which is exactly the split the reference has between Java graph
definition and C++ kernel execution.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

import jax
import numpy as np

from ..nativeops import PjrtClient


class NativeModelRunner:
    """Run inference for a ``MultiLayerNetwork`` or ``ComputationGraph``
    through the native PJRT client.

    >>> runner = NativeModelRunner(net)          # params upload here
    >>> y = runner.output(x)                     # native execute
    >>> runner.cache_stats()["entries"]          # 1 executable per shape
    """

    def __init__(self, model, client: Optional[PjrtClient] = None,
                 plugin_path: Optional[str] = None, max_shapes: int = 32):
        from .computation_graph import ComputationGraph
        model.init()
        self._model = model
        self._is_graph = isinstance(model, ComputationGraph)
        self._client = client or PjrtClient(plugin_path)
        self._owns_client = client is None
        leaves, self._state_tree = jax.tree.flatten(
            (model.params, model.net_state))
        self._leaf_avals = [jax.ShapeDtypeStruct(np.shape(l),
                                                 np.asarray(l).dtype)
                            for l in leaves]
        # host copies survive paging: free_device_buffers() drops the
        # device residency, ensure_device_buffers() re-uploads these
        self._host_leaves = [np.asarray(l) for l in leaves]
        self._leaf_bytes = int(sum(l.nbytes for l in self._host_leaves))
        self._buf_ids = [self._client.buffer_from_host(l)
                         for l in self._host_leaves]
        # insertion/access-ordered: oldest-used first, so hitting
        # max_shapes evicts exactly the least-recently-used executable
        self._execs: "OrderedDict[Tuple, int]" = OrderedDict()
        self._max_shapes = int(max_shapes)
        self.evictions = 0

    # ------------------------------------------------------------- compile
    def _exec_for(self, avals) -> int:
        """Executable id for one input-shape signature (compiled once;
        the per-shape analogue of cuDNN descriptor/algo caching)."""
        key = tuple((a.shape, str(a.dtype)) for a in avals)
        if key in self._execs:
            self._execs.move_to_end(key)  # LRU touch
            return self._execs[key]

        if self._is_graph:
            def fwd(leaves, *features):
                params, net_state = jax.tree.unflatten(self._state_tree,
                                                       leaves)
                acts, _, _ = self._model._forward(
                    params, net_state, tuple(features), train=False,
                    rng=None, input_masks=None)
                return tuple(acts[o]
                             for o in self._model.conf.network_outputs)
        else:
            def fwd(leaves, *features):
                params, net_state = jax.tree.unflatten(self._state_tree,
                                                       leaves)
                out, _, _ = self._model._forward(
                    params, net_state, features[0], train=False, rng=None,
                    mask=None)
                return out

        # keep_unused: params not used at inference (e.g. pretrain-only
        # state) must STAY as program operands, or the buffer-id ->
        # operand mapping below would shift
        while len(self._execs) >= self._max_shapes:
            # bound executable memory under shape churn (the reference's
            # cuDNN caches are bounded per layer; here per runner) by
            # evicting the least-recently-used entry only — a steady
            # working set of <= max_shapes shapes never recompiles
            _, old_id = self._execs.popitem(last=False)
            self.evictions += 1
            if self._owns_client:
                self._client.cache_evict(old_id)
            else:
                # a SHARED client may hold other runners' executables —
                # only drop this runner's reference (the id stays valid
                # in the shared cache until its owner clears it)
                import warnings
                warnings.warn(
                    "NativeModelRunner hit max_shapes on a shared "
                    "PjrtClient: dropping the LRU executable ref; the "
                    "shared cache retains it until its owner calls "
                    "cache_clear()", RuntimeWarning, stacklevel=2)
        lowered = jax.jit(fwd, keep_unused=True).lower(self._leaf_avals,
                                                       *avals)
        mlir = lowered.as_text()
        exec_id, _ = self._client.compile_cached(mlir)
        self._execs[key] = exec_id
        return exec_id

    # ------------------------------------------------------------- paging
    def resident_bytes(self) -> int:
        """Device bytes currently pinned by this runner's weight/state
        buffers (0 when paged out)."""
        return self._leaf_bytes if self._buf_ids else 0

    def free_device_buffers(self) -> int:
        """Page the weight/state buffers OFF device, keeping executables
        and host copies (the serving registry's evict primitive).
        Returns bytes released; ``output()`` after this re-uploads
        lazily via :meth:`ensure_device_buffers`."""
        freed = self.resident_bytes()
        for b in self._buf_ids:
            try:
                self._client.buffer_free(b)
            except Exception:
                pass
        self._buf_ids = []
        return freed

    def ensure_device_buffers(self) -> None:
        """Re-upload the host weight copies after a page-out (no-op when
        resident).  Executables are keyed by program, not buffer ids, so
        nothing recompiles."""
        if not self._buf_ids:
            self._buf_ids = [self._client.buffer_from_host(l)
                             for l in self._host_leaves]

    # --------------------------------------------------------------- run
    def output(self, *features) -> np.ndarray:
        """Forward pass via native PJRT execution (reference
        ``MultiLayerNetwork.output:1519`` / ``ComputationGraph.output``
        semantics: inference mode, running BN stats, no dropout)."""
        self.ensure_device_buffers()
        feats = [np.ascontiguousarray(f) for f in features]
        avals = [jax.ShapeDtypeStruct(f.shape, f.dtype) for f in feats]
        exec_id = self._exec_for(avals)
        outs = self._client.execute_mixed(exec_id,
                                          [*self._buf_ids, *feats])
        # same return convention as the containers: single array for one
        # output, list for multi-output graphs
        return outs[0] if len(outs) == 1 else outs

    def cache_stats(self) -> dict:
        return self._client.cache_stats()

    # ------------------------------------------------------------ teardown
    def close(self) -> None:
        for b in self._buf_ids:
            try:
                self._client.buffer_free(b)
            except Exception:
                pass
        self._buf_ids = []
        if self._owns_client and self._client is not None:
            self._client.close()
            self._client = None

    def __enter__(self) -> "NativeModelRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
