"""MultiLayerNetwork: sequential network container.

TPU-native equivalent of the reference's
``nn/multilayer/MultiLayerNetwork.java`` (2527 LoC): ``init():384-470``
(flat params + per-layer views), ``fit(DataSetIterator):976``,
``computeGradientAndScore:1805``, ``output:1519-1601``, ``score:1705``.

Architecture: the reference materializes layer objects holding views over one
flat parameter buffer, then drives per-layer ``activate``/``backpropGradient``
loops from a ``Solver``.  Here the entire inner loop — forward, loss,
backward (``jax.grad``), updater — is ONE jitted function, so XLA compiles
the whole train step into a single HLO graph executed on the TPU (the north
star in BASELINE.json).  Params/updater-state are pytrees; ``params()``
exposes the reference's flat-vector invariant via deterministic raveling.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import precision as _precision
from . import updaters as _updaters
from .. import monitor as _monitor
from .conf.neural_net_configuration import MultiLayerConfiguration
from ..datasets.dataset import DataSet

Array = jax.Array


class MultiLayerNetwork:
    """Sequential model: list of layer configs -> pure train/inference fns."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: List[Dict[str, Array]] = []
        self.net_state: List[Dict[str, Array]] = []
        self.updater_state: List[Dict[str, Any]] = []
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._init_done = False
        self._score = float("nan")
        self._rng_key: Optional[jax.Array] = None
        self._rnn_carries = None
        self._rnn_carry_batch = -1
        self._pretrain_step_cache: Dict[int, Any] = {}
        self._pretrain_done = False
        self._tbptt_step_cache: Dict[int, Any] = {}
        self._decode_grow_cache: Dict[int, Any] = {}
        self._precision: Optional[_precision.PrecisionPolicy] = None

    def _pol(self) -> _precision.PrecisionPolicy:
        """The precision policy, resolved once per network instance
        (docs/PERFORMANCE.md) — param storage dtype, compute dtype,
        updater-state dtype, and the fp32-master-weights flag."""
        p = self._precision
        if p is None:
            p = self._precision = _precision.resolve_policy(self.conf.conf)
        return p

    @functools.cached_property
    def _solver(self):
        """Line-search solver when ``optimization_algo`` asks for one
        (reference ``Solver.java``); None selects the jitted SGD path.
        Unknown algorithms raise instead of silently training with SGD."""
        from ..optimize.solvers import SGD, Solver
        algo = (self.conf.conf.optimization_algo or SGD).lower()
        if algo == SGD:
            return None
        if self.conf.backprop_type == "tbptt":
            raise ValueError(
                f"optimization_algo {algo!r} is incompatible with tBPTT; "
                "use stochastic_gradient_descent")
        return Solver(self, algo)

    # ------------------------------------------------------------------ init
    def init(self) -> "MultiLayerNetwork":
        """Initialize params/state (reference ``init():384-470``)."""
        if self._init_done:
            return self
        pol = self._pol()
        _precision.publish(pol)
        dtype = jnp.dtype(pol.param_dtype)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        self._rng_key = key
        keys = jax.random.split(key, len(self.layers) + 1)
        self.params = [
            layer.init_params(keys[i], dtype)
            for i, layer in enumerate(self.layers)
        ]
        self.net_state = [layer.init_state(dtype) for layer in self.layers]
        self.updater_state = [
            _updaters.init_state(
                self._updater_conf(i),
                _updaters.updatable_params(self.layers[i], self.params[i]),
                policy=pol)
            for i in range(len(self.layers))
        ]
        self._init_done = True
        return self

    def _updater_conf(self, i: int) -> _updaters.UpdaterConfig:
        return self.layers[i].updater or self.conf.conf.updater

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, x, *, train: bool,
                 rng: Optional[jax.Array], mask=None, carries=None,
                 to_layer: Optional[int] = None, from_layer: int = 0,
                 preoutput_last: bool = False):
        """Compose preprocessors + layers (reference ``feedForwardToLayer``).

        Returns (out, new_state, new_carries).  ``mask`` is the per-timestep
        features mask (batch, time).  ``carries`` is a per-layer list of
        recurrent carries ((), for non-recurrent layers) used by tBPTT and
        ``rnn_time_step``; None runs every recurrent layer from zero state.
        With ``preoutput_last`` the final (output) layer contributes its
        pre-activation, letting the loss fuse softmax/sigmoid stably.
        ``from_layer`` starts composition mid-stack with ``x`` as that
        layer's input (the suffix path of the exact-tBPTT split).
        """
        from .layers.recurrent import BaseRecurrentLayer
        n = len(self.layers) if to_layer is None else to_layer + 1
        new_state = list(net_state)
        new_carries = list(carries) if carries is not None else [
            () for _ in self.layers]
        keys = (jax.random.split(rng, n) if rng is not None else [None] * n)
        pol = self._pol()
        compute_dtype = jnp.dtype(pol.compute_dtype)
        if jnp.issubdtype(x.dtype, jnp.floating):
            # Cast inputs to the policy compute dtype (bfloat16 for
            # MXU-friendly matmuls under the TPU default); integer inputs
            # (embedding indices) pass through.
            x = x.astype(compute_dtype)
        if compute_dtype != jnp.dtype(pol.param_dtype):
            # Mixed compute: storage params stay in the param dtype; compute
            # sees a bfloat16 copy (XLA fuses the casts into the matmul/conv).
            params = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        for i in range(from_layer, n):
            layer = self.layers[i]
            if i in self.conf.input_preprocessors:
                x = self.conf.input_preprocessors[i](x)
            if preoutput_last and i == n - 1 and hasattr(layer, "pre_output"):
                if layer.dropout and train:
                    x = layer.apply_dropout(x, train, keys[i])
                x = layer.pre_output(params[i], x)
            elif (pol.downcasts_output and i == len(self.layers) - 1
                  and hasattr(layer, "pre_output")
                  and hasattr(layer, "_activate")):
                # fp32 logits contract, head half: the output head's
                # logits are cast to fp32 BEFORE the softmax/sigmoid so
                # serving probabilities are fp32-exact, not bf16-rounded
                # (bf16 softmax row sums wobble at the 1e-3 level).
                # Checked BEFORE the carries branch: a carried step
                # (rnn_step / decode_step) must honor the same contract
                # or N single-token calls drift from output() under
                # mixed precision.  The only recurrent head with
                # pre_output is RnnOutputLayer, whose carry is () — so
                # skipping forward_seq leaves new_carries[i] correct.
                x = layer.apply_dropout(x, train, keys[i])
                x = layer._activate(
                    layer.pre_output(params[i], x).astype(jnp.float32))
            elif (carries is not None
                  and isinstance(layer, BaseRecurrentLayer)):
                x, new_carries[i] = layer.forward_seq(
                    params[i], x, carries[i], train=train, rng=keys[i],
                    mask=mask)
            else:
                x, new_state[i] = layer.forward(
                    params[i], net_state[i], x, train=train, rng=keys[i],
                    mask=mask)
        if pol.downcasts_output:
            # fp32 logits contract: every consumer (loss, softmax, metrics
            # accumulation, serving) sees fp32 even under bf16 storage so
            # Evaluation numbers never drift with the policy.
            x = x.astype(jnp.float32)
        return x, new_state, new_carries

    # ----------------------------------------------------------------- loss
    def _loss_fn(self, params, net_state, features, labels, features_mask,
                 labels_mask, rng, train: bool, carries=None,
                 from_layer: int = 0, per_example: bool = False):
        """Data loss (+ new state, new carries).  Regularization is handled
        updater-side to match the reference order of operations (SURVEY.md §7
        hard part d); the reported score adds the reg term separately
        (``BaseLayer.calcL2``).  ``from_layer`` scores a mid-stack
        activation through the remaining layers (exact-tBPTT suffix).
        ``per_example`` returns the unreduced (batch,) score vector
        (reference ``computeScoreForExamples``)."""
        out_layer = self.layers[-1]
        if getattr(out_layer, "NEEDS_INPUT_FOR_SCORE", False):
            # Center-loss-style heads score against the layer *input* (the
            # penultimate features) as well as the preactivation.
            n = len(self.layers)
            x, new_state, new_carries = self._forward(
                params, net_state, features, train=train, rng=rng,
                mask=features_mask, carries=carries, to_layer=n - 2,
                from_layer=from_layer)
            if (n - 1) in self.conf.input_preprocessors:
                x = self.conf.input_preprocessors[n - 1](x)
            if out_layer.dropout and train:
                x = out_layer.apply_dropout(
                    x, train, jax.random.fold_in(rng, n - 1)
                    if rng is not None else None)
            if per_example:
                data_loss = out_layer.compute_score_examples_with_input(
                    params[n - 1], labels, x, labels_mask)
            else:
                data_loss = out_layer.compute_score_with_input(
                    params[n - 1], labels, x, labels_mask,
                    average=self.conf.conf.mini_batch)
            return data_loss, (new_state, new_carries)
        preout, new_state, new_carries = self._forward(
            params, net_state, features, train=train, rng=rng,
            mask=features_mask, carries=carries, preoutput_last=True,
            from_layer=from_layer)
        if not hasattr(out_layer, "compute_score"):
            raise ValueError(
                "Last layer must be an output/loss layer to fit()")
        lmask = labels_mask
        if lmask is None and features_mask is not None and preout.ndim == 3:
            # Per-timestep output: the features mask doubles as the labels
            # mask (reference feedForwardMaskArray propagation).
            lmask = features_mask
        if per_example:
            data_loss = out_layer.compute_score_examples(labels, preout,
                                                         lmask)
            return data_loss, (new_state, new_carries)
        data_loss = out_layer.compute_score(labels, preout, lmask,
                                            average=self.conf.conf.mini_batch)
        return data_loss, (new_state, new_carries)

    def _reg_score(self, params) -> Array:
        total = jnp.asarray(0.0, jnp.float32)
        for i, layer in enumerate(self.layers):
            total = total + _updaters.regularization_score(
                params[i], layer.l1_by_param(), layer.l2_by_param())
        return total

    # ------------------------------------------------------------ train step
    def _apply_updates(self, params, updater_state, grads, iteration):
        """DL4J-order updater application (l1/l2 into grad, grad-norm, then
        per-param update rule)."""
        new_params, new_updater_state = [], []
        for i, layer in enumerate(self.layers):
            g = grads[i]
            if g:
                new_p, ustate = _updaters.apply_layer_updates(
                    self._updater_conf(i), layer, params[i],
                    updater_state[i], g, iteration)
                new_params.append(new_p)
                new_updater_state.append(ustate)
            else:
                new_params.append(params[i])
                new_updater_state.append(updater_state[i])
        return new_params, new_updater_state

    def _build_train_step(self, health: bool):
        """Build the jitted train step: fwd + bwd + updater in one XLA
        program.  Donation lets XLA update params/updater state in place in
        HBM (the analogue of the reference's in-place flat-buffer step).

        With ``health=True`` the step additionally packs the per-layer
        grad/param/update statistics (``monitor/health.py``) — a few
        scalar reductions over values already in registers — applies the
        in-jit divergence guard, and returns the packed vector as a
        fifth output.  Both variants register under the same compile-
        watch name: the fit paths dispatch the health variant only, so
        the per-``fn`` compile counters stay meaningful."""
        from ..monitor import health as _health

        def step(params, updater_state, net_state, iteration, features,
                 labels, features_mask, labels_mask, base_rng):
            rng = jax.random.fold_in(base_rng, iteration)
            (data_loss, (new_state, _)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(
                    params, net_state, features, labels, features_mask,
                    labels_mask, rng, True)
            new_params, new_updater_state = self._apply_updates(
                params, updater_state, grads, iteration)
            score = data_loss + self._reg_score(params)
            if not health:
                return new_params, new_updater_state, new_state, score
            hvec, bad = _health.layer_stats(params, new_params, grads,
                                            data_loss)
            new_params, new_updater_state, new_state = _health.guard_select(
                bad, (new_params, new_updater_state, new_state),
                (params, updater_state, net_state))
            return new_params, new_updater_state, new_state, score, hvec

        return _monitor.watched_jit(step, name="mln.train_step",
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _train_step(self):
        """Plain 4-output step (external callers: benches, scaling)."""
        return self._build_train_step(health=False)

    @functools.cached_property
    def _train_step_h(self):
        """Health-instrumented step; the ``fit`` paths use this one."""
        return self._build_train_step(health=True)

    def _build_multi_train_step(self, health: bool):
        """S sequential train steps in ONE XLA program via ``lax.scan`` over
        stacked (S, B, ...) batches.  The reference runs its inner loop on
        the host (``StochasticGradientDescent.java:50-72``, one dispatch per
        iteration); on TPU the scan keeps the whole loop on-chip, so
        throughput is set by the MXU, not by host dispatch latency.

        ``health=True`` stacks the packed per-step health vector as a
        second scan output — (S, 2+3L) f32 riding the same dispatch, so
        exact per-step telemetry costs zero extra dispatches."""

        from . import ingest
        from ..monitor import health as _health

        def multi(params, updater_state, net_state, iteration, features,
                  labels, features_mask, labels_mask, base_rng, wire=None):
            def body(carry, xs):
                p, u, s, it = carry
                f, l, fm, lm = xs
                f = ingest.device_decode(f, wire)
                rng = jax.random.fold_in(base_rng, it)
                (data_loss, (new_s, _)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(
                        p, s, f, l, fm, lm, rng, True)
                new_p, new_u = self._apply_updates(p, u, grads, it)
                score = data_loss + self._reg_score(p)
                if not health:
                    return (new_p, new_u, new_s, it + 1), score
                hvec, bad = _health.layer_stats(p, new_p, grads, data_loss)
                new_p, new_u, new_s = _health.guard_select(
                    bad, (new_p, new_u, new_s), (p, u, s))
                return (new_p, new_u, new_s, it + 1), (score, hvec)

            init = (params, updater_state, net_state,
                    jnp.asarray(iteration, jnp.int32))
            (params, updater_state, net_state, _), out = jax.lax.scan(
                body, init, (features, labels, features_mask, labels_mask))
            if not health:
                return params, updater_state, net_state, out
            scores, hstack = out
            return params, updater_state, net_state, scores, hstack

        return _monitor.watched_jit(multi, name="mln.multi_train_step",
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _multi_train_step(self):
        """Plain 4-output scan step (AOT benches, profilers)."""
        return self._build_multi_train_step(health=False)

    @functools.cached_property
    def _multi_train_step_h(self):
        """Health-instrumented scan step; the ``fit`` paths use this."""
        return self._build_multi_train_step(health=True)

    def _build_gather_train_step(self, health: bool):
        """Device-cached-epoch train step, v2: the epoch PERMUTATION is
        computed on device (threefry ``fold_in(shuffle_key, epoch)``
        feeding ``jax.random.permutation``) and up to ``fused`` whole
        epochs scan in ONE XLA program, each step gathering its
        minibatch from the HBM-resident dataset arrays.  v1 uploaded a
        host-shuffled (S, B) int32 index array every epoch; v2's
        steady-state epochs move ZERO bytes host->device — the epoch
        loop never leaves the chip.  When the resident features are the
        uint8 wire, the affine decode fuses into the gathered batch
        (``ingest.device_decode``).

        Static args (``fused``/``steps``/``batch``/``shuffle``/
        ``tail``/``start``/``run``) fix the program shape;
        ``first_epoch`` stays dynamic (weak int32) so advancing epochs
        never retraces.  ``tail > 0`` selects the 1-step tail dispatch:
        the SAME epoch permutation is recomputed and its last ``tail``
        entries form the ragged final batch, keeping v1's batch
        boundaries.  ``start``/``run`` select the sub-range
        ``[start, start+run)`` of the epoch's full-batch steps — the
        preemption-resume hook: a checkpoint restored mid-epoch
        re-derives the SAME permutation and scans from the saved
        offset, so the split epoch is bit-identical to the fused one
        (the scan body compiles to the same per-step HLO regardless of
        trip count, and the carry chain crosses dispatches exactly).

        ``health=True`` adds the (S, 2+3L) packed per-step health stack
        as a second scan output, fetched once per dispatch — the fused
        multi-epoch program stays ONE dispatch per call."""
        from . import ingest
        from ..monitor import health as _health

        def multi(params, updater_state, net_state, iteration, data_f,
                  data_l, base_rng, shuffle_key, first_epoch, fused,
                  steps, batch, shuffle, tail, wire, start=0, run=None):
            n = data_f.shape[0]
            span = steps if run is None else run

            def epoch_rows(e):
                if shuffle:
                    perm = jax.random.permutation(
                        jax.random.fold_in(shuffle_key, e), n)
                else:
                    perm = jnp.arange(n)
                if tail:
                    return perm[steps * batch:].reshape(1, tail)
                return perm[start * batch:(start + span) * batch] \
                    .reshape(span, batch)

            rows = jax.vmap(epoch_rows)(first_epoch + jnp.arange(fused))
            rows = rows.reshape((-1,) + rows.shape[2:])

            def body(carry, idx_row):
                p, u, s, it = carry
                f = ingest.device_decode(
                    jnp.take(data_f, idx_row, axis=0), wire)
                l = jnp.take(data_l, idx_row, axis=0)
                rng = jax.random.fold_in(base_rng, it)
                (data_loss, (new_s, _)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(
                        p, s, f, l, None, None, rng, True)
                new_p, new_u = self._apply_updates(p, u, grads, it)
                score = data_loss + self._reg_score(p)
                if not health:
                    return (new_p, new_u, new_s, it + 1), score
                hvec, bad = _health.layer_stats(p, new_p, grads, data_loss)
                new_p, new_u, new_s = _health.guard_select(
                    bad, (new_p, new_u, new_s), (p, u, s))
                return (new_p, new_u, new_s, it + 1), (score, hvec)

            init = (params, updater_state, net_state,
                    jnp.asarray(iteration, jnp.int32))
            (params, updater_state, net_state, _), out = jax.lax.scan(
                body, init, rows)
            if not health:
                return params, updater_state, net_state, out
            scores, hstack = out
            return params, updater_state, net_state, scores, hstack

        return _monitor.watched_jit(multi, name="mln.gather_train_step",
                                    static_argnums=(9, 10, 11, 12, 13,
                                                    15, 16),
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _gather_train_step(self):
        """Plain 4-output gather step (profilers, external callers)."""
        return self._build_gather_train_step(health=False)

    @functools.cached_property
    def _gather_train_step_h(self):
        """Health-instrumented gather step; ``_fit_device_cached`` uses
        this one."""
        return self._build_gather_train_step(health=True)

    def _fit_device_cached(self, source, epochs: int,
                           start_step: int = 0, ckpt=None):
        """One ``fit`` over a device-resident dataset (see
        ``_gather_train_step``).  ``source`` is the underlying
        ``ListDataSetIterator`` vetted by ``ingest.cacheable_source``.
        Batch boundaries (incl. the tail batch) and the per-iteration
        RNG/updater stream are IDENTICAL to the per-batch path; the
        example order comes from the on-device threefry permutation
        stream (keyed off the fit RNG, continuing across fits via
        ``self.epoch``) — parity-tested against a host replay of the
        same permutations.  Listeners fire per iteration by replaying
        the scanned scores.  ``start_step``/``ckpt`` are the resume
        offset and checkpoint manager threaded through to the shared
        driver (``ingest.run_device_cached_fit``)."""
        from . import ingest

        data_f, data_l, wire = ingest.device_cached_arrays(
            self, source._ds, source.get_preprocessor())
        shuffle_key = jax.random.fold_in(self._rng_key, 0xFFFFFFFF)
        steps = source._ds.num_examples() // source._batch

        def dispatch(first_epoch, fused, tail, start=0, run=None):
            (self.params, self.updater_state, self.net_state,
             scores, health) = self._gather_train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, data_f, data_l, self._rng_key,
                shuffle_key, first_epoch, fused, steps, source._batch,
                bool(source._shuffle), tail, wire, start,
                steps if run is None else run)
            _monitor.health.record_dispatch(self, health, self.iteration)
            return scores

        return ingest.run_device_cached_fit(self, source, epochs, dispatch,
                                            start_step=start_step,
                                            ckpt=ckpt)

    def _fit_windowed(self, iterator, epochs: int, window: int,
                      ckpt=None):
        """Streaming ``fit(iterator)`` in multi-batch windows: the host
        stacks window k+1 (numpy) and enqueues its transfer while window
        k's multi-step scan runs on-chip — JAX async dispatch provides
        the overlap, nothing blocks until scores are fetched (the
        double-buffered-staging half of the ingest design; datasets that
        fit HBM take ``_fit_device_cached`` instead).  ``ckpt`` saves at
        epoch boundaries (windows re-stack from the host iterator, so
        mid-epoch offsets are not replayable here — the epoch-cache
        path owns exact mid-epoch resume)."""
        from . import ingest
        from ..resilience import faults as _faults

        replay = ingest.ScoreReplayer(self)

        def dispatch(buf):
            t0 = time.perf_counter()
            # straggler point inside the timed data phase, so an armed
            # DL4J_TPU_FAULT_SLOW_WORKER_MS stall lands in phase_data_ms
            # and the step attributor names "data" as the dominant
            # component (monitor/attribution.py)
            _faults.slow_worker()
            features, labels, fm, lm = ingest.stack_window(buf)
            u8, wire = ingest.window_wire(buf)
            if u8 is not None:
                features = u8      # 1 byte/pixel; decode fused on device
            else:
                features = ingest.cast_for_transfer(
                    features, self._pol().compute_name)
            features = jnp.asarray(features)
            labels = jnp.asarray(labels)
            fm = None if fm is None else jnp.asarray(fm)
            lm = None if lm is None else jnp.asarray(lm)
            _monitor.gauge(
                "ingest_staged_bytes",
                "bytes uploaded to the device per staging event").set(
                features.nbytes + labels.nbytes, path="window")
            t1 = time.perf_counter()
            _monitor.observe_phase("data", t1 - t0)
            (self.params, self.updater_state, self.net_state,
             scores, health) = self._multi_train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, fm, lm, self._rng_key,
                wire)
            _monitor.health.record_dispatch(self, health, self.iteration)
            replay.add(self.iteration, scores)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            _monitor.counter("train_iterations_total",
                             "supervised train iterations").inc(len(buf))
            self.iteration += len(buf)
            self.last_batch_size = buf[0].num_examples()

        it_mark = self.iteration
        for _ in range(epochs):
            with _monitor.span("fit/epoch", epoch=self.epoch,
                               path="window"):
                for listener in self.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(self)
                if hasattr(iterator, "reset"):
                    iterator.reset()
                buf, sig = [], None
                for ds in iterator:
                    s = ingest.window_signature(ds)
                    if buf and (s != sig or len(buf) >= window):
                        dispatch(buf)
                        buf = []
                    sig = s
                    buf.append(ds)
                if buf:
                    dispatch(buf)
                if self.listeners:
                    t2 = time.perf_counter()
                    replay.replay()
                    _monitor.observe_phase("listener",
                                           time.perf_counter() - t2)
                for listener in self.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(self)
                self.epoch += 1
            if ckpt is not None:
                ckpt.note_steps(self.iteration - it_mark)
                it_mark = self.iteration
                if ckpt.due(epoch_boundary=True):
                    replay.replay()
                    ckpt.save(self, step_in_epoch=0)
            _faults.maybe_die(self.iteration)
        if ckpt is not None:
            replay.replay()
            ckpt.save_if_progress(self, step_in_epoch=0)
            ckpt.flush()
        replay.finish()
        return self

    def fit_scan(self, batches: Sequence[DataSet]) -> np.ndarray:
        """Fit a list of same-shaped minibatches in one device dispatch
        (scan-based inner loop).  Returns the per-step scores.  Listeners
        fire once at the end with the final iteration — per-step host
        callbacks would break the single-HLO hot loop.

        Supports the standard-backprop regime only: configs using tBPTT,
        pretraining, or ``num_iterations > 1`` must go through ``fit()``
        (raises loudly rather than silently training differently)."""
        self.init()
        if self.conf.backprop_type == "tbptt":
            raise ValueError("fit_scan does not support tBPTT; use fit()")
        if self.conf.pretrain and not self._pretrain_done:
            raise ValueError("fit_scan does not run pretraining; call "
                             "pretrain() (or fit()) first")
        if self.conf.conf.num_iterations != 1:
            raise ValueError("fit_scan runs one update per batch; "
                             "num_iterations > 1 must use fit()")
        if self._solver is not None:
            raise ValueError("fit_scan supports the SGD path only; this "
                             "config uses a line-search solver")

        def stack_masks(get):
            present = [get(b) is not None for b in batches]
            if not any(present):
                return None
            if not all(present):
                raise ValueError(
                    "Mixed mask presence across batches in fit_scan; "
                    "provide masks on all batches or none")
            return jnp.stack([jnp.asarray(get(b)) for b in batches])

        from ..resilience import faults as _faults
        t0 = time.perf_counter()
        # straggler point inside the timed data phase (see dispatch())
        _faults.slow_worker()
        features = jnp.stack([jnp.asarray(b.features) for b in batches])
        labels = jnp.stack([jnp.asarray(b.labels) for b in batches])
        fmask = stack_masks(lambda b: b.features_mask)
        lmask = stack_masks(lambda b: b.labels_mask)
        t1 = time.perf_counter()
        _monitor.observe_phase("data", t1 - t0)
        (self.params, self.updater_state, self.net_state,
         scores, health) = self._multi_train_step_h(
            self.params, self.updater_state, self.net_state, self.iteration,
            features, labels, fmask, lmask, self._rng_key)
        _monitor.health.record_dispatch(self, health, self.iteration)
        _monitor.observe_phase("step", time.perf_counter() - t1)
        _monitor.counter("train_iterations_total",
                         "supervised train iterations").inc(len(batches))
        self.iteration += len(batches)
        self._score = scores[-1]
        self.last_batch_size = batches[0].num_examples()
        self._fire_listeners()
        return np.asarray(scores)

    def _last_stateful_recurrent(self) -> int:
        """Index of the deepest layer carrying real recurrent state (-1 if
        none); the exact-tBPTT split point.  RnnOutputLayer-style
        time-distributed heads have an empty carry and sit in the suffix."""
        from .layers.recurrent import BaseRecurrentLayer
        last = -1
        for i, layer in enumerate(self.layers):
            if isinstance(layer, BaseRecurrentLayer) \
                    and layer.init_carry(1, jnp.float32) != ():
                last = i
        return last

    def _tbptt_window_loss(self, adv: int, carries):
        """Loss closure for ONE truncated-BPTT window with ``carries`` in
        (gradients stopped at the window boundary): ``loss(p, ns, f, l,
        fm, lm, r) -> (loss, (new_state, new_carries))``.  Shared by the
        single-device window step (:meth:`_tbptt_step_for`) and
        ``ParallelWrapper``'s per-worker round, so both train the exact
        same windowed program.

        ``adv`` > 0 reproduces the reference's ``tbptt_back_length <
        fwd`` semantics exactly (``LSTMHelpers`` truncated backward loop):
        the leading ``adv`` steps run through the recurrent trunk with
        stopped gradients, then score through the suffix layers normally —
        so layers above the last recurrent layer accumulate gradients from
        ALL window steps while the recurrent trunk sees only the trailing
        ``back`` steps, matching the reference's per-layer truncation.
        """
        last_rec = self._last_stateful_recurrent()
        carries = jax.lax.stop_gradient(carries)

        def loss(p, ns, f, l, fm, lm, r):
            if adv == 0:
                return self._loss_fn(p, ns, f, l, fm, lm, r, True,
                                     carries=carries)
            rA = rB = None
            if r is not None:
                rA = jax.random.fold_in(r, 0)
                rB = jax.random.fold_in(r, 1)
            fmA = None if fm is None else fm[:, :adv]
            # leading steps: recurrent trunk, gradients stopped
            trunk, _, mid = self._forward(
                p, ns, f[:, :adv], train=True, rng=rA, mask=fmA,
                carries=carries, to_layer=last_rec)
            trunk = jax.lax.stop_gradient(trunk)
            mid = jax.lax.stop_gradient(mid)
            lmA = None if lm is None else lm[:, :adv]
            lmB = None if lm is None else lm[:, adv:]
            loss_a, _ = self._loss_fn(
                p, ns, trunk, l[:, :adv], fmA, lmA, rA, True,
                from_layer=last_rec + 1)
            loss_b, aux = self._loss_fn(
                p, ns, f[:, adv:], l[:, adv:],
                None if fm is None else fm[:, adv:], lmB, rB,
                True, carries=mid)
            # Masked scores normalize by each segment's own mask
            # count; recombine so the window averages over the
            # TOTAL active steps, matching the adv == 0 path.
            eff_a = lmA if lmA is not None else fmA
            eff_b = (lmB if lmB is not None
                     else (None if fm is None else fm[:, adv:]))
            if (self.conf.conf.mini_batch and eff_a is not None
                    and eff_b is not None):
                ca = jnp.sum(eff_a)
                cb = jnp.sum(eff_b)
                total = (loss_a * ca + loss_b * cb) / \
                    jnp.maximum(ca + cb, 1.0)
                return total, aux
            return loss_a + loss_b, aux

        return loss

    def _tbptt_step_for(self, adv: int):
        """Truncated-BPTT window step (reference ``doTruncatedBPTT:1138``):
        one fwd+bwd+update over a ``tbptt_fwd_length`` window with carries
        in from the previous window, gradients stopped at the window
        boundary (window-loss semantics: :meth:`_tbptt_window_loss`).
        """
        if adv not in self._tbptt_step_cache:

            def step(params, updater_state, net_state, carries, iteration,
                     features, labels, features_mask, labels_mask,
                     base_rng):
                rng = (jax.random.fold_in(base_rng, iteration)
                       if base_rng is not None else None)
                loss = self._tbptt_window_loss(adv, carries)
                (data_loss, (new_state, new_carries)), grads = \
                    jax.value_and_grad(loss, has_aux=True)(
                        params, net_state, features, labels, features_mask,
                        labels_mask, rng)
                new_params, new_updater_state = self._apply_updates(
                    params, updater_state, grads, iteration)
                score = data_loss + self._reg_score(params)
                return (new_params, new_updater_state, new_state,
                        new_carries, score)

            self._tbptt_step_cache[adv] = _monitor.watched_jit(
                step, name=f"mln.tbptt_step_adv{adv}",
                donate_argnums=(0, 1, 2, 3))
        return self._tbptt_step_cache[adv]

    @functools.cached_property
    def _score_fn(self):
        def score(params, net_state, features, labels, features_mask,
                  labels_mask):
            data_loss, _ = self._loss_fn(params, net_state, features, labels,
                                         features_mask, labels_mask, None,
                                         False)
            return data_loss + self._reg_score(params)
        return _monitor.watched_jit(score, name="mln.score")

    @functools.cached_property
    def _output_fn(self):
        def run(params, net_state, features, features_mask):
            out, _, _ = self._forward(params, net_state, features,
                                      train=False, rng=None,
                                      mask=features_mask)
            return out
        return _monitor.watched_jit(run, name="mln.output")

    @functools.cached_property
    def _eval_argmax_fn(self):
        """Inference forward + argmax in one program: evaluation transfers
        int32 class indices, not (batch, classes) logits."""
        def run(params, net_state, features, features_mask):
            out, _, _ = self._forward(params, net_state, features,
                                      train=False, rng=None,
                                      mask=features_mask)
            return jnp.argmax(out, axis=-1).astype(jnp.int32)
        return _monitor.watched_jit(run, name="mln.eval_argmax")

    @functools.cached_property
    def _rnn_step_fn(self):
        """Streaming inference step (reference ``rnnTimeStep:2230``): forward
        with explicit carries in/out, jitted once and reused per step."""
        def run(params, net_state, carries, features):
            out, _, new_carries = self._forward(
                params, net_state, features, train=False, rng=None,
                carries=carries)
            return out, new_carries
        return _monitor.watched_jit(run, name="mln.rnn_step")

    @functools.cached_property
    def _decode_step_fn(self):
        """Autoregressive decode step: the ``rnn_step`` contract over
        generalized state trees (RNN carries AND KV-cache rings), under
        its own jit name so the serving sanitizer can budget
        ``serving.decode_step`` separately (one dispatch per token)."""
        def run(params, net_state, carries, features):
            out, _, new_carries = self._forward(
                params, net_state, features, train=False, rng=None,
                carries=carries)
            return out, new_carries
        return _monitor.watched_jit(run, name="mln.decode_step")

    def _decode_grow_fn(self, cache_len: int):
        """Jitted state-tree growth to a larger KV ring capacity — ONE
        dispatch per (shape, target) pair, cached like the tbptt steps,
        so a serving bucket hop costs exactly one extra dispatch."""
        from .layers.recurrent import BaseRecurrentLayer
        if cache_len not in self._decode_grow_cache:
            def grow(carries):
                return [
                    layer.grow_carry(carries[i], cache_len)
                    if (isinstance(layer, BaseRecurrentLayer)
                        and getattr(layer, "HAS_KV_RING", False))
                    else carries[i]
                    for i, layer in enumerate(self.layers)]
            self._decode_grow_cache[cache_len] = _monitor.watched_jit(
                grow, name="mln.decode_grow")
        return self._decode_grow_cache[cache_len]

    # -------------------------------------------------------------- pretrain
    def _pretrain_step(self, i: int):
        """Jitted one-batch unsupervised step for layer ``i``: forward the
        input through layers 0..i-1 (inference mode), stop the gradient, and
        apply the layer's ``pretrain_grads`` through the DL4J-order updater
        — all one XLA program (reference ``MultiLayerNetwork.pretrain:991``:
        per-layer fit with ``feedForwardToLayer`` input)."""
        if i not in self._pretrain_step_cache:
            layer = self.layers[i]
            uconf = self._updater_conf(i)

            def step(params, ustate_i, net_state, iteration, features,
                     base_rng):
                rng = jax.random.fold_in(base_rng, iteration)
                x, _, _ = self._forward(params, net_state, features,
                                        train=False, rng=None,
                                        to_layer=i - 1)
                if i in self.conf.input_preprocessors:
                    x = self.conf.input_preprocessors[i](x)
                x = jax.lax.stop_gradient(x)
                score, grads = layer.pretrain_grads(params[i], x, rng)
                grads = _updaters.regularize(grads, params[i],
                                             layer.l1_by_param(),
                                             layer.l2_by_param())
                grads = _updaters.normalize_gradients(
                    grads, layer.gradient_normalization,
                    layer.gradient_normalization_threshold)
                updates, new_ustate = _updaters.compute_update(
                    uconf, grads, ustate_i, iteration,
                    params={k: params[i][k] for k in grads})
                new_p = jax.tree.map(lambda p, u: p - u, params[i], updates)
                score = score + _updaters.regularization_score(
                    params[i], layer.l1_by_param(), layer.l2_by_param())
                return new_p, new_ustate, score

            self._pretrain_step_cache[i] = _monitor.watched_jit(
                step, name=f"mln.pretrain_step_layer{i}", donate_argnums=(1,))
        return self._pretrain_step_cache[i]

    def pretrain(self, data, epochs: int = 1) -> "MultiLayerNetwork":
        """Greedy layer-wise unsupervised pretraining of every pretrainable
        layer (VAE/AutoEncoder/RBM), in order (reference
        ``MultiLayerNetwork.pretrain:991``)."""
        self.init()
        if not isinstance(data, DataSet) and not hasattr(data, "reset"):
            data = list(data)  # one-shot iterable: each layer needs a pass
        for i, layer in enumerate(self.layers):
            if getattr(layer, "IS_PRETRAINABLE", False):
                self.pretrain_layer(i, data, epochs)
        # fit() must not re-run pretraining (and the flag serializes, so a
        # restored model doesn't re-pretrain over fine-tuned weights)
        self._pretrain_done = True
        return self

    def pretrain_layer(self, i: int, data,
                       epochs: int = 1) -> "MultiLayerNetwork":
        """Pretrain one layer (reference ``pretrainLayer``); non-pretrainable
        layers are skipped like the reference (no-op, not an error)."""
        self.init()
        layer = self.layers[i]
        if not getattr(layer, "IS_PRETRAINABLE", False):
            return self
        if getattr(layer, "frozen", False):
            return self          # frozen extractor: pretraining is a no-op
        step = self._pretrain_step(i)
        if isinstance(data, DataSet):
            data_iter: Sequence[DataSet] = [data]
        else:
            data_iter = data
        for _ in range(epochs):
            if hasattr(data_iter, "reset"):
                data_iter.reset()
            for ds in data_iter:
                features = jnp.asarray(ds.features)
                (self.params[i], self.updater_state[i],
                 score) = step(self.params, self.updater_state[i],
                               self.net_state, self.iteration, features,
                               self._rng_key)
                self._score = score
                self.iteration += 1
                self._fire_listeners()
        return self

    # ------------------------------------------------------------------- fit
    def _resolve_resilience(self, checkpoint, resume_from, epochs):
        """(manager, start_step, remaining_epochs) for ``fit``'s
        ``checkpoint=``/``resume_from=`` hooks; the no-resilience call
        stays import-free."""
        if checkpoint is None and resume_from is None:
            return None, 0, epochs
        from ..resilience.checkpoint import resolve_fit_resilience
        return resolve_fit_resilience(self, checkpoint, resume_from,
                                      epochs)

    def _warn_partial_epoch_restart(self, start_step: int,
                                    path: str) -> None:
        """Mid-epoch resume offsets are only replayable on the
        epoch-cache path (the shuffle lives in the on-device threefry
        stream); other paths restart the interrupted epoch."""
        if start_step:
            import warnings
            warnings.warn(
                f"resume_from checkpoint was taken mid-epoch "
                f"(step_in_epoch={start_step}) but the {path} path "
                "cannot seek into an epoch; restarting the epoch from "
                "step 0 (at-least-once semantics)", RuntimeWarning)

    def fit(self, data, labels=None, epochs: int = 1,
            ingest: str = "auto",
            window: int = 16, checkpoint=None,
            resume_from=None) -> "MultiLayerNetwork":
        """Train (reference ``fit(DataSetIterator):976`` /
        ``fit(INDArray,INDArray):1406``).

        ``data`` may be a DataSetIterator-like iterable of :class:`DataSet`,
        a single :class:`DataSet`, or a features array with ``labels``.

        With ``conf.pretrain=True`` the first call runs layer-wise
        unsupervised pretraining before supervised backprop (reference
        ``fit`` at ``:991``); with ``conf.backprop=False`` only pretraining
        runs.

        ``ingest`` selects the iterator data path (the reference hides
        ETL behind ``AsyncDataSetIterator`` prefetch; on TPU the wins
        are device residency and transfer/compute overlap):

        - ``"auto"`` (default): device-resident epoch cache when the
          dataset fits HBM (``nn/ingest.py`` eligibility), else
          windowed double-buffered staging, else per-batch.
        - ``"cache"`` / ``"window"`` / ``"batch"``: force one path.

        The cache/window paths run multi-step ``lax.scan`` dispatches
        and fire listeners by exact per-step score replay (params seen
        by a replayed listener are end-of-dispatch — the ``fit_scan``
        compromise).  Solver/tBPTT/num_iterations>1 configs always use
        the per-batch path.

        Resilience (``docs/RESILIENCE.md``): ``checkpoint=`` (a
        ``resilience.CheckpointManager`` or a directory) saves
        preemption-safe checkpoints at the manager's step/second
        cadence (epoch boundaries by default); ``resume_from=``
        (``"auto"``, a directory, or a checkpoint path) restores
        params/updater/RNG/progress before training.  With
        ``resume_from``, ``epochs`` is the TOTAL epoch target the
        original run aimed for — the restored epoch counter determines
        how much work remains, so callers re-issue the identical fit
        call after a preemption.  On the epoch-cache path a mid-epoch
        restore resumes at the exact fused-scan step offset
        (bit-identical to the uninterrupted run); the window/batch
        paths restart the interrupted epoch from its beginning.
        """
        if ingest not in ("auto", "cache", "window", "batch"):
            raise ValueError(
                f"unknown ingest mode {ingest!r}; expected 'auto', "
                "'cache', 'window', or 'batch'")
        self.init()
        ckpt, start_step, epochs = self._resolve_resilience(
            checkpoint, resume_from, epochs)
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, DataSet):
            batches: Sequence[DataSet] = [data]
            iterator = None
        else:
            iterator = data
            batches = None

        from ..optimize.listeners.listeners import finalize_listeners
        try:
            if self.conf.pretrain and not self._pretrain_done:
                if batches is None and not hasattr(iterator, "reset"):
                    # One-shot iterable: materialize so layer-wise
                    # pretraining and the supervised phase each see the
                    # full data.
                    batches = list(iterator)
                    iterator = None
                self.pretrain(batches if batches is not None else iterator)
                self._pretrain_done = True
            if not self.conf.backprop:
                return self

            if (iterator is not None and ingest != "batch"
                    and self._solver is None
                    and self.conf.backprop_type != "tbptt"
                    and self.conf.conf.num_iterations == 1):
                from . import ingest as ingest_mod
                if ingest in ("auto", "cache"):
                    source = ingest_mod.cacheable_source(iterator)
                    if source is not None:
                        return self._fit_device_cached(
                            source, epochs, start_step=start_step,
                            ckpt=ckpt)
                    if ingest == "cache":
                        raise ValueError(
                            "ingest='cache' but the iterator is not "
                            "device-cacheable (see nn/ingest.py "
                            "eligibility)")
                self._warn_partial_epoch_restart(start_step, "window")
                return self._fit_windowed(iterator, epochs, window,
                                          ckpt=ckpt)

            self._warn_partial_epoch_restart(start_step, "batch")
            from ..resilience import faults as _faults
            it_mark = self.iteration
            for _ in range(epochs):
                with _monitor.span("fit/epoch", epoch=self.epoch,
                                   path="batch"):
                    for listener in self.listeners:
                        if hasattr(listener, "on_epoch_start"):
                            listener.on_epoch_start(self)
                    it = batches if batches is not None else iterator
                    if hasattr(it, "reset"):
                        it.reset()
                    for ds in it:
                        self._fit_batch(ds)
                    for listener in self.listeners:
                        if hasattr(listener, "on_epoch_end"):
                            listener.on_epoch_end(self)
                    self.epoch += 1
                if ckpt is not None:
                    ckpt.note_steps(self.iteration - it_mark)
                    it_mark = self.iteration
                    if ckpt.due(epoch_boundary=True):
                        ckpt.save(self, step_in_epoch=0)
                _faults.maybe_die(self.iteration)
            if ckpt is not None:
                ckpt.save_if_progress(self, step_in_epoch=0)
                ckpt.flush()
            return self
        finally:
            finalize_listeners(self.listeners)

    def _fire_listeners(self) -> None:
        """Per-iteration listener callbacks, timed as the ``listener``
        phase (they run on the host and may force a device score fetch)."""
        if not self.listeners:
            return
        t0 = time.perf_counter()
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)
        _monitor.observe_phase("listener", time.perf_counter() - t0)

    def _fit_batch(self, ds: DataSet) -> None:
        from ..resilience import faults as _faults
        self.last_batch_size = ds.num_examples()
        t0 = time.perf_counter()
        # straggler point inside the timed data phase (see dispatch())
        _faults.slow_worker()
        features = jnp.asarray(ds.features)
        labels = jnp.asarray(ds.labels)
        fmask = (None if ds.features_mask is None
                 else jnp.asarray(ds.features_mask))
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        _monitor.observe_phase("data", time.perf_counter() - t0)
        iters = _monitor.counter("train_iterations_total",
                                 "supervised train iterations")
        if self._solver is not None:
            # line-search solver family (reference Solver.optimize path)
            for _ in range(self.conf.conf.num_iterations):
                t1 = time.perf_counter()
                self._score = self._solver.optimize(features, labels,
                                                    fmask, lmask)
                _monitor.observe_phase("step", time.perf_counter() - t1)
                self.iteration += 1
                iters.inc()
                self._fire_listeners()
            return
        if self.conf.backprop_type == "tbptt":
            for _ in range(self.conf.conf.num_iterations):
                self._fit_tbptt(features, labels, fmask, lmask)
            return
        for _ in range(self.conf.conf.num_iterations):
            t1 = time.perf_counter()
            (self.params, self.updater_state, self.net_state,
             score, health) = self._train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, fmask, lmask,
                self._rng_key)
            _monitor.health.record_dispatch(self, health, self.iteration)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            self._score = score
            self.iteration += 1
            iters.inc()
            self._fire_listeners()

    def _fit_tbptt(self, features, labels, fmask, lmask) -> None:
        """Slice the time axis into tbptt_fwd_length windows, carrying
        recurrent state forward across windows (reference
        ``doTruncatedBPTT:1138`` + ``updateRnnStateWithTBPTTState:1187``).
        State is cleared at the start of each new minibatch."""
        self._require_carry_support("truncated BPTT")
        if labels.ndim < 3:
            raise ValueError(
                "Truncated BPTT needs per-timestep labels (batch, time, ...); "
                f"got shape {labels.shape}. Use standard backprop for "
                "sequence-level labels.")
        window = self.conf.tbptt_fwd_length
        back = self.conf.tbptt_back_length or window
        if back > window:
            raise ValueError(
                f"tbptt_back_length ({back}) > tbptt_fwd_length "
                f"({window}) is not meaningful")
        T = features.shape[1]
        carries = self._init_carries(features.shape[0])
        scores = []
        for start in range(0, T, window):
            stop = min(start + window, T)
            # back < fwd: loss covers the WHOLE window; the leading
            # fwd-back steps run the recurrent trunk gradient-stopped
            # (exact reference semantics — see _tbptt_step_for)
            adv = max(0, (stop - start) - back)
            sl = slice(start, stop)
            f = features[:, sl]
            l = labels[:, sl]
            fm = None if fmask is None else fmask[:, sl]
            lm = None if lmask is None else lmask[:, sl]
            t1 = time.perf_counter()
            (self.params, self.updater_state, self.net_state, carries,
             score) = self._tbptt_step_for(adv)(
                self.params, self.updater_state, self.net_state, carries,
                self.iteration, f, l, fm, lm, self._rng_key)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            scores.append(score)
            self.iteration += 1
            _monitor.counter("train_iterations_total",
                             "supervised train iterations").inc()
            self._fire_listeners()
        self._score = scores[-1] if scores else self._score

    def _require_carry_support(self, what: str) -> None:
        """Bidirectional layers cannot carry state across time chunks
        (reference GravesBidirectionalLSTM.rnnTimeStep throws
        UnsupportedOperationException)."""
        from .layers.recurrent import BaseRecurrentLayer
        for i, layer in enumerate(self.layers):
            if (isinstance(layer, BaseRecurrentLayer)
                    and not layer.SUPPORTS_CARRY):
                raise ValueError(
                    f"Layer {i} ({type(layer).__name__}) does not support "
                    f"{what}: its backward pass needs the full sequence")

    def _init_carries(self, batch: int, cache_len: Optional[int] = None):
        """Zero recurrent carries, one entry per layer (() if stateless).
        ``cache_len`` overrides KV-ring capacities (the serving
        (batch, cache_len) bucket ladder); RNN carries ignore it."""
        from .layers.recurrent import BaseRecurrentLayer
        dtype = jnp.dtype(self._pol().compute_dtype)
        out = []
        for layer in self.layers:
            if not isinstance(layer, BaseRecurrentLayer):
                out.append(())
            elif cache_len is not None and getattr(layer, "HAS_KV_RING",
                                                   False):
                out.append(layer.init_carry(batch, dtype,
                                            cache_len=cache_len))
            else:
                out.append(layer.init_carry(batch, dtype))
        return out

    def has_kv_ring(self) -> bool:
        """Whether any layer carries a KV-cache ring (the decode-serving
        state class — chooses the ``serving.decode_step`` sanitizer
        scenario over ``serving.rnn_step``)."""
        return any(getattr(layer, "HAS_KV_RING", False)
                   for layer in self.layers)

    def max_cache_len(self) -> int:
        """Largest KV-ring capacity across layers (0 without rings) —
        the top of the serving cache-len bucket ladder."""
        return max((int(layer.cache_len) for layer in self.layers
                    if getattr(layer, "HAS_KV_RING", False)), default=0)

    # ------------------------------------------------------------- inference
    def output(self, features, train: bool = False,
               features_mask=None) -> np.ndarray:
        """Forward pass (reference ``output:1519-1601``; TEST mode: no
        dropout, BN running stats)."""
        self.init()
        fmask = None if features_mask is None else jnp.asarray(features_mask)
        out = self._output_fn(self.params, self.net_state,
                              jnp.asarray(features), fmask)
        return np.asarray(out)

    def compile_output(self, feature_shape, dtype=None, mask_shape=None,
                       mask_dtype=None, params=None, net_state=None):
        """AOT-compile the inference forward for ONE concrete input shape
        (``jit(...).lower().compile()`` through ``monitor.watched_jit``,
        so every warmed shape is counted in
        ``jit_compiles_total{fn="mln.output"}``).  This is the serving
        bucket-warmup primitive: the ``serving.InferenceEngine`` compiles
        one executable per (batch-bucket, timestep-bucket) up front and
        then dispatches with zero trace/compile work on the hot path.

        Returns the compiled executable; call it as
        ``compiled(params, net_state, features, features_mask)`` with
        arrays matching the lowered shapes exactly (pass ``None`` for the
        mask iff ``mask_shape`` was ``None``).  ``params``/``net_state``
        override the lowering operands — pass device-committed copies to
        pin the executable to a specific device (the serving worker-pool
        path).
        """
        self.init()
        if params is None:
            params = self.params
        if net_state is None:
            net_state = self.net_state
        dt = jnp.dtype(dtype if dtype is not None else self.conf.conf.dtype)
        aval = jax.ShapeDtypeStruct(tuple(int(d) for d in feature_shape),
                                    dt)
        maval = None
        if mask_shape is not None:
            mdt = jnp.dtype(mask_dtype if mask_dtype is not None else dt)
            maval = jax.ShapeDtypeStruct(
                tuple(int(d) for d in mask_shape), mdt)
        return self._output_fn.lower(params, net_state, aval,
                                     maval).compile()

    def feed_forward(self, features) -> List[np.ndarray]:
        """All layer activations (reference ``feedForward:655-747``)."""
        self.init()
        acts = []
        for i in range(len(self.layers)):
            x, _, _ = self._forward(self.params, self.net_state,
                                    jnp.asarray(features), train=False,
                                    rng=None, to_layer=i)
            acts.append(np.asarray(x))
        return acts

    # --------------------------------------------- rnn streaming state API
    def rnn_time_step(self, features) -> np.ndarray:
        """Stateful streaming inference (reference ``rnnTimeStep:2230``):
        feeds one or more timesteps, carrying hidden state between calls.
        2-D input (batch, features) is one timestep and returns
        (batch, n_out); 3-D input returns the full (batch, time, n_out)."""
        self.init()
        self._require_carry_support("rnn_time_step")
        x = jnp.asarray(features)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        if self._rnn_carries is None:
            self._rnn_carries = self._init_carries(x.shape[0])
            self._rnn_carry_batch = x.shape[0]
        elif self._rnn_carry_batch != x.shape[0]:
            # Reference throws DL4JInvalidInputException here — silently
            # resetting would discard state from a half-fed sequence.
            raise ValueError(
                f"rnn_time_step batch size {x.shape[0]} != stored state "
                f"batch size {self._rnn_carry_batch}; call "
                "rnn_clear_previous_state() between unrelated sequences")
        out, self._rnn_carries = self._rnn_step_fn(
            self.params, self.net_state, self._rnn_carries, x)
        out = np.asarray(out)
        return out[:, -1] if squeeze else out

    def rnn_stateless_step(self, carries, features, params=None,
                           net_state=None):
        """Explicit-carry streaming step (the re-entrant twin of
        :meth:`rnn_time_step`): advance the given carry pytree by the
        input timesteps and return ``(out, new_carries)`` WITHOUT
        touching the model's own hidden-state slot.  ``carries=None``
        starts from zero state.  This is what lets N concurrent serving
        sessions share one model instance (``serving.SessionCache``) —
        state lives with the caller, arrays stay on device, and each
        call is exactly ONE dispatch of the jitted
        ``mln.rnn_step`` program.

        3-D ``features`` only (``(batch, time, n_in)``); the session
        layer owns the 2-D squeeze convention.

        ``params``/``net_state`` override the weight operands (same
        shapes/dtypes, so the jitted step is a cache hit, never a
        recompile) — what lets a serving session stay pinned to the
        weight version its carries came from across a hot-swap
        (docs/DEPLOY.md).
        """
        self.init()
        self._require_carry_support("rnn_stateless_step")
        x = jnp.asarray(features)
        if x.ndim != 3:
            raise ValueError(
                f"rnn_stateless_step expects (batch, time, features), "
                f"got shape {x.shape}")
        if carries is None:
            carries = self._init_carries(int(x.shape[0]))
        return self._rnn_step_fn(
            self.params if params is None else params,
            self.net_state if net_state is None else net_state,
            carries, x)

    def decode_step(self, carries, features, params=None, net_state=None):
        """Autoregressive decode step: :meth:`rnn_stateless_step`
        generalized to arbitrary per-session state trees — RNN carries
        and KV-cache rings alike — under the ``mln.decode_step`` jit
        name.  Advance the state tree by the input timesteps and return
        ``(out, new_carries)``; N single-token calls BIT-match one
        full-sequence ``output()`` (fp32-logits contract included —
        ``tests/test_decode.py``).  ``carries=None`` starts a fresh
        state tree (ring capacity from the layers' ``cache_len``).
        3-D ``(batch, time, n_in)`` features only; ``params``/
        ``net_state`` override the weight operands for version-pinned
        serving sessions (same shapes → jit cache hit, no recompile).
        """
        self.init()
        self._require_carry_support("decode_step")
        # No explicit jnp.asarray: jit commits np inputs itself, and an
        # eager device_put of a single-token array costs more host time
        # than the decode dispatch it feeds (bench.py --decode).
        x = features if hasattr(features, "ndim") else np.asarray(features)
        if x.ndim != 3:
            raise ValueError(
                f"decode_step expects (batch, time, features), got "
                f"shape {x.shape}")
        if carries is None:
            carries = self._init_carries(int(x.shape[0]))
        return self._decode_step_fn(
            self.params if params is None else params,
            self.net_state if net_state is None else net_state,
            carries, x)

    def grow_decode_carries(self, carries, cache_len: int):
        """Pad every KV ring in ``carries`` up to ``cache_len`` slots
        (ONE jitted dispatch; non-ring carries pass through) — the
        serving cache-len bucket hop.  Ring slots beyond the cursor are
        exact-zero under the cursor mask, so growth never changes
        results."""
        self.init()
        return self._decode_grow_fn(int(cache_len))(carries)

    def rnn_clear_previous_state(self) -> None:
        """Reference ``rnnClearPreviousState()``."""
        self._rnn_carries = None
        self._rnn_carry_batch = -1

    def rnn_get_previous_state(self, layer: int):
        """Carry pytree for one layer (reference ``rnnGetPreviousState``)."""
        return (None if self._rnn_carries is None
                else self._rnn_carries[layer])

    def rnn_set_previous_state(self, layer: int, state) -> None:
        if self._rnn_carries is None:
            raise ValueError("No rnn state yet; call rnn_time_step first")
        self._rnn_carries[layer] = state

    def predict(self, features) -> np.ndarray:
        """Argmax class predictions (reference ``predict``)."""
        return np.argmax(self.output(features), axis=-1)

    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Mean loss on a dataset (reference ``score:1705``)."""
        if dataset is None:
            return float(self._score)
        self.init()
        fmask = (None if dataset.features_mask is None
                 else jnp.asarray(dataset.features_mask))
        lmask = (None if dataset.labels_mask is None
                 else jnp.asarray(dataset.labels_mask))
        val = self._score_fn(self.params, self.net_state,
                             jnp.asarray(dataset.features),
                             jnp.asarray(dataset.labels), fmask, lmask)
        return float(val)

    @functools.cached_property
    def _score_examples_fn(self):
        @functools.partial(_monitor.watched_jit,
                           name="mln.score_examples", static_argnums=(6,))
        def run(params, net_state, features, labels, features_mask,
                labels_mask, add_reg):
            per, _ = self._loss_fn(params, net_state, features, labels,
                                   features_mask, labels_mask, None, False,
                                   per_example=True)
            if add_reg:
                per = per + self._reg_score(params)
            return per
        return run

    def score_examples(self, data,
                       add_regularization_terms: bool = True) -> np.ndarray:
        """Per-example loss vector, no batch averaging (reference
        ``scoreExamples:1740-1775``) — e.g. autoencoder anomaly scoring.
        ``data`` is a DataSet or an iterator (streamed batch by batch);
        with regularization, each entry equals ``score()`` on that single
        example."""
        self.init()
        batches = [data] if isinstance(data, DataSet) else iter(data)
        out = []
        for ds in batches:
            fmask = (None if ds.features_mask is None
                     else jnp.asarray(ds.features_mask))
            lmask = (None if ds.labels_mask is None
                     else jnp.asarray(ds.labels_mask))
            out.append(np.asarray(self._score_examples_fn(
                self.params, self.net_state, jnp.asarray(ds.features),
                jnp.asarray(ds.labels), fmask, lmask,
                bool(add_regularization_terms))))
        if not out:
            return np.zeros((0,), np.float32)
        return np.concatenate(out)

    def do_evaluation(self, iterator, *evaluators):
        """Run one forward pass per batch, feeding every evaluator
        (reference ``doEvaluation(iterator, IEvaluation...)``) —
        time-series outputs go through the masked ``evalTimeSeries``
        path.  Returns the evaluators.

        When every evaluator is a plain top-1 ``Evaluation``, the argmax
        runs on device fused into the forward program and only int32
        class indices cross the wire; label argmax and mask filtering
        stay on the host where the labels already live.  The
        ``eval_bytes_transferred`` gauge reports what the last
        evaluation actually moved device->host."""
        from ..eval.evaluation import Evaluation
        if isinstance(iterator, DataSet):
            iterator = [iterator]
        if hasattr(iterator, "reset"):
            iterator.reset()
        fast = bool(evaluators) and all(
            type(ev) is Evaluation and ev.top_n == 1 for ev in evaluators)
        bytes_moved = 0
        for ds in iterator:
            labels = np.asarray(ds.labels)
            mask = (ds.labels_mask if ds.labels_mask is not None
                    else ds.features_mask)
            mask = None if mask is None else np.asarray(mask)
            if fast:
                self.init()
                fmask = (None if ds.features_mask is None
                         else jnp.asarray(ds.features_mask))
                guess = np.asarray(self._eval_argmax_fn(
                    self.params, self.net_state, jnp.asarray(ds.features),
                    fmask))
                bytes_moved += guess.nbytes
                actual = labels.argmax(-1)
                if labels.ndim == 3:
                    actual, guess = actual.reshape(-1), guess.reshape(-1)
                    if mask is not None:
                        keep = mask.reshape(-1) > 0
                        actual, guess = actual[keep], guess[keep]
                for ev in evaluators:
                    ev.eval_class_indices(actual, guess, labels.shape[-1])
                continue
            out = self.output(ds.features, features_mask=ds.features_mask)
            bytes_moved += out.nbytes
            for ev in evaluators:
                if out.ndim == 3:
                    ev.eval_time_series(labels, out, mask)
                else:
                    ev.eval(labels, out)
        _monitor.gauge(
            "eval_bytes_transferred",
            "device->host bytes moved by the most recent do_evaluation",
        ).set(bytes_moved, path="indices" if fast else "logits")
        return evaluators

    def evaluate(self, iterator):
        """Classification evaluation over an iterator (reference
        ``MultiLayerNetwork.evaluate``)."""
        from ..eval.evaluation import Evaluation
        return self.do_evaluation(iterator, Evaluation())[0]

    def evaluate_roc(self, iterator, threshold_steps: int = 30):
        """Binary ROC over an iterator (reference ``evaluateROC``)."""
        from ..eval.roc import ROC
        return self.do_evaluation(iterator, ROC(threshold_steps))[0]

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 30):
        """One-vs-all ROC (reference ``evaluateROCMultiClass``)."""
        from ..eval.roc import ROCMultiClass
        return self.do_evaluation(iterator,
                                  ROCMultiClass(threshold_steps))[0]

    def evaluate_regression(self, iterator):
        """Per-column regression stats (reference
        ``evaluateRegression``)."""
        from ..eval.regression import RegressionEvaluation
        return self.do_evaluation(iterator, RegressionEvaluation())[0]

    def f1_score(self, data) -> float:
        """Macro F1 on a DataSet/iterator (reference ``f1Score``)."""
        return self.evaluate(data).f1()

    # ------------------------------------------------ flat-param invariant
    def param_table(self) -> Dict[str, np.ndarray]:
        """Named params ``{"0_W": ..., "0_b": ...}`` (reference
        ``paramTable()`` naming)."""
        from ..utils.device import fetch_all
        self.init()
        dev = {}
        for i, layer in enumerate(self.layers):
            for name in layer.param_order():
                dev[f"{i}_{name}"] = self.params[i][name]
        return dict(zip(dev, fetch_all(dev.values())))

    def num_params(self) -> int:
        self.init()
        return sum(int(np.prod(p.shape))
                   for tree in self.params
                   for p in jax.tree_util.tree_leaves(tree))

    def get_flat_params(self) -> np.ndarray:
        """One contiguous vector over all params in deterministic layer/param
        order — the reference's single flat buffer (``init():396-470``)."""
        from ..utils.device import fetch_all
        self.init()
        dev = [self.params[i][name]
               for i, layer in enumerate(self.layers)
               for name in layer.param_order()]
        chunks = [a.ravel() for a in fetch_all(dev)]
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_flat_params(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for i, layer in enumerate(self.layers):
            for name in layer.param_order():
                shape = self.params[i][name].shape
                size = int(np.prod(shape))
                self.params[i][name] = jnp.asarray(
                    flat[offset:offset + size].reshape(shape),
                    self.params[i][name].dtype)
                offset += size
        if offset != flat.size:
            raise ValueError(
                f"Flat param size mismatch: expected {offset}, got {flat.size}")
        self._sync_masters_from_params()

    def _sync_masters_from_params(self) -> None:
        """Re-derive the fp32 masters from freshly-assigned params so the
        master/param coherence invariant holds after a direct param write
        (param averaging, solvers).  Checkpoint restore overwrites the
        masters afterwards with the exact saved fp32 values
        (set_flat_params runs before set_flat_updater_state)."""
        for i, tree in enumerate(self.updater_state):
            if isinstance(tree, dict) and _updaters.MASTER_KEY in tree:
                tree[_updaters.MASTER_KEY] = {
                    k: jnp.asarray(self.params[i][k], jnp.float32)
                    for k in tree[_updaters.MASTER_KEY]}

    def get_flat_updater_state(self) -> np.ndarray:
        """Updater state as one flat vector (reference
        ``BaseUpdater.getStateViewArray`` -> ``updaterState.bin``)."""
        self.init()
        leaves = []
        for tree in self.updater_state:
            leaves.extend(np.asarray(l).ravel()
                          for l in jax.tree_util.tree_leaves(tree))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_updater_state(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        new_states = []
        for tree in self.updater_state:
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            new_leaves = []
            for leaf in leaves:
                size = int(np.prod(leaf.shape))
                new_leaves.append(jnp.asarray(
                    flat[offset:offset + size].reshape(leaf.shape),
                    leaf.dtype))
                offset += size
            new_states.append(jax.tree_util.tree_unflatten(treedef, new_leaves))
        self.updater_state = new_states

    # -------------------------------------------------------------- misc API
    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def clone(self) -> "MultiLayerNetwork":
        """Config+params copy (reference ``clone()``)."""
        import copy
        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        other.init()
        # Materialize copies: the jitted train step donates the originals, so
        # shared references would be invalidated by the next fit().
        other.params = jax.tree.map(jnp.copy, self.params)
        other.net_state = jax.tree.map(jnp.copy, self.net_state)
        other.updater_state = jax.tree.map(jnp.copy, self.updater_state)
        other.iteration = self.iteration
        other._pretrain_done = self._pretrain_done
        return other
