"""MultiLayerNetwork: sequential network container.

TPU-native equivalent of the reference's
``nn/multilayer/MultiLayerNetwork.java`` (2527 LoC): ``init():384-470``
(flat params + per-layer views), ``fit(DataSetIterator):976``,
``computeGradientAndScore:1805``, ``output:1519-1601``, ``score:1705``.

Architecture: the reference materializes layer objects holding views over one
flat parameter buffer, then drives per-layer ``activate``/``backpropGradient``
loops from a ``Solver``.  Here the entire inner loop — forward, loss,
backward (``jax.grad``), updater — is ONE jitted function, so XLA compiles
the whole train step into a single HLO graph executed on the TPU (the north
star in BASELINE.json).  Params/updater-state are pytrees; ``params()``
exposes the reference's flat-vector invariant via deterministic raveling.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import updaters as _updaters
from .conf.neural_net_configuration import MultiLayerConfiguration
from ..datasets.dataset import DataSet

Array = jax.Array


class MultiLayerNetwork:
    """Sequential model: list of layer configs -> pure train/inference fns."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: List[Dict[str, Array]] = []
        self.net_state: List[Dict[str, Array]] = []
        self.updater_state: List[Dict[str, Any]] = []
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._init_done = False
        self._score = float("nan")
        self._rng_key: Optional[jax.Array] = None

    # ------------------------------------------------------------------ init
    def init(self) -> "MultiLayerNetwork":
        """Initialize params/state (reference ``init():384-470``)."""
        if self._init_done:
            return self
        dtype = jnp.dtype(self.conf.conf.dtype)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        self._rng_key = key
        keys = jax.random.split(key, len(self.layers) + 1)
        self.params = [
            layer.init_params(keys[i], dtype)
            for i, layer in enumerate(self.layers)
        ]
        self.net_state = [layer.init_state(dtype) for layer in self.layers]
        self.updater_state = [
            _updaters.init_state(self._updater_conf(i), self.params[i])
            for i in range(len(self.layers))
        ]
        self._init_done = True
        return self

    def _updater_conf(self, i: int) -> _updaters.UpdaterConfig:
        return self.layers[i].updater or self.conf.conf.updater

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, x, *, train: bool,
                 rng: Optional[jax.Array], mask=None,
                 to_layer: Optional[int] = None,
                 preoutput_last: bool = False):
        """Compose preprocessors + layers (reference ``feedForwardToLayer``).

        Returns (out, new_state).  With ``preoutput_last`` the final (output)
        layer contributes its pre-activation, letting the loss fuse
        softmax/sigmoid stably.
        """
        n = len(self.layers) if to_layer is None else to_layer + 1
        new_state = list(net_state)
        keys = (jax.random.split(rng, n) if rng is not None else [None] * n)
        compute_dtype = self.conf.conf.compute_dtype
        if jnp.issubdtype(x.dtype, jnp.floating):
            # Cast inputs to the model dtype (params dtype, or the bfloat16
            # compute dtype for MXU-friendly matmuls); integer inputs
            # (embedding indices) pass through.
            x = x.astype(jnp.dtype(compute_dtype or self.conf.conf.dtype))
        if compute_dtype:
            # Mixed precision: master params stay in the param dtype; compute
            # sees a bfloat16 copy (XLA fuses the casts into the matmul/conv).
            cast = jnp.dtype(compute_dtype)
            params = jax.tree.map(
                lambda p: p.astype(cast)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        for i in range(n):
            layer = self.layers[i]
            if i in self.conf.input_preprocessors:
                x = self.conf.input_preprocessors[i](x)
            if preoutput_last and i == n - 1 and hasattr(layer, "pre_output"):
                if layer.dropout and train:
                    x = layer.apply_dropout(x, train, keys[i])
                x = layer.pre_output(params[i], x)
            else:
                x, new_state[i] = layer.forward(
                    params[i], net_state[i], x, train=train, rng=keys[i],
                    mask=mask)
        if compute_dtype:
            x = x.astype(jnp.float32)
        return x, new_state

    # ----------------------------------------------------------------- loss
    def _loss_fn(self, params, net_state, features, labels, labels_mask,
                 rng, train: bool):
        """Data loss (+ new state).  Regularization is handled updater-side
        to match the reference order of operations (SURVEY.md §7 hard part d);
        the reported score adds the reg term separately
        (``BaseLayer.calcL2``)."""
        preout, new_state = self._forward(
            params, net_state, features, train=train, rng=rng,
            preoutput_last=True)
        out_layer = self.layers[-1]
        if not hasattr(out_layer, "compute_score"):
            raise ValueError(
                "Last layer must be an output/loss layer to fit()")
        data_loss = out_layer.compute_score(labels, preout, labels_mask,
                                            average=self.conf.conf.mini_batch)
        return data_loss, new_state

    def _reg_score(self, params) -> Array:
        total = jnp.asarray(0.0, jnp.float32)
        for i, layer in enumerate(self.layers):
            total = total + _updaters.regularization_score(
                params[i], layer.l1_by_param(), layer.l2_by_param())
        return total

    # ------------------------------------------------------------ train step
    @functools.cached_property
    def _train_step(self):
        """Build the jitted train step: fwd + bwd + updater in one XLA
        program.  Donation lets XLA update params/updater state in place in
        HBM (the analogue of the reference's in-place flat-buffer step)."""

        def step(params, updater_state, net_state, iteration, features,
                 labels, labels_mask, base_rng):
            rng = jax.random.fold_in(base_rng, iteration)
            (data_loss, new_state), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(
                    params, net_state, features, labels, labels_mask, rng,
                    True)
            new_params, new_updater_state = [], []
            for i, layer in enumerate(self.layers):
                uconf = self._updater_conf(i)
                g = grads[i]
                if g:
                    g = _updaters.regularize(g, params[i], layer.l1_by_param(),
                                             layer.l2_by_param())
                    g = _updaters.normalize_gradients(
                        g, layer.gradient_normalization,
                        layer.gradient_normalization_threshold)
                    updates, ustate = _updaters.compute_update(
                        uconf, g, updater_state[i], iteration)
                    new_params.append(jax.tree.map(
                        lambda p, u: p - u, params[i], updates))
                    new_updater_state.append(ustate)
                else:
                    new_params.append(params[i])
                    new_updater_state.append(updater_state[i])
            score = data_loss + self._reg_score(params)
            return new_params, new_updater_state, new_state, score

        return jax.jit(step, donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _score_fn(self):
        def score(params, net_state, features, labels, labels_mask):
            data_loss, _ = self._loss_fn(params, net_state, features, labels,
                                         labels_mask, None, False)
            return data_loss + self._reg_score(params)
        return jax.jit(score)

    @functools.cached_property
    def _output_fn(self):
        def run(params, net_state, features):
            out, _ = self._forward(params, net_state, features, train=False,
                                   rng=None)
            return out
        return jax.jit(run)

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1) -> "MultiLayerNetwork":
        """Train (reference ``fit(DataSetIterator):976`` /
        ``fit(INDArray,INDArray):1406``).

        ``data`` may be a DataSetIterator-like iterable of :class:`DataSet`,
        a single :class:`DataSet`, or a features array with ``labels``.
        """
        self.init()
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, DataSet):
            batches: Sequence[DataSet] = [data]
            iterator = None
        else:
            iterator = data
            batches = None

        for _ in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            it = batches if batches is not None else iterator
            if hasattr(it, "reset"):
                it.reset()
            for ds in it:
                self._fit_batch(ds)
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
            self.epoch += 1
        return self

    def _fit_batch(self, ds: DataSet) -> None:
        self.last_batch_size = ds.num_examples()
        features = jnp.asarray(ds.features)
        labels = jnp.asarray(ds.labels)
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        for _ in range(self.conf.conf.num_iterations):
            (self.params, self.updater_state, self.net_state,
             score) = self._train_step(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, lmask, self._rng_key)
            self._score = score
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    # ------------------------------------------------------------- inference
    def output(self, features, train: bool = False) -> np.ndarray:
        """Forward pass (reference ``output:1519-1601``; TEST mode: no
        dropout, BN running stats)."""
        self.init()
        out = self._output_fn(self.params, self.net_state,
                              jnp.asarray(features))
        return np.asarray(out)

    def feed_forward(self, features) -> List[np.ndarray]:
        """All layer activations (reference ``feedForward:655-747``)."""
        self.init()
        acts = []
        x = jnp.asarray(features)
        for i in range(len(self.layers)):
            x, _ = self._forward(self.params, self.net_state,
                                 jnp.asarray(features), train=False, rng=None,
                                 to_layer=i)
            acts.append(np.asarray(x))
        return acts

    def predict(self, features) -> np.ndarray:
        """Argmax class predictions (reference ``predict``)."""
        return np.argmax(self.output(features), axis=-1)

    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Mean loss on a dataset (reference ``score:1705``)."""
        if dataset is None:
            return float(self._score)
        self.init()
        lmask = (None if dataset.labels_mask is None
                 else jnp.asarray(dataset.labels_mask))
        val = self._score_fn(self.params, self.net_state,
                             jnp.asarray(dataset.features),
                             jnp.asarray(dataset.labels), lmask)
        return float(val)

    def evaluate(self, iterator):
        """Classification evaluation over an iterator (reference
        ``MultiLayerNetwork.evaluate``)."""
        from ..eval.evaluation import Evaluation
        ev = Evaluation()
        if isinstance(iterator, DataSet):
            iterator = [iterator]
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(np.asarray(ds.labels), out)
        return ev

    # ------------------------------------------------ flat-param invariant
    def param_table(self) -> Dict[str, np.ndarray]:
        """Named params ``{"0_W": ..., "0_b": ...}`` (reference
        ``paramTable()`` naming)."""
        self.init()
        out = {}
        for i, layer in enumerate(self.layers):
            for name in layer.param_order():
                out[f"{i}_{name}"] = np.asarray(self.params[i][name])
        return out

    def num_params(self) -> int:
        self.init()
        return sum(int(np.prod(p.shape))
                   for tree in self.params
                   for p in jax.tree_util.tree_leaves(tree))

    def get_flat_params(self) -> np.ndarray:
        """One contiguous vector over all params in deterministic layer/param
        order — the reference's single flat buffer (``init():396-470``)."""
        self.init()
        chunks = []
        for i, layer in enumerate(self.layers):
            for name in layer.param_order():
                chunks.append(np.asarray(self.params[i][name]).ravel())
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_flat_params(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for i, layer in enumerate(self.layers):
            for name in layer.param_order():
                shape = self.params[i][name].shape
                size = int(np.prod(shape))
                self.params[i][name] = jnp.asarray(
                    flat[offset:offset + size].reshape(shape),
                    self.params[i][name].dtype)
                offset += size
        if offset != flat.size:
            raise ValueError(
                f"Flat param size mismatch: expected {offset}, got {flat.size}")

    def get_flat_updater_state(self) -> np.ndarray:
        """Updater state as one flat vector (reference
        ``BaseUpdater.getStateViewArray`` -> ``updaterState.bin``)."""
        self.init()
        leaves = []
        for tree in self.updater_state:
            leaves.extend(np.asarray(l).ravel()
                          for l in jax.tree_util.tree_leaves(tree))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_updater_state(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        new_states = []
        for tree in self.updater_state:
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            new_leaves = []
            for leaf in leaves:
                size = int(np.prod(leaf.shape))
                new_leaves.append(jnp.asarray(
                    flat[offset:offset + size].reshape(leaf.shape),
                    leaf.dtype))
                offset += size
            new_states.append(jax.tree_util.tree_unflatten(treedef, new_leaves))
        self.updater_state = new_states

    # -------------------------------------------------------------- misc API
    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def clone(self) -> "MultiLayerNetwork":
        """Config+params copy (reference ``clone()``)."""
        import copy
        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        other.init()
        # Materialize copies: the jitted train step donates the originals, so
        # shared references would be invalidated by the next fit().
        other.params = jax.tree.map(jnp.copy, self.params)
        other.net_state = jax.tree.map(jnp.copy, self.net_state)
        other.updater_state = jax.tree.map(jnp.copy, self.updater_state)
        other.iteration = self.iteration
        return other
