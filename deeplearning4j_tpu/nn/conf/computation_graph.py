"""ComputationGraph configuration: DAG of named vertices.

TPU-native equivalent of the reference's
``nn/conf/ComputationGraphConfiguration.java`` (664 LoC) and its
``GraphBuilder`` (``addLayer:525``, ``addInputs:561``, ``setOutputs:589``,
``addVertex:605``, ``build:614``), plus the vertex configs in
``nn/conf/graph/`` (MergeVertex, ElementWiseVertex, SubsetVertex,
StackVertex, UnstackVertex, ScaleVertex, PreprocessorVertex, L2Vertex,
L2NormalizeVertex) and ``nn/conf/graph/rnn/`` (LastTimeStepVertex,
DuplicateToTimeSeriesVertex).

The reference materializes vertex objects and runs Kahn's algorithm at
runtime (``ComputationGraph.topologicalSortOrder():850``).  Here the topo
sort happens once at config build; execution is pure function composition
traced by jax, so the whole graph compiles to a single XLA program.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import inputs as _inputs
from . import serde
from ..layers.base import BaseLayerConfig

InputType = _inputs.InputType
Array = jax.Array


# --------------------------------------------------------------- vertices
@dataclasses.dataclass
class BaseVertex:
    """A DAG node: consumes the activations of ``inputs`` (vertex names),
    produces one activation.  Stateless vertices implement ``apply``;
    LayerVertex delegates to its layer config."""

    inputs: List[str] = dataclasses.field(default_factory=list)

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *xs: Array, masks=None) -> Array:
        raise NotImplementedError


@serde.register("vertex_layer")
@dataclasses.dataclass
class LayerVertex(BaseVertex):
    """Wraps a layer config (reference ``nn/conf/graph/LayerVertex.java``);
    optional input preprocessor applied before the layer."""

    layer: Optional[BaseLayerConfig] = None
    preprocessor: Optional[object] = None

    def output_type(self, *input_types: InputType) -> InputType:
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)


@serde.register("vertex_merge")
@dataclasses.dataclass
class MergeVertex(BaseVertex):
    """Concatenate along the feature (last) axis (reference
    ``MergeVertex.java`` merges along dimension 1 = channels/features; our
    layouts keep features last)."""

    def output_type(self, *input_types: InputType) -> InputType:
        first = input_types[0]
        if first.kind == "ff":
            return _inputs.feed_forward(sum(t.size for t in input_types))
        if first.kind == "recurrent":
            return _inputs.recurrent(sum(t.size for t in input_types),
                                     first.timesteps)
        if first.kind == "cnn":
            return _inputs.convolutional(
                first.height, first.width,
                sum(t.channels for t in input_types))
        raise ValueError(f"MergeVertex cannot merge {first.kind}")

    def apply(self, *xs: Array, masks=None) -> Array:
        return jnp.concatenate(xs, axis=-1)


@serde.register("vertex_elementwise")
@dataclasses.dataclass
class ElementWiseVertex(BaseVertex):
    """Pointwise combine (reference ``ElementWiseVertex.java``; ops Add,
    Subtract, Product; Average/Max added by later reference versions kept
    for completeness)."""

    op: str = "add"

    def apply(self, *xs: Array, masks=None) -> Array:
        op = self.op.lower()
        if op == "add":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(xs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return xs[0] - xs[1]
        if op == "product":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(xs) / len(xs)
        if op == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown elementwise op '{self.op}'")


@serde.register("vertex_subset")
@dataclasses.dataclass
class SubsetVertex(BaseVertex):
    """Feature slice [from, to] inclusive (reference ``SubsetVertex.java``)."""

    from_index: int = 0
    to_index: int = 0

    def output_type(self, *input_types: InputType) -> InputType:
        n = self.to_index - self.from_index + 1
        it = input_types[0]
        if it.kind == "recurrent":
            return _inputs.recurrent(n, it.timesteps)
        return _inputs.feed_forward(n)

    def apply(self, *xs: Array, masks=None) -> Array:
        return xs[0][..., self.from_index:self.to_index + 1]


@serde.register("vertex_stack")
@dataclasses.dataclass
class StackVertex(BaseVertex):
    """Concatenate along the batch axis (reference ``StackVertex.java``,
    used for weight-shared multi-branch input)."""

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *xs: Array, masks=None) -> Array:
        return jnp.concatenate(xs, axis=0)


@serde.register("vertex_unstack")
@dataclasses.dataclass
class UnstackVertex(BaseVertex):
    """Take batch slice ``from_index`` of ``stack_size`` equal chunks
    (reference ``UnstackVertex.java``)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, *xs: Array, masks=None) -> Array:
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@serde.register("vertex_scale")
@dataclasses.dataclass
class ScaleVertex(BaseVertex):
    """Multiply by a fixed scalar (reference ``ScaleVertex.java``)."""

    scale_factor: float = 1.0

    def apply(self, *xs: Array, masks=None) -> Array:
        return xs[0] * self.scale_factor


@serde.register("vertex_shift")
@dataclasses.dataclass
class ShiftVertex(BaseVertex):
    """Add a fixed scalar (reference ``ShiftVertex.java``)."""

    shift_factor: float = 0.0

    def apply(self, *xs: Array, masks=None) -> Array:
        return xs[0] + self.shift_factor


@serde.register("vertex_preprocessor")
@dataclasses.dataclass
class PreprocessorVertex(BaseVertex):
    """Standalone input preprocessor (reference ``PreprocessorVertex.java``)."""

    preprocessor: Optional[object] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return self.preprocessor.output_type(input_types[0])

    def apply(self, *xs: Array, masks=None) -> Array:
        return self.preprocessor(xs[0])


@serde.register("vertex_l2")
@dataclasses.dataclass
class L2Vertex(BaseVertex):
    """Pairwise L2 distance between two activations (reference
    ``L2Vertex.java``, used by siamese/triplet setups)."""

    eps: float = 1e-8

    def output_type(self, *input_types: InputType) -> InputType:
        return _inputs.feed_forward(1)

    def apply(self, *xs: Array, masks=None) -> Array:
        a, b = xs
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)


@serde.register("vertex_l2_normalize")
@dataclasses.dataclass
class L2NormalizeVertex(BaseVertex):
    """Normalize activations to unit L2 norm (reference
    ``L2NormalizeVertex.java``)."""

    eps: float = 1e-8

    def apply(self, *xs: Array, masks=None) -> Array:
        x = xs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.sqrt(jnp.sum(flat * flat, axis=1) + self.eps)
        return x / norm.reshape((-1,) + (1,) * (x.ndim - 1))


@serde.register("vertex_last_time_step")
@dataclasses.dataclass
class LastTimeStepVertex(BaseVertex):
    """(batch, time, f) -> (batch, f) at the last *unmasked* step (reference
    ``rnn/LastTimeStepVertex.java``; ``mask_input`` names the network input
    whose mask identifies sequence ends)."""

    mask_input: Optional[str] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return _inputs.feed_forward(input_types[0].size)

    def apply(self, *xs: Array, masks=None) -> Array:
        x = xs[0]
        mask = None if masks is None else masks.get(self.mask_input)
        if mask is None:
            return x[:, -1]
        idx = jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1
        idx = jnp.clip(idx, 0, x.shape[1] - 1)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


@serde.register("vertex_duplicate_to_time_series")
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(BaseVertex):
    """(batch, f) -> (batch, time, f), broadcast along the time axis of a
    reference input (reference ``rnn/DuplicateToTimeSeriesVertex.java``)."""

    reference_input: Optional[str] = None

    def output_type(self, *input_types: InputType) -> InputType:
        return _inputs.recurrent(input_types[0].flat_size())

    def apply(self, *xs: Array, masks=None, timesteps: Optional[int] = None
              ) -> Array:
        x = xs[0]
        if timesteps is None:
            raise ValueError("DuplicateToTimeSeriesVertex needs the "
                             "reference input's timestep count")
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], timesteps, x.shape[1]))


# ----------------------------------------------------------- configuration
@serde.register("computation_graph_conf")
@dataclasses.dataclass
class ComputationGraphConfiguration:
    """Reference ``ComputationGraphConfiguration``: named DAG + global conf."""

    conf: object = None                      # GlobalConfig
    network_inputs: List[str] = dataclasses.field(default_factory=list)
    network_outputs: List[str] = dataclasses.field(default_factory=list)
    vertices: Dict[str, BaseVertex] = dataclasses.field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 0
    input_types: Optional[List[object]] = None

    # topo order is derived, not serialized redundantly but recomputed
    def topological_order(self) -> List[str]:
        """Kahn's algorithm over vertex names (reference
        ``topologicalSortOrder():850``); deterministic (insertion order
        tie-break)."""
        indeg = {name: 0 for name in self.vertices}
        dependents: Dict[str, List[str]] = {n: [] for n in self.vertices}
        for name, v in self.vertices.items():
            for inp in v.inputs:
                if inp in self.vertices:
                    indeg[name] += 1
                    dependents[inp].append(name)
                elif inp not in self.network_inputs:
                    raise ValueError(
                        f"Vertex '{name}' consumes unknown input '{inp}'")
        queue = [n for n, d in indeg.items() if d == 0]
        order: List[str] = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for dep in dependents[n]:
                indeg[dep] -= 1
                if indeg[dep] == 0:
                    queue.append(dep)
        if len(order) != len(self.vertices):
            cyclic = sorted(set(self.vertices) - set(order))
            raise ValueError(f"Graph has a cycle involving {cyclic}")
        return order

    # ---- JSON round-trip -------------------------------------------------
    def to_dict(self) -> dict:
        return serde.to_dict(self)

    def to_json(self, indent: Optional[int] = 2) -> str:
        import json
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return serde.from_dict(d)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        import json
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """Reference ``ComputationGraphConfiguration.toYaml``."""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))


class GraphBuilder:
    """Reference ``ComputationGraphConfiguration.GraphBuilder`` fluent API."""

    def __init__(self, global_conf):
        self._cgc = ComputationGraphConfiguration(conf=global_conf)

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._cgc.network_inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: BaseLayerConfig,
                  *inputs: str, preprocessor=None) -> "GraphBuilder":
        """Reference ``addLayer(name, layer, [preprocessor,] inputs...)``."""
        self._cgc.vertices[name] = LayerVertex(
            inputs=list(inputs), layer=layer, preprocessor=preprocessor)
        return self

    # reference alias
    layer = add_layer

    def add_vertex(self, name: str, vertex: BaseVertex,
                   *inputs: str) -> "GraphBuilder":
        vertex.inputs = list(inputs)
        self._cgc.vertices[name] = vertex
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._cgc.network_outputs = list(names)
        return self

    def set_input_types(self, *input_types) -> "GraphBuilder":
        self._cgc.input_types = list(input_types)
        return self

    def backprop_type(self, kind: str) -> "GraphBuilder":
        self._cgc.backprop_type = kind.lower()
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._cgc.tbptt_fwd_length = int(n)
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._cgc.tbptt_back_length = int(n)
        return self

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._cgc.pretrain = flag
        return self

    def backprop(self, flag: bool) -> "GraphBuilder":
        self._cgc.backprop = flag
        return self

    def build(self) -> ComputationGraphConfiguration:
        cgc = self._cgc
        if not cgc.network_inputs:
            raise ValueError("addInputs() never called")
        if not cgc.network_outputs:
            raise ValueError("setOutputs() never called")
        for out in cgc.network_outputs:
            if out not in cgc.vertices:
                raise ValueError(f"Output '{out}' is not a vertex")
        defaults = cgc.conf.layer_defaults()
        for v in cgc.vertices.values():
            if isinstance(v, LayerVertex) and v.layer is not None:
                v.layer.finalize_defaults(defaults)
        if cgc.input_types is not None:
            _infer_graph_shapes(cgc)
        cgc.topological_order()  # validates acyclicity + unknown inputs
        from .validation import validate_computation_graph_configuration
        validate_computation_graph_configuration(cgc)
        return cgc


def _infer_graph_shapes(cgc: ComputationGraphConfiguration) -> None:
    """Propagate InputTypes through the DAG in topo order, setting each
    layer's n_in and auto-inserting family preprocessors (reference
    ``GraphBuilder.setInputTypes`` + ``addPreProcessors``)."""
    from .neural_net_configuration import _layer_input_kind, _preprocessor_for

    if len(cgc.input_types) != len(cgc.network_inputs):
        raise ValueError(
            f"{len(cgc.network_inputs)} inputs but "
            f"{len(cgc.input_types)} input types")
    types: Dict[str, InputType] = dict(zip(cgc.network_inputs,
                                           cgc.input_types))
    for name in cgc.topological_order():
        v = cgc.vertices[name]
        in_types = [types[i] for i in v.inputs]
        if isinstance(v, LayerVertex):
            it = in_types[0]
            if v.preprocessor is None:
                pp = _preprocessor_for(it, _layer_input_kind(v.layer))
                if pp is not None:
                    v.preprocessor = pp
            if v.preprocessor is not None:
                it = v.preprocessor.output_type(it)
            v.layer.set_n_in(it)
            types[name] = v.layer.output_type(it)
        else:
            types[name] = v.output_type(*in_types)
    cgc._inferred_types = types
