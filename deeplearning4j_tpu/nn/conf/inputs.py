"""Input-type system for shape inference.

TPU-native equivalent of the reference's ``nn/conf/inputs/InputType.java:52-84``
(``feedForward`` / ``recurrent`` / ``convolutional`` / ``convolutionalFlat``
factories).  Used by the list/graph builders to infer each layer's ``n_in``
from the declared network input and to auto-insert preprocessors between layer
families (``ListBuilder.setInputType`` — reference
``NeuralNetConfiguration.java:255``).

Layout note (TPU-first): convolutional activations are NHWC (XLA:TPU's
preferred layout) and recurrent activations are (batch, time, features) —
unlike the reference's NCHW / (batch, features, time).  Converters at the
serialization/import boundary handle the mapping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import serde


@serde.register("input_ff")
@dataclasses.dataclass
class InputTypeFeedForward:
    size: int = 0

    @property
    def kind(self) -> str:
        return "ff"

    def flat_size(self) -> int:
        return self.size


@serde.register("input_recurrent")
@dataclasses.dataclass
class InputTypeRecurrent:
    size: int = 0
    timesteps: int = -1  # -1 = variable (padded/bucketed at batch time)

    @property
    def kind(self) -> str:
        return "recurrent"

    def flat_size(self) -> int:
        return self.size


@serde.register("input_cnn")
@dataclasses.dataclass
class InputTypeConvolutional:
    height: int = 0
    width: int = 0
    channels: int = 0

    @property
    def kind(self) -> str:
        return "cnn"

    def flat_size(self) -> int:
        return self.height * self.width * self.channels


@serde.register("input_cnn_flat")
@dataclasses.dataclass
class InputTypeConvolutionalFlat:
    """Flattened image input (e.g. MNIST rows of 784) that should be reshaped
    to NHWC before the first conv layer (reference ``convolutionalFlat``)."""

    height: int = 0
    width: int = 0
    channels: int = 1

    @property
    def kind(self) -> str:
        return "cnn_flat"

    def flat_size(self) -> int:
        return self.height * self.width * self.channels


InputType = (InputTypeFeedForward | InputTypeRecurrent |
             InputTypeConvolutional | InputTypeConvolutionalFlat)


def feed_forward(size: int) -> InputTypeFeedForward:
    return InputTypeFeedForward(size)


def recurrent(size: int, timesteps: int = -1) -> InputTypeRecurrent:
    return InputTypeRecurrent(size, timesteps)


def convolutional(height: int, width: int, channels: int) -> InputTypeConvolutional:
    return InputTypeConvolutional(height, width, channels)


def convolutional_flat(height: int, width: int,
                       channels: int = 1) -> InputTypeConvolutionalFlat:
    return InputTypeConvolutionalFlat(height, width, channels)
