"""Layer/config validation.

TPU-native equivalent of the reference's ``util/LayerValidation.java``
(called during network init: per-layer nIn/nOut checks, learning-rate /
updater / regularization consistency warnings via ``generalValidation``).
Hard inconsistencies raise; suspicious-but-legal combinations log
warnings (matching the reference's warn-don't-fail stance)."""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger("deeplearning4j_tpu")

# The runtime (updaters.normalize_gradients) matches lowercased
# camelCase names; accept either spelling here by stripping separators.
_KNOWN_GRAD_NORM = {"none", "renormalizel2perlayer",
                    "renormalizel2perparamtype",
                    "clipelementwiseabsolutevalue",
                    "clipl2perlayer", "clipl2perparamtype"}


def _canon_grad_norm(name: str) -> str:
    return name.lower().replace("_", "")


def validate_layer(layer, index: Optional[int] = None,
                   name: Optional[str] = None,
                   require_shapes: bool = True) -> None:
    """Per-layer hard checks (reference ``LayerValidation.generalValidation``
    + the per-layer nIn/nOut assertions in ``FeedForwardLayer``).

    ``require_shapes=False`` skips the n_out-positive check — used when no
    input type was declared, so shape inference is deferred to network
    init (the reference also validates shapes at init time)."""
    where = name or (f"layer {index}" if index is not None
                     else type(layer).__name__)

    n_in = getattr(layer, "n_in", None)
    n_out = getattr(layer, "n_out", None)
    if require_shapes and n_out is not None and n_out <= 0:
        raise ValueError(f"{where}: n_out must be positive (got {n_out}); "
                         f"set n_out or provide an input type")
    if n_in is not None and n_in < 0:
        raise ValueError(f"{where}: n_in is negative ({n_in})")

    dropout = getattr(layer, "dropout", None)
    if dropout is not None and not 0.0 <= float(dropout) < 1.0:
        raise ValueError(f"{where}: dropout must be in [0, 1), got "
                         f"{dropout}")

    for reg in ("l1", "l2"):
        v = getattr(layer, reg, None)
        if v is not None and float(v) < 0:
            raise ValueError(f"{where}: {reg} must be >= 0, got {v}")

    # activation / loss resolvability — fail at build, not mid-training
    act = getattr(layer, "activation", None)
    if isinstance(act, str):
        from .. import activations
        activations.get(act)           # raises on unknown names
    loss = getattr(layer, "loss", None)
    if isinstance(loss, str):
        from .. import lossfunctions
        lossfunctions.get(loss)

    # warn-level checks (reference warns on likely-unintended combos)
    if dropout is not None and float(dropout) > 0.9:
        logger.warning("%s: dropout %.2f keeps <10%% of activations — "
                       "likely a keep-prob/drop-prob mixup", where, dropout)


def validate_multi_layer_configuration(mlc) -> None:
    """Whole-config checks, called from ``ListBuilder.build`` (reference
    calls LayerValidation during MultiLayerNetwork.init)."""
    shapes_known = mlc.input_type is not None
    for i, layer in enumerate(mlc.layers):
        validate_layer(layer, index=i, require_shapes=shapes_known)
    validate_global(mlc.conf)
    if getattr(mlc, "backprop_type", "standard") == "tbptt":
        if mlc.tbptt_fwd_length is not None and mlc.tbptt_fwd_length <= 0:
            raise ValueError("tbptt_fwd_length must be positive under "
                             "tbptt backprop")
        if mlc.tbptt_back_length is not None and mlc.tbptt_back_length < 0:
            raise ValueError("tbptt_back_length must be >= 0 (0 = same "
                             "as forward)")


def validate_global(conf) -> None:
    gn = getattr(conf, "gradient_normalization", None)
    if isinstance(gn, str) and _canon_grad_norm(gn) not in _KNOWN_GRAD_NORM:
        raise ValueError(f"unknown gradient_normalization {gn!r}")


def validate_computation_graph_configuration(cgc) -> None:
    """Graph-config twin of the list validation (same checks per
    LayerVertex layer)."""
    shapes_known = cgc.input_types is not None
    for name, v in cgc.vertices.items():
        layer = getattr(v, "layer", None)
        if layer is not None:
            validate_layer(layer, name=f"vertex {name!r}",
                           require_shapes=shapes_known)
    validate_global(cgc.conf)
