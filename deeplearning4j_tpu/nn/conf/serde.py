"""JSON serde registry for config polymorphism.

The reference uses Jackson subtype polymorphism to round-trip layer/vertex/
preprocessor configs through JSON/YAML (``MultiLayerConfiguration.toJson`` /
``fromJson`` — reference ``nn/conf/MultiLayerConfiguration.java:79-124``).
Here every config dataclass registers under a type name and serializes to a
``{"type": <name>, ...fields}`` dict; nested dataclasses recurse.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Type, TypeVar

T = TypeVar("T")

_REGISTRY: Dict[str, type] = {}


def register(type_name: str, custom: bool = False):
    """Class decorator registering a config dataclass for polymorphic serde.

    ``custom=True`` classes provide their own ``to_dict``/``from_dict``
    (e.g. to keep integer schedule keys) and are wrapped, not introspected.
    """

    def wrap(cls):
        cls._serde_type = type_name
        cls._serde_custom = custom
        _REGISTRY[type_name] = cls
        return cls

    return wrap


def register_class(cls, type_name: str, custom: bool = False):
    """Imperative form of :func:`register` for classes defined in modules
    that must not import this one (avoids circular imports)."""
    return register(type_name, custom)(cls)


def _encode(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        if getattr(value, "_serde_custom", False):
            d = value.to_dict()
            d["type"] = value._serde_type
            return d
        d = {}
        if hasattr(value, "_serde_type"):
            d["type"] = value._serde_type
        for f in dataclasses.fields(value):
            d[f.name] = _encode(getattr(value, f.name))
        return d
    if isinstance(value, dict):
        return {str(k): _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    return value


def to_dict(obj: Any) -> Any:
    return _encode(obj)


def from_dict(d: Any, cls: Type[T] | None = None) -> Any:
    """Decode a dict produced by :func:`to_dict`.

    Polymorphic dicts carry a ``type`` key resolved via the registry;
    otherwise ``cls`` must be given.
    """
    if isinstance(d, dict) and "type" in d and d["type"] in _REGISTRY:
        cls = _REGISTRY[d["type"]]
    if cls is None or not dataclasses.is_dataclass(cls):
        return d
    if getattr(cls, "_serde_custom", False):
        return cls.from_dict({k: v for k, v in d.items() if k != "type"})
    kwargs = {}
    hints = {f.name: f for f in dataclasses.fields(cls)}
    for key, value in d.items():
        if key == "type" or key not in hints:
            continue
        f = hints[key]
        if isinstance(value, dict) and "type" in value and value["type"] in _REGISTRY:
            kwargs[key] = from_dict(value)
        elif isinstance(value, list):
            kwargs[key] = [
                from_dict(v) if isinstance(v, dict) and "type" in v else v
                for v in value
            ]
        elif isinstance(value, dict) and dataclasses.is_dataclass(_field_type(f)):
            kwargs[key] = from_dict(value, _field_type(f))
        elif isinstance(value, dict):
            # Plain mapping whose values may be polymorphic configs
            # (e.g. ComputationGraphConfiguration.vertices).
            kwargs[key] = {
                k: (from_dict(v) if isinstance(v, dict) and "type" in v
                    else v)
                for k, v in value.items()}
        else:
            kwargs[key] = value
    # tuples serialized as lists: coerce back where the default is a tuple
    for name, f in hints.items():
        if name in kwargs and isinstance(kwargs[name], list):
            default = _field_default(f)
            if isinstance(default, tuple):
                kwargs[name] = tuple(kwargs[name])
    return cls(**kwargs)


def _field_type(f: dataclasses.Field):
    t = f.type
    if isinstance(t, str):
        return None  # forward-ref string annotations handled via registry
    return t if dataclasses.is_dataclass(t) else None


def _field_default(f: dataclasses.Field):
    if f.default is not dataclasses.MISSING:
        return f.default
    if f.default_factory is not dataclasses.MISSING:  # type: ignore
        return f.default_factory()  # type: ignore
    return None


def registry() -> Dict[str, type]:
    return dict(_REGISTRY)
