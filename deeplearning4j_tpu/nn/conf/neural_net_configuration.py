"""Network configuration: global hyperparameters, fluent builder, and the
multi-layer configuration with JSON round-trip.

TPU-native equivalent of the reference's ``nn/conf/NeuralNetConfiguration.java``
(builder methods at 521-900), ``nn/conf/MultiLayerConfiguration.java``
(``toJson``/``fromJson`` at 79-124), ``BackpropType``, and the
``ListBuilder.setInputType`` shape-inference pass
(``NeuralNetConfiguration.java:255``) that infers each layer's ``n_in`` and
auto-inserts preprocessors between layer families.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from ..updaters import UpdaterConfig
from ..weights import Distribution
from . import inputs as _inputs
from . import preprocessors as _pp
from . import serde
from ..layers.base import BaseLayerConfig

InputType = _inputs.InputType


@serde.register("global_conf")
@dataclasses.dataclass
class GlobalConfig:
    """Network-level defaults cloned into layers unless overridden
    (reference ``NeuralNetConfiguration`` fields)."""

    seed: int = 12345
    num_iterations: int = 1
    optimization_algo: str = "stochastic_gradient_descent"
    mini_batch: bool = True          # average score/grads over batch
    # stored+serialized but intentionally unconsumed: the reference's 0.7.3
    # optimize path also never reads it (step direction comes from the
    # optimizer's step function, BaseOptimizer.getDefaultStepFunctionForOptimizer)
    minimize: bool = True
    dtype: str = "float32"           # param dtype; bfloat16 compute opt-in
    compute_dtype: Optional[str] = None  # e.g. "bfloat16" for MXU-friendly matmuls
    updater: UpdaterConfig = dataclasses.field(default_factory=UpdaterConfig)
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    dist: Optional[Distribution] = None
    bias_init: float = 0.0
    dropout: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0

    def layer_defaults(self) -> Dict[str, object]:
        return {
            "activation": self.activation,
            "weight_init": self.weight_init,
            "dist": self.dist,
            "bias_init": self.bias_init,
            "dropout": self.dropout,
            "l1": self.l1,
            "l2": self.l2,
            "l1_bias": self.l1_bias,
            "l2_bias": self.l2_bias,
            "updater": self.updater,
            "gradient_normalization": (
                None if self.gradient_normalization in ("none", None)
                else self.gradient_normalization),
        }


@serde.register("multi_layer_conf")
@dataclasses.dataclass
class MultiLayerConfiguration:
    """Reference ``MultiLayerConfiguration``: ordered layer configs +
    per-boundary input preprocessors + backprop settings."""

    conf: GlobalConfig = dataclasses.field(default_factory=GlobalConfig)
    layers: List[BaseLayerConfig] = dataclasses.field(default_factory=list)
    input_preprocessors: Dict[int, object] = dataclasses.field(
        default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"      # standard | tbptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 0           # 0 = same as tbptt_fwd_length
    input_type: Optional[object] = None

    # ---- JSON round-trip (reference MultiLayerConfiguration.java:79-124) --
    def to_dict(self) -> dict:
        return serde.to_dict(self)

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        out = serde.from_dict(d)
        out.input_preprocessors = {
            int(k): v for k, v in out.input_preprocessors.items()}
        return out

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """Reference ``MultiLayerConfiguration.toYaml:79-124``."""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))


class NeuralNetConfiguration:
    """Namespace mirroring the reference entry point:
    ``NeuralNetConfiguration.Builder()`` starts a fluent config chain."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()

    Builder = None  # assigned below


class Builder:
    """Fluent global-hyperparameter builder (reference
    ``NeuralNetConfiguration.Builder``, methods at 521-900)."""

    def __init__(self):
        self._g = GlobalConfig()

    # -- core ---------------------------------------------------------------
    def seed(self, seed: int) -> "Builder":
        self._g.seed = int(seed)
        return self

    def iterations(self, n: int) -> "Builder":
        self._g.num_iterations = int(n)
        return self

    def optimization_algo(self, algo: str) -> "Builder":
        self._g.optimization_algo = algo.lower()
        return self

    def mini_batch(self, flag: bool) -> "Builder":
        self._g.mini_batch = flag
        return self

    def minimize(self, flag: bool = True) -> "Builder":
        self._g.minimize = flag
        return self

    def dtype(self, dtype: str) -> "Builder":
        self._g.dtype = dtype
        return self

    def compute_dtype(self, dtype: str) -> "Builder":
        """bfloat16 compute for MXU-friendly matmuls (TPU-first extension)."""
        self._g.compute_dtype = dtype
        return self

    # -- updater / lr -------------------------------------------------------
    def updater(self, updater: str | UpdaterConfig) -> "Builder":
        if isinstance(updater, UpdaterConfig):
            self._g.updater = updater
        else:
            self._g.updater.updater = updater.lower()
        return self

    def learning_rate(self, lr: float) -> "Builder":
        self._g.updater.learning_rate = float(lr)
        return self

    def learning_rate_decay_policy(self, policy: str) -> "Builder":
        self._g.updater.lr_policy = policy.lower()
        return self

    def lr_policy_decay_rate(self, rate: float) -> "Builder":
        self._g.updater.lr_policy_decay_rate = float(rate)
        return self

    def lr_policy_power(self, power: float) -> "Builder":
        self._g.updater.lr_policy_power = float(power)
        return self

    def lr_policy_steps(self, steps: float) -> "Builder":
        self._g.updater.lr_policy_steps = float(steps)
        return self

    def learning_rate_schedule(self, schedule: Dict[int, float]) -> "Builder":
        self._g.updater.lr_schedule = dict(schedule)
        self._g.updater.lr_policy = "schedule"
        return self

    def momentum(self, momentum: float) -> "Builder":
        self._g.updater.momentum = float(momentum)
        return self

    def momentum_after(self, schedule: Dict[int, float]) -> "Builder":
        self._g.updater.momentum_schedule = dict(schedule)
        return self

    def rms_decay(self, decay: float) -> "Builder":
        self._g.updater.rms_decay = float(decay)
        return self

    def adam_mean_decay(self, b1: float) -> "Builder":
        self._g.updater.adam_mean_decay = float(b1)
        return self

    def adam_var_decay(self, b2: float) -> "Builder":
        self._g.updater.adam_var_decay = float(b2)
        return self

    def rho(self, rho: float) -> "Builder":
        self._g.updater.rho = float(rho)
        return self

    def epsilon(self, eps: float) -> "Builder":
        self._g.updater.epsilon = float(eps)
        return self

    # -- layer defaults ------------------------------------------------------
    def activation(self, name: str) -> "Builder":
        self._g.activation = name.lower()
        return self

    def weight_init(self, scheme: str) -> "Builder":
        self._g.weight_init = scheme.lower()
        return self

    def dist(self, dist: Distribution) -> "Builder":
        self._g.dist = dist
        self._g.weight_init = "distribution"
        return self

    def bias_init(self, value: float) -> "Builder":
        self._g.bias_init = float(value)
        return self

    def drop_out(self, p: float) -> "Builder":
        self._g.dropout = float(p)
        return self

    def regularization(self, flag: bool = True) -> "Builder":
        # Reference gate for l1/l2; here l1/l2 > 0 implies regularization.
        return self

    def l1(self, value: float) -> "Builder":
        self._g.l1 = float(value)
        return self

    def l2(self, value: float) -> "Builder":
        self._g.l2 = float(value)
        return self

    def l1_bias(self, value: float) -> "Builder":
        self._g.l1_bias = float(value)
        return self

    def l2_bias(self, value: float) -> "Builder":
        self._g.l2_bias = float(value)
        return self

    def gradient_normalization(self, mode: str,
                               threshold: float = 1.0) -> "Builder":
        self._g.gradient_normalization = mode
        self._g.gradient_normalization_threshold = float(threshold)
        return self

    # -- transition to layer list -------------------------------------------
    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self):
        """Start a ComputationGraph config (reference
        ``ComputationGraphConfiguration.GraphBuilder``)."""
        from .computation_graph import GraphBuilder
        return GraphBuilder(self._g)

    def build_global(self) -> GlobalConfig:
        return self._g


NeuralNetConfiguration.Builder = Builder


class ListBuilder:
    """Reference ``NeuralNetConfiguration.ListBuilder``: ordered layers +
    optional input type, producing a ``MultiLayerConfiguration``."""

    def __init__(self, global_conf: GlobalConfig):
        self._mlc = MultiLayerConfiguration(conf=global_conf)

    def layer(self, index_or_layer, layer: Optional[BaseLayerConfig] = None
              ) -> "ListBuilder":
        """``layer(conf)`` appends; ``layer(i, conf)`` sets position i
        (reference signature)."""
        if layer is None:
            self._mlc.layers.append(index_or_layer)
        else:
            idx = int(index_or_layer)
            while len(self._mlc.layers) <= idx:
                self._mlc.layers.append(None)  # type: ignore
            self._mlc.layers[idx] = layer
        return self

    def input_preprocessor(self, index: int, pp) -> "ListBuilder":
        self._mlc.input_preprocessors[int(index)] = pp
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._mlc.backprop = flag
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._mlc.pretrain = flag
        return self

    def backprop_type(self, kind: str) -> "ListBuilder":
        self._mlc.backprop_type = kind.lower()
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._mlc.tbptt_fwd_length = int(n)
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._mlc.tbptt_back_length = int(n)
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._mlc.input_type = input_type
        return self

    def build(self) -> MultiLayerConfiguration:
        mlc = self._mlc
        if any(l is None for l in mlc.layers):
            raise ValueError("Gaps in layer list (layer(i, ...) skipped an index)")
        defaults = mlc.conf.layer_defaults()
        for layer in mlc.layers:
            layer.finalize_defaults(defaults)
        if mlc.input_type is not None:
            _infer_shapes(mlc)
        from .validation import validate_multi_layer_configuration
        validate_multi_layer_configuration(mlc)
        return mlc


def _layer_input_kind(layer: BaseLayerConfig) -> str:
    """Which activation family the layer consumes: ff | cnn | rnn | any."""
    return getattr(layer, "INPUT_KIND", "ff")


def _infer_shapes(mlc: MultiLayerConfiguration) -> None:
    """Walk the layer list, auto-inserting preprocessors at family boundaries
    and setting each layer's n_in (reference ``ListBuilder.build`` calling
    ``InputType`` inference + ``getPreProcessorForInputType``)."""
    current = mlc.input_type
    for i, layer in enumerate(mlc.layers):
        if i not in mlc.input_preprocessors:
            pp = _preprocessor_for(current, _layer_input_kind(layer))
            if pp is not None:
                mlc.input_preprocessors[i] = pp
        if i in mlc.input_preprocessors:
            current = mlc.input_preprocessors[i].output_type(current)
        layer.set_n_in(current)
        current = layer.output_type(current)


def _preprocessor_for(input_type: InputType, want: str):
    """Pick the adapter between an incoming InputType and a layer family
    (reference per-InputType ``getPreProcessorForInputType...``)."""
    kind = input_type.kind
    if want == "any" or kind == want or (kind, want) == ("recurrent", "rnn"):
        return None
    if kind == "cnn_flat":
        if want == "cnn":
            return _pp.FlatToCnnPreProcessor(
                input_type.height, input_type.width, input_type.channels)
        if want == "ff":
            return None  # already flat rows
    if kind == "cnn" and want == "ff":
        return _pp.CnnToFeedForwardPreProcessor(
            input_type.height, input_type.width, input_type.channels)
    if kind == "ff" and want == "cnn":
        raise ValueError(
            "Cannot infer H/W/C for ff->cnn; add FeedForwardToCnnPreProcessor "
            "explicitly via input_preprocessor()")
    if kind == "recurrent" and want == "ff":
        return _pp.RnnToFeedForwardPreProcessor()
    if kind == "ff" and want == "rnn":
        return _pp.FeedForwardToRnnPreProcessor()
    if kind == "cnn" and want == "rnn":
        return _pp.CnnToRnnPreProcessor()
    if kind == "recurrent" and want == "cnn":
        raise ValueError(
            "Cannot infer H/W/C for rnn->cnn; add RnnToCnnPreProcessor "
            "explicitly")
    raise ValueError(f"No preprocessor from {kind} to {want}")
