"""Input preprocessors: shape adapters between layer families.

TPU-native equivalent of the reference's ``nn/conf/preprocessor/`` (13
classes — CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
RnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor,
CnnToRnnPreProcessor, RnnToCnnPreProcessor, ReshapePreProcessor, ...).

In the reference each preprocessor implements both ``preProcess`` (forward)
and ``backprop`` (reverse reshape of epsilons); here only the forward reshape
is needed — reshapes are differentiable and XLA treats them as free layout
ops.  Layouts are TPU-first: CNN activations are NHWC, RNN activations are
(batch, time, features).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import inputs as _inputs
from . import serde

Array = jax.Array
InputType = _inputs.InputType


@dataclasses.dataclass
class BasePreProcessor:
    def __call__(self, x: Array) -> Array:
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError


@serde.register("cnn_to_ff")
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(BasePreProcessor):
    """(batch, H, W, C) -> (batch, H*W*C).  Reference
    ``CnnToFeedForwardPreProcessor`` (which flattens NCHW; layout differs but
    the flat size and semantics match)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x: Array) -> Array:
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.feed_forward(input_type.flat_size())


@serde.register("ff_to_cnn")
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(BasePreProcessor):
    """(batch, H*W*C) -> (batch, H, W, C)."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x: Array) -> Array:
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.convolutional(self.height, self.width, self.channels)


@serde.register("rnn_to_ff")
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(BasePreProcessor):
    """(batch, time, features) -> (batch*time, features).

    Reference ``RnnToFeedForwardPreProcessor`` flattens the time axis so
    dense layers apply per-timestep; the inverse restores it.
    """

    def __call__(self, x: Array) -> Array:
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.feed_forward(input_type.flat_size())


@serde.register("ff_to_rnn")
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(BasePreProcessor):
    """(batch*time, features) -> (batch, time, features); ``timesteps`` must
    be known (set at network input or carried through)."""

    timesteps: int = -1

    def __call__(self, x: Array) -> Array:
        if self.timesteps <= 0:
            raise ValueError("FeedForwardToRnnPreProcessor needs timesteps")
        return x.reshape(-1, self.timesteps, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.recurrent(input_type.flat_size(), self.timesteps)


@serde.register("cnn_to_rnn")
@dataclasses.dataclass
class CnnToRnnPreProcessor(BasePreProcessor):
    """(batch*time, H, W, C) -> (batch, time, H*W*C) (reference
    ``CnnToRnnPreProcessor``)."""

    timesteps: int = -1

    def __call__(self, x: Array) -> Array:
        feat = x.shape[1] * x.shape[2] * x.shape[3]
        return x.reshape(-1, self.timesteps, feat)

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.recurrent(input_type.flat_size(), self.timesteps)


@serde.register("rnn_to_cnn")
@dataclasses.dataclass
class RnnToCnnPreProcessor(BasePreProcessor):
    """(batch, time, H*W*C) -> (batch*time, H, W, C)."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x: Array) -> Array:
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.convolutional(self.height, self.width, self.channels)


@serde.register("reshape")
@dataclasses.dataclass
class ReshapePreProcessor(BasePreProcessor):
    """Arbitrary reshape keeping the batch axis (reference
    ``ReshapePreProcessor``); ``shape`` excludes the batch dim."""

    shape: tuple = ()

    def __call__(self, x: Array) -> Array:
        return x.reshape((x.shape[0],) + tuple(self.shape))

    def output_type(self, input_type: InputType) -> InputType:
        shape = tuple(self.shape)
        if len(shape) == 1:
            return _inputs.feed_forward(shape[0])
        if len(shape) == 2:
            return _inputs.recurrent(shape[1], shape[0])
        if len(shape) == 3:
            return _inputs.convolutional(*shape)
        raise ValueError(f"Cannot infer InputType from shape {shape}")


@serde.register("flat_to_cnn")
@dataclasses.dataclass
class FlatToCnnPreProcessor(BasePreProcessor):
    """(batch, H*W*C) flat image rows -> NHWC, for ``convolutionalFlat``
    inputs (reference handles this inside ``FeedForwardToCnnPreProcessor``
    when built from ``InputType.convolutionalFlat``)."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x: Array) -> Array:
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.convolutional(self.height, self.width, self.channels)
