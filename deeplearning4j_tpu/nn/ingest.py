"""Overlapped ingest for ``fit(iterator)``: device-resident epoch cache
and windowed double-buffered staging.

The reference hides ETL behind compute with a prefetch thread
(``datasets/iterator/AsyncDataSetIterator.java`` feeding
``MultiLayerNetwork.fit:976-980``).  On a TPU behind a host<->device
link, the analogous wins are:

1. **Device-resident epoch cache** — a dataset that fits in HBM is
   uploaded ONCE and stays resident across epochs; each epoch's
   permutation is computed ON DEVICE (threefry keyed off the fit RNG)
   inside the same ``lax.scan`` dispatch that gathers and trains, so
   steady-state epochs have ZERO per-epoch host->device traffic — not
   even the index upload v1 paid.  Consecutive epochs additionally
   fuse into one dispatch (bounded by
   :func:`max_steps_per_dispatch`) when no listeners need per-epoch
   callbacks and there is no tail batch.
2. **Windowed staging** — datasets that do not fit HBM stream in
   multi-batch windows: the host stacks window k+1 and enqueues its
   transfer while window k's multi-step scan runs on-chip (JAX async
   dispatch provides the overlap; nothing blocks until scores are
   fetched).

Both paths ship the **uint8 wire** when the source carries one
(``datasets/dataset.attach_wire``): integer-pixel datasets upload 1
byte/pixel — 4x fewer bytes than float32 (47 MB instead of 188 MB for
MNIST-60k) — and the ``f32(u8)/denom*mult+add`` decode is fused into
the first ops of the compiled train step (:func:`device_decode`).  The
decode replicates the host's float32 op order exactly, so wire and
non-wire paths are BIT-EXACT for both float32 and bfloat16 compute
(parity-tested; ``DL4J_TPU_WIRE_UINT8=0`` is the escape hatch).

Both paths preserve per-iteration listener semantics by REPLAY: the
scan returns per-step scores, and listeners fire once per underlying
iteration with the exact score of that step (params seen by a replayed
listener are end-of-dispatch params — the documented divergence, same
compromise as ``fit_scan``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import List, Optional, Tuple

import numpy as np

from .. import monitor as _monitor
from ..datasets.dataset import wire_enabled, wire_of

#: Datasets larger than this (features + labels bytes) never device-cache.
#: Default 2 GB leaves headroom on a 16 GB-HBM chip for params, updater
#: state, activations and the scan's score stack.
DEVICE_CACHE_LIMIT_BYTES = int(os.environ.get(
    "DL4J_TPU_DEVICE_CACHE_LIMIT", 2_000_000_000))

_CACHEABLE_DTYPES = ("float32", "bfloat16")


def max_steps_per_dispatch() -> int:
    """Upper bound on scan steps folded into ONE epoch-cache dispatch
    (``DL4J_TPU_MAX_STEPS_PER_DISPATCH``, default 1024).  Bounds both
    the scanned score stack's HBM footprint and how long listeners can
    lag behind the chip when epochs fuse."""
    return int(os.environ.get("DL4J_TPU_MAX_STEPS_PER_DISPATCH", 1024))


def _scaler_wire(preprocessor, features: np.ndarray):
    """(u8, fmt) when ``preprocessor`` is an affine pixel scaler over
    uint8 features — the one preprocessor whose transform the device
    decode can reproduce bit-exactly — else None."""
    from ..datasets.normalizers import wire_format_of
    if preprocessor is None or features.dtype != np.uint8:
        return None
    fmt = wire_format_of(preprocessor)
    return None if fmt is None else (features, fmt)


def cacheable_source(iterator):
    """Return the underlying ``ListDataSetIterator`` when ``iterator``
    can be served by the device-resident epoch cache, else ``None``.

    Mirrors the eligibility posture of the native-prefetch takeover in
    ``datasets/iterators.py``: exact ``ListDataSetIterator`` iteration
    semantics only (a subclass overriding ``__next__``/``reset`` keeps
    its override by falling back), dense float features/labels, no
    masks, and total bytes under :data:`DEVICE_CACHE_LIMIT_BYTES`.
    Preprocessors disqualify — with ONE exception: an affine pixel
    scaler (``ImagePreProcessingScaler``) over uint8 features, whose
    transform IS the uint8 wire decode and therefore fuses into the
    compiled step (wire enabled only).
    """
    from ..datasets.iterators import (AsyncDataSetIterator,
                                      ListDataSetIterator)
    u = iterator
    if isinstance(u, AsyncDataSetIterator):
        if u.get_preprocessor() is not None:
            return None
        u = u._under
    if not isinstance(u, ListDataSetIterator):
        return None
    if (type(u).__next__ is not ListDataSetIterator.__next__
            or type(u).reset is not ListDataSetIterator.reset):
        return None
    ds = u._ds
    if ds.features is None or ds.labels is None:
        return None
    if ds.features_mask is not None or ds.labels_mask is not None:
        return None
    f = np.asarray(ds.features)
    l = np.asarray(ds.labels)
    if u.get_preprocessor() is not None:
        if not (wire_enabled()
                and _scaler_wire(u.get_preprocessor(), f) is not None):
            return None
    elif f.dtype.name not in _CACHEABLE_DTYPES:
        return None
    if l.dtype.name not in _CACHEABLE_DTYPES:
        return None
    if f.nbytes + l.nbytes > DEVICE_CACHE_LIMIT_BYTES:
        return None
    return u


def device_cached_arrays(model, ds, preprocessor=None) -> Tuple:
    """``(dev_features, dev_labels, wire_spec)`` device copies of ``ds``
    that stay resident ACROSS ``fit()`` calls (true epoch-cache
    residency: without this, every fit() re-paid the full dataset
    host->device transfer, which dominated end-to-end throughput over
    the tunnel).

    When ``ds`` carries a uint8 wire twin (or ``preprocessor`` is an
    affine pixel scaler over uint8 features) and the wire is enabled,
    the UINT8 buffer is what gets uploaded — 4x fewer bytes than
    float32 — and ``wire_spec`` is the ``(denom, mult, add)`` float
    triple whose on-device decode (:func:`device_decode`) reproduces
    the float32 features bit-exactly.  ``wire_spec`` is None when the
    float32 arrays shipped as-is.

    The cache lives on the model and is keyed by host-array identity
    (plus the wire decision, so flipping ``DL4J_TPU_WIRE_UINT8``
    between fits re-uploads): it holds references to the exact
    feature/label ndarrays it uploaded, so re-use requires ``ds`` to
    still expose those same objects; assigning new arrays re-uploads.
    In-place mutation of the same arrays between fits is NOT detected —
    matching the reference's posture that a dataset is immutable while
    training on it."""
    import jax.numpy as jnp
    f = np.asarray(ds.features)
    l = np.asarray(ds.labels)
    wire = None
    if wire_enabled():
        w = wire_of(ds)
        if w is not None and w[0].shape == f.shape:
            wire = w
        else:
            wire = _scaler_wire(preprocessor, f)
    fmt = None if wire is None else wire[1]
    cache = getattr(model, "_ingest_device_cache", None)
    if (cache is not None and cache[0] is f and cache[1] is l
            and cache[2] == fmt):
        return cache[3], cache[4], cache[5]
    if wire is not None:
        dev_f = jnp.asarray(np.ascontiguousarray(wire[0]))
        wire_spec = fmt.as_tuple()
    else:
        dev_f = jnp.asarray(f)
        wire_spec = None
    dev_l = jnp.asarray(l)
    _monitor.gauge(
        "ingest_staged_bytes",
        "bytes uploaded to the device per staging event").set(
        dev_f.nbytes + dev_l.nbytes, path="cache")
    model._ingest_device_cache = (f, l, fmt, dev_f, dev_l, wire_spec)
    return dev_f, dev_l, wire_spec


def device_decode(f, wire):
    """Fused on-device wire decode: ``f32(u8) / denom * mult + add``.
    Applied unconditionally (all three ops) so the program shape never
    depends on the wire VALUES — ``/1.0``, ``*1.0`` and ``+0.0`` are
    exact float32 identities for the non-negative pixel range.  The op
    order replicates the host readers' numpy float32 arithmetic
    (``u8.astype(f32) / 255.0``; ``ImagePreProcessingScaler.transform``)
    operation for operation, and IEEE-754 round-to-nearest-even makes
    each op bit-identical between numpy and XLA — the root of the
    wire-vs-float32 parity guarantee.  ``wire`` is a ``(denom, mult,
    add)`` python-float triple (weak-typed scalars: values never force
    a retrace) or None for pass-through."""
    if wire is None:
        return f
    import jax.numpy as jnp
    denom, mult, add = wire
    return f.astype(jnp.float32) / denom * mult + add


def consume_epoch(u) -> None:
    """Advance ``u`` through one epoch's worth of state transitions
    without materializing any batches.  The canonical ``fit(iterator)``
    path resets twice per epoch (the explicit ``it.reset()`` plus
    ``__iter__``'s reset), so the cache path performs the same two
    transitions and then marks the iterator consumed — external
    observers (and a later fall-back to the per-batch path) see the
    same iterator state.  The example ORDER itself comes from the
    on-device threefry permutation stream, not from the iterator's
    host RNG."""
    u.reset()
    u.reset()
    u._pos = u._ds.num_examples()


def epoch_index_batches(order: np.ndarray,
                        batch: int) -> List[np.ndarray]:
    """Split an epoch permutation into (S, B) full-batch indices plus an
    optional (1, tail) remainder — the same batch boundaries
    ``ListDataSetIterator.__next__`` produces."""
    n = order.shape[0]
    s, tail = divmod(n, batch)
    out = []
    if s:
        out.append(order[:s * batch].reshape(s, batch).astype(np.int32))
    if tail:
        out.append(order[s * batch:].reshape(1, tail).astype(np.int32))
    return out


def window_signature(ds) -> Tuple:
    """Shape/mask-presence signature of a DataSet; a window only stacks
    batches with identical signatures (a change flushes the window)."""
    def shp(a):
        return None if a is None else np.shape(a)
    return (shp(ds.features), shp(ds.labels), shp(ds.features_mask),
            shp(ds.labels_mask))


def multi_window_signature(mds) -> Tuple:
    """Signature for a MultiDataSet (lists of inputs/labels/masks)."""
    def shps(seq):
        if seq is None:
            return None
        return tuple(None if a is None else np.shape(a) for a in seq)
    return (shps(mds.features), shps(mds.labels),
            shps(mds.features_masks), shps(mds.labels_masks))


def stack_window(batches) -> Tuple:
    """Stack a window of same-signature DataSets into (W, B, ...) numpy
    arrays (host-side, so the work overlaps on-chip execution of the
    previous window).  Returns (features, labels, fmask, lmask)."""
    features = np.stack([np.asarray(b.features) for b in batches])
    labels = np.stack([np.asarray(b.labels) for b in batches])
    fm = (None if batches[0].features_mask is None else
          np.stack([np.asarray(b.features_mask) for b in batches]))
    lm = (None if batches[0].labels_mask is None else
          np.stack([np.asarray(b.labels_mask) for b in batches]))
    return features, labels, fm, lm


def stack_multi_window(mbs) -> Tuple:
    """Graph twin of :func:`stack_window` for MultiDataSets: per-input
    stacked lists (the shapes already agreed via the signature)."""
    n_in = len(mbs[0].features)
    n_out = len(mbs[0].labels)
    features = [np.stack([np.asarray(m.features[i]) for m in mbs])
                for i in range(n_in)]
    labels = [np.stack([np.asarray(m.labels[i]) for m in mbs])
              for i in range(n_out)]

    def masks(get, count):
        if all(get(m) is None for m in mbs):
            return None
        out = []
        for i in range(count):
            if get(mbs[0]) is None or get(mbs[0])[i] is None:
                out.append(None)
            else:
                out.append(np.stack([np.asarray(get(m)[i]) for m in mbs]))
        return out

    fmasks = masks(lambda m: m.features_masks, n_in)
    lmasks = masks(lambda m: m.labels_masks, n_out)
    return features, labels, fmasks, lmasks


def window_wire(batches) -> Tuple[Optional[np.ndarray], Optional[Tuple]]:
    """When EVERY batch in a window carries the same-format uint8 wire
    twin (and the wire is enabled), return the stacked ``(W, B, ...)``
    uint8 array plus the ``(denom, mult, add)`` spec — the windowed
    path then ships 1 byte/pixel and decodes on device.  Else
    ``(None, None)`` and the window stages float32 (or host-cast
    bfloat16) as before."""
    if not wire_enabled():
        return None, None
    wires = [wire_of(b) for b in batches]
    if any(w is None for w in wires):
        return None, None
    if len({w[1] for w in wires}) != 1:
        return None, None
    if any(w[0].shape != np.shape(b.features)
           for w, b in zip(wires, batches)):
        return None, None
    return np.stack([w[0] for w in wires]), wires[0][1].as_tuple()


def multi_window_wire(mbs, n_in: int):
    """Graph twin of :func:`window_wire`: per-input wire staging for a
    window of MultiDataSets (wire twins ride on ``_wires``, attached by
    ``computation_graph._as_multi`` when the source batch carried one).
    Returns ``(stacks, specs)`` — per-input lists where a wired slot
    holds its stacked (W, B, ...) uint8 array / ``(denom, mult, add)``
    spec and an unwired slot holds None — or ``(None, None)`` when no
    input wires."""
    if not wire_enabled():
        return None, None
    wire_lists = [getattr(m, "_wires", None) for m in mbs]
    stacks: List[Optional[np.ndarray]] = []
    specs: List[Optional[Tuple]] = []
    for i in range(n_in):
        ok = all(w is not None and len(w) > i and w[i] is not None
                 for w in wire_lists)
        if (ok and len({w[i][1] for w in wire_lists}) == 1
                and all(w[i][0].shape == np.shape(m.features[i])
                        for w, m in zip(wire_lists, mbs))):
            stacks.append(np.stack([w[i][0] for w in wire_lists]))
            specs.append(wire_lists[0][i][1].as_tuple())
        else:
            stacks.append(None)
            specs.append(None)
    if all(s is None for s in stacks):
        return None, None
    return stacks, tuple(specs)


def cast_for_transfer(features: np.ndarray, compute_dtype) -> np.ndarray:
    """Halve the windowed path's host->device bytes: when the model
    computes in bfloat16, cast float32 feature stacks on HOST before the
    transfer.  The train step's first action on floating inputs is this
    exact cast (``multilayer.py`` ``_forward``: inputs go to the compute
    dtype), both sides round-to-nearest-even, so this just moves the
    cast across the wire — identical numerics, half the bytes on the
    bandwidth-bound link.  Integer features (embedding ids) and labels
    (loss-side) are left untouched."""
    if compute_dtype != "bfloat16" or features.dtype != np.float32:
        return features
    import ml_dtypes
    return features.astype(ml_dtypes.bfloat16)


class ScoreReplayer:
    """Collects (start_iteration, device scores) per dispatch and
    replays listeners with per-step scores.  Fetching a dispatch's
    scores is the only blocking point, so dispatch k+1's staging always
    overlaps dispatch k's on-chip execution."""

    def __init__(self, model):
        self._model = model
        self._pending: List[Tuple[int, object]] = []

    def add(self, start_iteration: int, scores) -> None:
        self._pending.append((start_iteration, scores))

    def replay(self) -> None:
        """Fetch pending scores and fire ``iteration_done`` once per
        step (exact per-iteration score; params are end-of-dispatch)."""
        model = self._model
        for start, dev_scores in self._pending:
            scores = np.asarray(dev_scores)
            for j, s in enumerate(scores):
                model._score = s
                for listener in model.listeners:
                    listener.iteration_done(model, start + j + 1)
        self._pending = []

    def finish(self) -> None:
        """End-of-fit bookkeeping for the no-listener case: leave
        ``_score`` as the LAZY last-step device scalar (no host
        round-trip on the hot path — ``score()`` fetches on demand)."""
        if self._pending:
            self._model._score = self._pending[-1][1][-1]
            self._pending = []


def run_device_cached_fit(model, u, epochs: int, dispatch, *,
                          start_step: int = 0, ckpt=None):
    """Shared MLN/ComputationGraph driver for the device-resident
    epoch-cache fit.  ``u`` is the vetted ``ListDataSetIterator``;
    ``dispatch(first_epoch, fused_epochs, tail, start, run)`` invokes
    the model's gather-scan train step (which derives each epoch's
    permutation on device — see ``_gather_train_step``) and returns
    per-step scores; ``start``/``run`` select a sub-range of the
    epoch's full-batch steps so a dispatch can begin mid-epoch.

    One call per epoch normally; when no listeners are attached, the
    batch divides the dataset (no tail), and no step-cadence checkpoint
    is active, up to :func:`max_steps_per_dispatch` steps' worth of
    CONSECUTIVE epochs fold into a single dispatch — multi-epoch fits
    become a handful of XLA invocations with zero host traffic between
    them.  Listeners force per-epoch dispatches so score replay and
    epoch callbacks keep their per-iteration/per-epoch semantics.  A
    tail batch runs as its own 1-step dispatch (same on-device
    permutation, last ``tail`` entries), preserving the per-batch
    path's batch boundaries.

    Resilience hooks: ``start_step`` (from a restored checkpoint's
    ``step_in_epoch``) starts the FIRST epoch at that scan offset —
    the permutation is recomputed from the same threefry key, so the
    split epoch trains the identical step sequence an uninterrupted
    run would have, then later epochs return to full fusion.  ``ckpt``
    (a ``resilience.CheckpointManager``) bounds dispatch chunks to the
    step cadence, saves when due (scores are replayed first so
    listener output is never ahead of a checkpoint), and gives the
    fault layer its preemption point *after* each save."""
    from ..resilience import faults as _faults

    replay = ScoreReplayer(model)
    iters = _monitor.counter("train_iterations_total",
                             "supervised train iterations")
    n = u._ds.num_examples()
    batch = u._batch
    steps, tail = divmod(n, batch)
    fuse_cap = max(1, max_steps_per_dispatch() // max(1, steps))
    pos = int(start_step)
    if pos < 0 or pos >= steps:
        pos = 0
    step_cadence = (getattr(ckpt, "every_steps", None)
                    if ckpt is not None else None)

    def maybe_save(step_in_epoch, epoch_boundary=False):
        if ckpt is not None and ckpt.due(epoch_boundary=epoch_boundary):
            replay.replay()  # flush scores; listeners never trail a save
            ckpt.save(model, step_in_epoch=step_in_epoch)

    done = 0
    while done < epochs:
        fuse = 1
        if (not model.listeners and tail == 0 and steps > 0 and pos == 0
                and step_cadence is None):
            fuse = min(epochs - done, fuse_cap)
        with _monitor.span("fit/epoch", epoch=model.epoch, path="cache",
                           fused=fuse, start=pos):
            if pos == 0:
                for listener in model.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(model)
            t0 = time.perf_counter()
            for _ in range(fuse):
                consume_epoch(u)
            _monitor.observe_phase("data", time.perf_counter() - t0)
            t1 = time.perf_counter()
            chunked = bool(steps and (pos or step_cadence is not None))
            # Clean fused path: the sanitizer's budgeted unit is one
            # dispatch per fused epoch plus one for the tail batch.
            # Resumed/checkpointed epochs legitimately chunk into
            # multiple dispatches, so only the clean path is bracketed.
            scen = (contextlib.nullcontext() if chunked else
                    _monitor.sanitize_scenario("fit.epoch_cache",
                                               units=fuse,
                                               extra=1 if tail else 0))
            with scen:
                if chunked:
                    # resumed and/or checkpointed epoch: chunked
                    # dispatches over [pos, steps), each chunk ending
                    # on a save point
                    while pos < steps:
                        run = steps - pos
                        if step_cadence is not None:
                            run = min(run, ckpt.steps_to_next_save())
                        scores = dispatch(model.epoch, 1, 0, pos, run)
                        replay.add(model.iteration, scores)
                        iters.inc(run)
                        model.iteration += run
                        model.last_batch_size = batch
                        pos += run
                        if ckpt is not None:
                            ckpt.note_steps(run)
                        if pos < steps:
                            maybe_save(pos)
                            _faults.maybe_die(model.iteration)
                elif steps:
                    scores = dispatch(model.epoch, fuse, 0, 0, steps)
                    replay.add(model.iteration, scores)
                    iters.inc(fuse * steps)
                    model.iteration += fuse * steps
                    model.last_batch_size = batch
                    if ckpt is not None:
                        ckpt.note_steps(fuse * steps)
                if tail:
                    scores = dispatch(model.epoch, 1, tail, 0, 0)
                    replay.add(model.iteration, scores)
                    iters.inc(1)
                    model.iteration += 1
                    model.last_batch_size = tail
                    if ckpt is not None:
                        ckpt.note_steps(1)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            if model.listeners:
                t2 = time.perf_counter()
                replay.replay()     # blocks: exact per-step scores
                _monitor.observe_phase("listener",
                                       time.perf_counter() - t2)
            for listener in model.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(model)
            model.epoch += fuse
            pos = 0
        done += fuse
        maybe_save(0, epoch_boundary=True)
        _faults.maybe_die(model.iteration)
    if ckpt is not None:
        replay.replay()
        ckpt.save_if_progress(model, step_in_epoch=0)
        ckpt.flush()
    replay.finish()
    return model
