"""Overlapped ingest for ``fit(iterator)``: device-resident epoch cache
and windowed double-buffered staging.

The reference hides ETL behind compute with a prefetch thread
(``datasets/iterator/AsyncDataSetIterator.java`` feeding
``MultiLayerNetwork.fit:976-980``).  On a TPU behind a host<->device
link, the analogous wins are:

1. **Device-resident epoch cache** — a dataset that fits in HBM is
   uploaded ONCE and stays resident across epochs; each epoch is one
   ``lax.scan`` dispatch whose body gathers its minibatch from the
   resident arrays by index.  Per-epoch host traffic drops to one
   (S, B) int32 index array (the epoch permutation), so throughput
   approaches the staged-on-device compute ceiling instead of being
   host-transfer-bound.
2. **Windowed staging** — datasets that do not fit HBM stream in
   multi-batch windows: the host stacks window k+1 and enqueues its
   transfer while window k's multi-step scan runs on-chip (JAX async
   dispatch provides the overlap; nothing blocks until scores are
   fetched).

Both paths preserve per-iteration listener semantics by REPLAY: the
scan returns per-step scores, and listeners fire once per underlying
iteration with the exact score of that step (params seen by a replayed
listener are end-of-dispatch params — the documented divergence, same
compromise as ``fit_scan``).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

#: Datasets larger than this (features + labels bytes) never device-cache.
#: Default 2 GB leaves headroom on a 16 GB-HBM chip for params, updater
#: state, activations and the scan's score stack.
DEVICE_CACHE_LIMIT_BYTES = int(os.environ.get(
    "DL4J_TPU_DEVICE_CACHE_LIMIT", 2_000_000_000))

_CACHEABLE_DTYPES = ("float32", "bfloat16")


def cacheable_source(iterator):
    """Return the underlying ``ListDataSetIterator`` when ``iterator``
    can be served by the device-resident epoch cache, else ``None``.

    Mirrors the eligibility posture of the native-prefetch takeover in
    ``datasets/iterators.py``: exact ``ListDataSetIterator`` iteration
    semantics only (a subclass overriding ``__next__``/``reset`` keeps
    its override by falling back), dense float features/labels, no
    masks, no preprocessor, and total bytes under
    :data:`DEVICE_CACHE_LIMIT_BYTES`.
    """
    from ..datasets.iterators import (AsyncDataSetIterator,
                                      ListDataSetIterator)
    u = iterator
    if isinstance(u, AsyncDataSetIterator):
        if u.get_preprocessor() is not None:
            return None
        u = u._under
    if not isinstance(u, ListDataSetIterator):
        return None
    if (type(u).__next__ is not ListDataSetIterator.__next__
            or type(u).reset is not ListDataSetIterator.reset):
        return None
    if u.get_preprocessor() is not None:
        return None
    ds = u._ds
    if ds.features is None or ds.labels is None:
        return None
    if ds.features_mask is not None or ds.labels_mask is not None:
        return None
    f = np.asarray(ds.features)
    l = np.asarray(ds.labels)
    if f.dtype.name not in _CACHEABLE_DTYPES or \
            l.dtype.name not in _CACHEABLE_DTYPES:
        return None
    if f.nbytes + l.nbytes > DEVICE_CACHE_LIMIT_BYTES:
        return None
    return u


def device_cached_arrays(model, ds) -> Tuple:
    """Device copies of ``ds.features``/``ds.labels`` that stay resident
    ACROSS ``fit()`` calls (true epoch-cache residency: without this,
    every fit() re-paid the full dataset host->device transfer — 188 MB
    for f32 MNIST — which dominated end-to-end throughput over the
    tunnel).  The cache lives on the model and is keyed by host-array
    identity: it holds references to the exact feature/label ndarrays it
    uploaded, so re-use requires ``ds`` to still expose those same
    objects; assigning new arrays re-uploads.  In-place mutation of the
    same arrays between fits is NOT detected — matching the reference's
    posture that a dataset is immutable while training on it."""
    import jax.numpy as jnp
    f = np.asarray(ds.features)
    l = np.asarray(ds.labels)
    cache = getattr(model, "_ingest_device_cache", None)
    if cache is not None and cache[0] is f and cache[1] is l:
        return cache[2], cache[3]
    dev_f, dev_l = jnp.asarray(f), jnp.asarray(l)
    model._ingest_device_cache = (f, l, dev_f, dev_l)
    return dev_f, dev_l


def epoch_order(u) -> np.ndarray:
    """Advance ``u`` through one epoch's worth of state transitions and
    return the example order that epoch would have used.

    The canonical ``fit(iterator)`` path resets twice per epoch (the
    explicit ``it.reset()`` plus ``__iter__``'s reset), so the cache
    path performs the same two resets — the permutation stream is
    IDENTICAL to the per-batch path (exact-parity tested).  The
    iterator is then marked consumed so external observers see a
    finished epoch.
    """
    u.reset()
    u.reset()
    order = np.asarray(u._order)
    u._pos = u._ds.num_examples()
    return order


def epoch_index_batches(order: np.ndarray,
                        batch: int) -> List[np.ndarray]:
    """Split an epoch permutation into (S, B) full-batch indices plus an
    optional (1, tail) remainder — the same batch boundaries
    ``ListDataSetIterator.__next__`` produces."""
    n = order.shape[0]
    s, tail = divmod(n, batch)
    out = []
    if s:
        out.append(order[:s * batch].reshape(s, batch).astype(np.int32))
    if tail:
        out.append(order[s * batch:].reshape(1, tail).astype(np.int32))
    return out


def window_signature(ds) -> Tuple:
    """Shape/mask-presence signature of a DataSet; a window only stacks
    batches with identical signatures (a change flushes the window)."""
    def shp(a):
        return None if a is None else np.shape(a)
    return (shp(ds.features), shp(ds.labels), shp(ds.features_mask),
            shp(ds.labels_mask))


def multi_window_signature(mds) -> Tuple:
    """Signature for a MultiDataSet (lists of inputs/labels/masks)."""
    def shps(seq):
        if seq is None:
            return None
        return tuple(None if a is None else np.shape(a) for a in seq)
    return (shps(mds.features), shps(mds.labels),
            shps(mds.features_masks), shps(mds.labels_masks))


def stack_window(batches) -> Tuple:
    """Stack a window of same-signature DataSets into (W, B, ...) numpy
    arrays (host-side, so the work overlaps on-chip execution of the
    previous window).  Returns (features, labels, fmask, lmask)."""
    features = np.stack([np.asarray(b.features) for b in batches])
    labels = np.stack([np.asarray(b.labels) for b in batches])
    fm = (None if batches[0].features_mask is None else
          np.stack([np.asarray(b.features_mask) for b in batches]))
    lm = (None if batches[0].labels_mask is None else
          np.stack([np.asarray(b.labels_mask) for b in batches]))
    return features, labels, fm, lm


def stack_multi_window(mbs) -> Tuple:
    """Graph twin of :func:`stack_window` for MultiDataSets: per-input
    stacked lists (the shapes already agreed via the signature)."""
    n_in = len(mbs[0].features)
    n_out = len(mbs[0].labels)
    features = [np.stack([np.asarray(m.features[i]) for m in mbs])
                for i in range(n_in)]
    labels = [np.stack([np.asarray(m.labels[i]) for m in mbs])
              for i in range(n_out)]

    def masks(get, count):
        if all(get(m) is None for m in mbs):
            return None
        out = []
        for i in range(count):
            if get(mbs[0]) is None or get(mbs[0])[i] is None:
                out.append(None)
            else:
                out.append(np.stack([np.asarray(get(m)[i]) for m in mbs]))
        return out

    fmasks = masks(lambda m: m.features_masks, n_in)
    lmasks = masks(lambda m: m.labels_masks, n_out)
    return features, labels, fmasks, lmasks


def cast_for_transfer(features: np.ndarray, compute_dtype) -> np.ndarray:
    """Halve the windowed path's host->device bytes: when the model
    computes in bfloat16, cast float32 feature stacks on HOST before the
    transfer.  The train step's first action on floating inputs is this
    exact cast (``multilayer.py`` ``_forward``: inputs go to the compute
    dtype), both sides round-to-nearest-even, so this just moves the
    cast across the wire — identical numerics, half the bytes on the
    bandwidth-bound link.  Integer features (embedding ids) and labels
    (loss-side) are left untouched."""
    if compute_dtype != "bfloat16" or features.dtype != np.float32:
        return features
    import ml_dtypes
    return features.astype(ml_dtypes.bfloat16)


class ScoreReplayer:
    """Collects (start_iteration, device scores) per dispatch and
    replays listeners with per-step scores.  Fetching a dispatch's
    scores is the only blocking point, so dispatch k+1's staging always
    overlaps dispatch k's on-chip execution."""

    def __init__(self, model):
        self._model = model
        self._pending: List[Tuple[int, object]] = []

    def add(self, start_iteration: int, scores) -> None:
        self._pending.append((start_iteration, scores))

    def replay(self) -> None:
        """Fetch pending scores and fire ``iteration_done`` once per
        step (exact per-iteration score; params are end-of-dispatch)."""
        model = self._model
        for start, dev_scores in self._pending:
            scores = np.asarray(dev_scores)
            for j, s in enumerate(scores):
                model._score = s
                for listener in model.listeners:
                    listener.iteration_done(model, start + j + 1)
        self._pending = []

    def finish(self) -> None:
        """End-of-fit bookkeeping for the no-listener case: leave
        ``_score`` as the LAZY last-step device scalar (no host
        round-trip on the hot path — ``score()`` fetches on demand)."""
        if self._pending:
            self._model._score = self._pending[-1][1][-1]
            self._pending = []
