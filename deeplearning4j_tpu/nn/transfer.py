"""Transfer learning: freeze a feature extractor, swap the head, keep
the pretrained weights.

The reference grows this API one minor version after 0.7.3
(``TransferLearning.Builder`` / ``FrozenLayer``); it is included here
because it is the natural consumer of a trained Keras import or
pretrained zoo model, and the TPU build's per-layer config inheritance
makes it nearly free: frozen layers are plain configs with
``frozen=True`` (skipped by ``updaters.apply_layer_updates``), so the
whole fine-tune step still compiles to one XLA program.

Typical use::

    new_net = (TransferLearning.builder(trained_net)
               .fine_tune_learning_rate(1e-4)
               .set_feature_extractor(1)      # freeze layers 0..1
               .remove_layers_from(3)          # drop the old head
               .add_layer(OutputLayer(n_in=64, n_out=5))
               .build())
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax.numpy as jnp


def _apply_fine_tune_overrides(layers, global_updater, lr, updater):
    """Push fine-tune lr/updater into the global conf AND each unfrozen
    layer's finalized (de-aliased) updater conf."""
    if lr is not None:
        global_updater.learning_rate = lr
    if updater is not None:
        global_updater.updater = updater
    for layer in layers:
        if layer is None or getattr(layer, "frozen", False) \
                or layer.updater is None:
            continue
        if lr is not None:
            layer.updater.learning_rate = lr
        if updater is not None:
            layer.updater.updater = updater


def _copy_entry(src_net, dst_net, key):
    """Deep-copy one layer's params+state (donation in the new net's train
    step must not delete the source network's buffers)."""
    dst_net.params[key] = {k: jnp.array(v, copy=True)
                           for k, v in src_net.params[key].items()}
    dst_net.net_state[key] = {k: jnp.array(v, copy=True)
                              for k, v in src_net.net_state[key].items()}


class TransferLearning:
    """Namespace mirroring the reference's ``TransferLearning.Builder`` /
    ``TransferLearning.GraphBuilder``."""

    @staticmethod
    def builder(net) -> "TransferLearningBuilder":
        return TransferLearningBuilder(net)

    @staticmethod
    def graph_builder(net) -> "GraphTransferLearningBuilder":
        return GraphTransferLearningBuilder(net)


class TransferLearningBuilder:
    def __init__(self, net):
        from .multilayer import MultiLayerNetwork
        if not isinstance(net, MultiLayerNetwork):
            raise ValueError(
                "TransferLearning.builder operates on MultiLayerNetwork; "
                "use TransferLearning.graph_builder for ComputationGraph")
        net.init()
        self._src = net
        self._conf = copy.deepcopy(net.conf)
        self._keep = len(self._conf.layers)     # layers [0, _keep) retained
        self._frozen_up_to = -1
        self._added: List[object] = []
        self._lr: Optional[float] = None
        self._updater: Optional[str] = None

    # ---------------------------------------------------------- fine-tune
    def fine_tune_learning_rate(self, lr: float) -> "TransferLearningBuilder":
        """Override the network learning rate for the fine-tune phase
        (reference ``FineTuneConfiguration.learningRate``)."""
        self._lr = float(lr)
        return self

    def fine_tune_updater(self, updater: str) -> "TransferLearningBuilder":
        self._updater = updater
        return self

    # ------------------------------------------------------------ surgery
    def set_feature_extractor(self, layer_index: int
                              ) -> "TransferLearningBuilder":
        """Freeze layers ``0..layer_index`` inclusive (reference
        ``setFeatureExtractor``)."""
        self._frozen_up_to = int(layer_index)
        return self

    def remove_output_layer(self) -> "TransferLearningBuilder":
        return self.remove_layers_from(self._keep - 1)

    def remove_layers_from(self, layer_index: int
                           ) -> "TransferLearningBuilder":
        """Drop layers ``layer_index..end`` (reference
        ``removeLayersFromOutput``)."""
        if not 0 <= layer_index <= self._keep:
            raise ValueError(f"layer_index {layer_index} out of range "
                             f"[0, {self._keep}]")
        self._keep = int(layer_index)
        return self

    def add_layer(self, layer) -> "TransferLearningBuilder":
        """Append a freshly initialized layer config (reference
        ``addLayer``)."""
        self._added.append(layer)
        return self

    # -------------------------------------------------------------- build
    def build(self):
        from .multilayer import MultiLayerNetwork

        if self._frozen_up_to >= self._keep:
            raise ValueError(
                f"cannot freeze through layer {self._frozen_up_to}: only "
                f"{self._keep} layers are retained (added layers are new "
                f"heads and train by definition)")
        # never mutate the builder's stored conf: build() must be
        # repeatable and must not alter the source network's conf
        conf = copy.deepcopy(self._conf)
        kept_layers = [copy.deepcopy(l) for l in conf.layers[:self._keep]]
        for i, layer in enumerate(kept_layers):
            # preserve freezes inherited from a previous transfer
            layer.frozen = layer.frozen or i <= self._frozen_up_to
        # kept layers carry their own finalized updater confs (aliasing
        # with the global conf was broken by deepcopy), so fine-tune
        # overrides must be pushed into each unfrozen kept layer too
        _apply_fine_tune_overrides(kept_layers, conf.conf.updater,
                                   self._lr, self._updater)
        added = [copy.deepcopy(l) for l in self._added]
        for layer in added:
            # new layers inherit the (possibly overridden) global defaults
            layer.finalize_defaults(conf.conf.layer_defaults())
        conf.layers = kept_layers + added
        if not conf.layers:
            raise ValueError("transfer result has no layers")
        # preprocessors of removed layers are dropped (an old head's
        # preprocessor must not apply to a newly added layer at its index)
        conf.input_preprocessors = {
            i: p for i, p in conf.input_preprocessors.items()
            if i < self._keep}

        net = MultiLayerNetwork(conf).init()
        for i in range(self._keep):
            _copy_entry(self._src, net, i)
        # the source's completed pretraining carries over — fit() must not
        # re-run unsupervised pretraining over the transferred weights
        net._pretrain_done = self._src._pretrain_done
        return net


class GraphTransferLearningBuilder:
    """ComputationGraph transfer (reference ``TransferLearning
    .GraphBuilder``, scoped to the dominant uses): freeze a vertex and
    all its ancestors as the feature extractor, replace output-layer
    vertices for a new task, and override fine-tune hyperparameters."""

    def __init__(self, net):
        from .computation_graph import ComputationGraph
        if not isinstance(net, ComputationGraph):
            raise ValueError("graph_builder requires a ComputationGraph")
        net.init()
        self._src = net
        self._conf = copy.deepcopy(net.conf)
        self._freeze_roots: List[str] = []
        self._replaced: dict = {}
        self._lr: Optional[float] = None
        self._updater: Optional[str] = None

    def fine_tune_learning_rate(self, lr: float
                                ) -> "GraphTransferLearningBuilder":
        self._lr = float(lr)
        return self

    def fine_tune_updater(self, updater: str
                          ) -> "GraphTransferLearningBuilder":
        self._updater = updater
        return self

    def set_feature_extractor(self, *vertex_names: str
                              ) -> "GraphTransferLearningBuilder":
        """Freeze the named vertices and every ancestor vertex (reference
        ``setFeatureExtractor(vertexName)`` semantics)."""
        unknown = [n for n in vertex_names if n not in self._conf.vertices]
        if unknown:
            raise ValueError(f"unknown vertices: {unknown}")
        self._freeze_roots.extend(vertex_names)
        return self

    def replace_output_layer(self, vertex_name: str, new_layer
                             ) -> "GraphTransferLearningBuilder":
        """Swap the layer config of an existing layer vertex (typically an
        output head for a new class count); its params re-initialize
        (reference ``nOutReplace``/``removeVertexAndConnections`` +
        ``addLayer`` for the head-swap case)."""
        v = self._conf.vertices.get(vertex_name)
        if v is None or not hasattr(v, "layer"):
            raise ValueError(
                f"{vertex_name!r} is not a layer vertex of this graph")
        if vertex_name not in self._conf.network_outputs:
            # replacing a mid-graph vertex would copy old-shaped params of
            # downstream kept vertices into the re-inferred net and fail
            # later with an opaque shape error
            raise ValueError(
                f"{vertex_name!r} is not a network output of this graph "
                f"(outputs: {list(self._conf.network_outputs)}); "
                "replace_output_layer only swaps output heads")
        self._replaced[vertex_name] = new_layer
        return self

    def _ancestors(self, roots: List[str]) -> set:
        """Roots plus all transitive input vertices (network inputs
        excluded — they carry no params)."""
        out, stack = set(), list(roots)
        while stack:
            name = stack.pop()
            if name in out or name not in self._conf.vertices:
                continue
            out.add(name)
            stack.extend(self._conf.vertices[name].inputs or [])
        return out

    def build(self):
        from .computation_graph import ComputationGraph

        conf = copy.deepcopy(self._conf)
        frozen = self._ancestors(self._freeze_roots)
        overlap = frozen & set(self._replaced)
        if overlap:
            raise ValueError(
                f"vertices both frozen and replaced: {sorted(overlap)}")
        for name in frozen:
            v = conf.vertices[name]
            if hasattr(v, "layer") and v.layer is not None:
                # preserve freezes inherited from a previous transfer
                v.layer.frozen = True
        _apply_fine_tune_overrides(
            [getattr(v, "layer", None) for v in conf.vertices.values()],
            conf.conf.updater, self._lr, self._updater)
        for name, new_layer in self._replaced.items():
            nl = copy.deepcopy(new_layer)
            nl.finalize_defaults(conf.conf.layer_defaults())
            conf.vertices[name].layer = nl
        if self._replaced and getattr(conf, "input_types", None):
            # a replacement head given without n_in relies on shape
            # inference, exactly like the importer/zoo-built source did
            from .conf.computation_graph import _infer_graph_shapes
            _infer_graph_shapes(conf)

        net = ComputationGraph(conf).init()
        # copy params/state for every retained layer vertex (replaced
        # heads keep their fresh init)
        for name in self._src.params:
            if name not in self._replaced:
                _copy_entry(self._src, net, name)
        # see the MLN builder: transferred pretraining stays done
        net._pretrain_done = self._src._pretrain_done
        return net
