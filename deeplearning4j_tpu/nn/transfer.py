"""Transfer learning: freeze a feature extractor, swap the head, keep
the pretrained weights.

The reference grows this API one minor version after 0.7.3
(``TransferLearning.Builder`` / ``FrozenLayer``); it is included here
because it is the natural consumer of a trained Keras import or
pretrained zoo model, and the TPU build's per-layer config inheritance
makes it nearly free: frozen layers are plain configs with
``frozen=True`` (skipped by ``updaters.apply_layer_updates``), so the
whole fine-tune step still compiles to one XLA program.

Typical use::

    new_net = (TransferLearning.builder(trained_net)
               .fine_tune_learning_rate(1e-4)
               .set_feature_extractor(1)      # freeze layers 0..1
               .remove_layers_from(3)          # drop the old head
               .add_layer(OutputLayer(n_in=64, n_out=5))
               .build())
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax.numpy as jnp


class TransferLearning:
    """Namespace mirroring the reference's ``TransferLearning.Builder``."""

    @staticmethod
    def builder(net) -> "TransferLearningBuilder":
        return TransferLearningBuilder(net)


class TransferLearningBuilder:
    def __init__(self, net):
        from .multilayer import MultiLayerNetwork
        if not isinstance(net, MultiLayerNetwork):
            raise ValueError(
                "TransferLearning operates on MultiLayerNetwork; build "
                "graph surgery with GraphBuilder directly")
        net.init()
        self._src = net
        self._conf = copy.deepcopy(net.conf)
        self._keep = len(self._conf.layers)     # layers [0, _keep) retained
        self._frozen_up_to = -1
        self._added: List[object] = []
        self._lr: Optional[float] = None
        self._updater: Optional[str] = None

    # ---------------------------------------------------------- fine-tune
    def fine_tune_learning_rate(self, lr: float) -> "TransferLearningBuilder":
        """Override the network learning rate for the fine-tune phase
        (reference ``FineTuneConfiguration.learningRate``)."""
        self._lr = float(lr)
        return self

    def fine_tune_updater(self, updater: str) -> "TransferLearningBuilder":
        self._updater = updater
        return self

    # ------------------------------------------------------------ surgery
    def set_feature_extractor(self, layer_index: int
                              ) -> "TransferLearningBuilder":
        """Freeze layers ``0..layer_index`` inclusive (reference
        ``setFeatureExtractor``)."""
        self._frozen_up_to = int(layer_index)
        return self

    def remove_output_layer(self) -> "TransferLearningBuilder":
        return self.remove_layers_from(self._keep - 1)

    def remove_layers_from(self, layer_index: int
                           ) -> "TransferLearningBuilder":
        """Drop layers ``layer_index..end`` (reference
        ``removeLayersFromOutput``)."""
        if not 0 <= layer_index <= self._keep:
            raise ValueError(f"layer_index {layer_index} out of range "
                             f"[0, {self._keep}]")
        self._keep = int(layer_index)
        return self

    def add_layer(self, layer) -> "TransferLearningBuilder":
        """Append a freshly initialized layer config (reference
        ``addLayer``)."""
        self._added.append(layer)
        return self

    # -------------------------------------------------------------- build
    def build(self):
        from .multilayer import MultiLayerNetwork

        if self._frozen_up_to >= self._keep:
            raise ValueError(
                f"cannot freeze through layer {self._frozen_up_to}: only "
                f"{self._keep} layers are retained (added layers are new "
                f"heads and train by definition)")
        # never mutate the builder's stored conf: build() must be
        # repeatable and must not alter the source network's conf
        conf = copy.deepcopy(self._conf)
        kept_layers = [copy.deepcopy(l) for l in conf.layers[:self._keep]]
        for i, layer in enumerate(kept_layers):
            # preserve freezes inherited from a previous transfer
            layer.frozen = layer.frozen or i <= self._frozen_up_to
        if self._lr is not None:
            conf.conf.updater.learning_rate = self._lr
        if self._updater is not None:
            conf.conf.updater.updater = self._updater
        # kept layers carry their own finalized updater confs (aliasing
        # with the global conf was broken by deepcopy), so fine-tune
        # overrides must be pushed into each unfrozen kept layer too
        for layer in kept_layers:
            if layer.frozen or layer.updater is None:
                continue
            if self._lr is not None:
                layer.updater.learning_rate = self._lr
            if self._updater is not None:
                layer.updater.updater = self._updater
        added = [copy.deepcopy(l) for l in self._added]
        for layer in added:
            # new layers inherit the (possibly overridden) global defaults
            layer.finalize_defaults(conf.conf.layer_defaults())
        conf.layers = kept_layers + added
        if not conf.layers:
            raise ValueError("transfer result has no layers")
        # preprocessors of removed layers are dropped (an old head's
        # preprocessor must not apply to a newly added layer at its index)
        conf.input_preprocessors = {
            i: p for i, p in conf.input_preprocessors.items()
            if i < self._keep}

        net = MultiLayerNetwork(conf).init()
        # transfer params + layer state for every retained layer.  COPY,
        # don't alias: the new net's train step donates its param buffers,
        # and a shared buffer would be deleted out from under the source
        # network on the first fine-tune step.
        for i in range(self._keep):
            net.params[i] = {k: jnp.array(v, copy=True)
                             for k, v in self._src.params[i].items()}
            net.net_state[i] = {k: jnp.array(v, copy=True)
                                for k, v in self._src.net_state[i].items()}
        return net
