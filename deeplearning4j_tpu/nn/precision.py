"""Precision policy: storage/compute/updater dtypes + fp32 master weights.

One object answers every "which dtype?" question on the training hot
path (docs/PERFORMANCE.md).  A :class:`PrecisionPolicy` is resolved once
per network at ``init()`` from three sources, in precedence order:

1. ``DL4J_TPU_PRECISION`` env — the global switch.  Values:
   ``fp32``/``float32`` (everything fp32), ``bf16``/``bfloat16``
   (pure bf16: params, activations AND updater state — no masters),
   ``mixed_bf16``/``mixed`` (bf16 params + bf16 activations + fp32
   master copies carried in the updater state, cast-on-apply).
2. Explicit ``NeuralNetConfiguration`` fields: a non-default
   ``dtype`` and/or a ``compute_dtype``.  These reproduce the exact
   pre-policy semantics (e.g. fp32 params with bf16 matmuls when only
   ``compute_dtype="bfloat16"`` is set — no master copies, because the
   params already are the fp32 masters).
3. Backend default: **mixed_bf16 on TPU, fp32 everywhere else**.  CPU
   tier-1 numerics are therefore untouched by this module.

Master-weight contract: when ``master_weights`` is on, the updater
state for each layer carries an extra ``"_master"`` tree mirroring the
updatable params in fp32.  All updater math (l1/l2, gradient
normalization, momentum/Adam/etc.) runs against the fp32 masters; the
bf16 params the forward pass reads are re-derived each step via a
single cast (``param = master.astype(bf16)``).  Because the masters
live inside the (donated) updater-state carry they stay device-resident
across the fused ``lax.scan`` epoch, ship through ZeRO sharding
untouched, and serialize with the updater state — checkpoints always
store fp32 masters, so kill-and-resume stays bit-identical (bf16→fp32
round-trips losslessly).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp

_ENV = "DL4J_TPU_PRECISION"

# canonical mode names
FP32 = "fp32"
BF16 = "bf16"
MIXED_BF16 = "mixed_bf16"

_MODE_ALIASES = {
    "fp32": FP32, "float32": FP32, "f32": FP32,
    "bf16": BF16, "bfloat16": BF16, "pure_bf16": BF16,
    "mixed_bf16": MIXED_BF16, "mixed": MIXED_BF16,
    "bf16_fp32_master": MIXED_BF16,
}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Resolved dtype decisions for one network instance."""

    param_dtype: jnp.dtype        # storage dtype of layer params / net state
    compute_dtype: jnp.dtype      # activations + matmul dtype
    updater_dtype: jnp.dtype      # momentum/Adam-moment storage dtype
    master_weights: bool          # carry fp32 masters in the updater state
    name: str                     # fp32 | bf16 | mixed_bf16 | custom

    @property
    def compute_name(self) -> Optional[str]:
        """String form for wire-transfer casts (``cast_for_transfer``)."""
        return "bfloat16" if self.compute_dtype == jnp.bfloat16 else None

    @property
    def downcasts_output(self) -> bool:
        """True when activations are below fp32 and outputs need an fp32
        cast before loss/softmax/metrics accumulation."""
        return (jnp.issubdtype(self.compute_dtype, jnp.floating)
                and jnp.dtype(self.compute_dtype).itemsize < 4)

    def describe(self) -> str:
        return "%s(param=%s,compute=%s,updater=%s,masters=%d)" % (
            self.name, jnp.dtype(self.param_dtype).name,
            jnp.dtype(self.compute_dtype).name,
            jnp.dtype(self.updater_dtype).name, int(self.master_weights))


_FP32_POLICY = PrecisionPolicy(jnp.dtype(jnp.float32), jnp.dtype(jnp.float32),
                               jnp.dtype(jnp.float32), False, FP32)
_BF16_POLICY = PrecisionPolicy(jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.bfloat16),
                               jnp.dtype(jnp.bfloat16), False, BF16)
_MIXED_POLICY = PrecisionPolicy(jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.float32), True, MIXED_BF16)
_NAMED = {FP32: _FP32_POLICY, BF16: _BF16_POLICY, MIXED_BF16: _MIXED_POLICY}


def on_tpu() -> bool:
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


def env_mode() -> Optional[str]:
    """Canonical mode requested via DL4J_TPU_PRECISION, or None."""
    raw = os.environ.get(_ENV, "").strip().lower()
    if not raw:
        return None
    mode = _MODE_ALIASES.get(raw)
    if mode is None:
        raise ValueError(
            "%s=%r not understood; expected one of %s"
            % (_ENV, raw, sorted(set(_MODE_ALIASES))))
    return mode


def named_policy(mode: str) -> PrecisionPolicy:
    return _NAMED[_MODE_ALIASES[mode]]


def default_compute_dtype() -> Optional[str]:
    """The compute dtype a freshly-built net would default to on this
    backend ("bfloat16" on TPU / under a bf16 env mode, else None).

    This is the shared replacement for the `_bf16_if_tpu()` helper that
    bench.py / examples / tools each used to carry privately.
    """
    mode = env_mode()
    if mode is None:
        mode = MIXED_BF16 if on_tpu() else FP32
    return "bfloat16" if mode in (BF16, MIXED_BF16) else None


def resolve_policy(gconf) -> PrecisionPolicy:
    """Resolve the policy for one network from its GlobalConfig.

    ``gconf`` needs ``.dtype`` (str, default "float32") and
    ``.compute_dtype`` (str or None) attributes.
    """
    conf_dtype = getattr(gconf, "dtype", "float32") or "float32"
    conf_compute = getattr(gconf, "compute_dtype", None)
    explicit = conf_dtype != "float32" or conf_compute is not None

    mode = env_mode()
    if mode is not None:
        # the env is the global switch; it wins over conf fields so a
        # single knob can flip an unmodified model zoo / bench / CI run.
        return _NAMED[mode]

    if explicit:
        param = jnp.dtype(conf_dtype)
        compute = jnp.dtype(conf_compute) if conf_compute else param
        low_param = (jnp.issubdtype(param, jnp.floating)
                     and param.itemsize < 4)
        # pre-policy semantics: fp32 params + bf16 compute has no master
        # copies (params ARE the masters).  Explicitly-requested low-
        # precision *storage* gets fp32 masters — the safe default.
        return PrecisionPolicy(
            param_dtype=param, compute_dtype=compute,
            updater_dtype=jnp.dtype(jnp.float32) if low_param else param,
            master_weights=low_param, name="custom")

    return _MIXED_POLICY if on_tpu() else _FP32_POLICY


def publish(policy: PrecisionPolicy) -> None:
    """Expose the resolved policy on the metrics registry (best-effort)."""
    try:
        from .. import monitor
        monitor.gauge("precision_param_bits").set(
            jnp.dtype(policy.param_dtype).itemsize * 8)
        monitor.gauge("precision_compute_bits").set(
            jnp.dtype(policy.compute_dtype).itemsize * 8)
        monitor.gauge("precision_master_weights").set(
            int(policy.master_weights))
    except Exception:
        pass
