"""Causal decoder self-attention with a device-resident KV-cache ring.

The transformer-decode serving tier (ROADMAP item 5): a causal
multi-head self-attention layer whose *inference state* is a KV-cache
ring — fixed-capacity ``(batch, heads, cache_len, head_dim)`` K/V
buffers plus an int32 write cursor, updated in place via
``lax.dynamic_update_slice`` inside the compiled step
(``ops.attention.kv_ring_update``).  The ring is the layer's carry
under the :class:`BaseRecurrentLayer` contract, so everything built for
RNN streaming — ``rnn_time_step``, ``decode_step``,
``serving.SessionCache`` — serves autoregressive decode unchanged:
N single-token steps cost N single dispatches and BIT-match the
full-sequence forward (``tests/test_decode.py``).

Two forward tiers:

- **training** (``train=True``): ``flash_attention(causal=True)`` — the
  fused Pallas kernel, O(T·d) memory, fused backward; the ring never
  materializes.
- **inference** (``train=False`` / ``forward_seq``): the ring-dense
  path ``ops.attention.kv_ring_attention`` with exact cursor masking.
  Masked slots contribute exact zeros, so the result is bitwise
  independent of ring capacity — the parity contract that lets decode
  hop (batch, cache_len) buckets compile-free while still matching
  ``output()`` to the last ulp.

No positional encoding is built in: position information, when the
model needs it, comes from the upstream embedding/preprocessor stack
(the layer itself must stay position-free so the ring write at cursor
``t`` is the only place position enters — one source of truth for the
bit-parity proof).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ...ops.attention import (flash_attention, kv_ring_attention,
                              kv_ring_update)
from ..conf import serde
from ..weights import init_weights
from .base import Array, ParamTree
from .recurrent import BaseRecurrentLayer


@serde.register("causal_attention")
@dataclasses.dataclass
class CausalSelfAttention(BaseRecurrentLayer):
    """Multi-head causal self-attention over (batch, time, features)
    activations.

    ``n_heads`` must divide ``n_out``; ``cache_len`` is the ring
    capacity — the longest sequence the *inference* state can hold
    (training is not bounded by it).  Params: Wq/Wk/Wv (n_in, n_out),
    Wo (n_out, n_out), b (n_out,); the output projection applies the
    layer activation (default identity, the transformer convention).

    Carry: ``(k_cache, v_cache, cursor)`` with K/V of shape
    (batch, n_heads, cache_len, head_dim) and an int32 scalar cursor =
    tokens already written.  ``init_carry`` accepts a ``cache_len``
    override so serving can ladder ring capacity per session
    (``serving.sessions``); ``grow_carry`` pads a ring up to the next
    bucket (masked slots are inert, so growth never changes results).
    """

    HAS_KV_RING = True

    activation: str = "identity"
    n_heads: int = 1
    cache_len: int = 128

    # ------------------------------------------------------------- params
    def _head_dim(self) -> int:
        if self.n_out <= 0 or self.n_heads <= 0 \
                or self.n_out % self.n_heads:
            raise ValueError(
                f"n_heads={self.n_heads} must divide n_out={self.n_out}")
        return self.n_out // self.n_heads

    def param_order(self) -> tuple:
        return ("Wq", "Wk", "Wv", "Wo", "b")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        self._head_dim()
        kq, kk, kv, ko = jax.random.split(rng, 4)
        wi = self.weight_init or "xavier"
        return {
            "Wq": init_weights(kq, (self.n_in, self.n_out), wi,
                               self.dist, dtype),
            "Wk": init_weights(kk, (self.n_in, self.n_out), wi,
                               self.dist, dtype),
            "Wv": init_weights(kv, (self.n_in, self.n_out), wi,
                               self.dist, dtype),
            "Wo": init_weights(ko, (self.n_out, self.n_out), wi,
                               self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init or 0.0, dtype),
        }

    # -------------------------------------------------------------- carry
    def init_carry(self, batch: int, dtype,
                   cache_len: Optional[int] = None):
        cap = int(cache_len if cache_len is not None else self.cache_len)
        if cap < 1:
            raise ValueError("cache_len must be >= 1")
        shape = (batch, self.n_heads, cap, self._head_dim())
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                jnp.zeros((), jnp.int32))

    def grow_carry(self, carry, cache_len: int):
        """Zero-pad the ring's cache axis up to ``cache_len`` (cursor
        unchanged) — the compile-free bucket hop.  Traceable: runs
        inside the containers' jitted ``decode_grow`` step."""
        k_cache, v_cache, cursor = carry
        cap = k_cache.shape[2]
        if cache_len < cap:
            raise ValueError(
                f"cannot shrink KV ring from {cap} to {cache_len}")
        if cache_len == cap:
            return carry
        pad = [(0, 0), (0, 0), (0, cache_len - cap), (0, 0)]
        return (jnp.pad(k_cache, pad), jnp.pad(v_cache, pad), cursor)

    # ------------------------------------------------------------ forward
    def _project(self, params: ParamTree, x: Array):
        b, t = x.shape[0], x.shape[1]
        h, dh = self.n_heads, self._head_dim()
        q = (x @ params["Wq"]).reshape(b, t, h, dh)
        k = (x @ params["Wk"]).reshape(b, t, h, dh)
        v = (x @ params["Wv"]).reshape(b, t, h, dh)
        return q, k, v

    def _finish(self, params: ParamTree, ctx: Array, x: Array,
                mask: Optional[Array]):
        b, t = x.shape[0], x.shape[1]
        out = self._activate(
            ctx.reshape(b, t, self.n_out) @ params["Wo"] + params["b"])
        if mask is not None:
            # trailing time pad: causal queries never see later keys, so
            # zeroing padded outputs is the whole masking story
            out = out * mask[..., None].astype(out.dtype)
        return out

    def forward_seq(self, params: ParamTree, x: Array, carry, *,
                    train: bool, rng=None, mask: Optional[Array] = None):
        k_cache, v_cache, cursor = carry
        t, cap = x.shape[1], k_cache.shape[2]
        if t > cap:
            raise ValueError(
                f"chunk of {t} timesteps exceeds the KV ring capacity "
                f"{cap}; raise cache_len (or let serving.sessions hop "
                "buckets)")
        x = self.apply_dropout(x, train, rng)
        q, k, v = self._project(params, x)
        k_cache, v_cache = kv_ring_update(
            k_cache, v_cache, cursor,
            jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))
        ctx = kv_ring_attention(q, k_cache, v_cache, cursor)
        out = self._finish(params, ctx, x, mask)
        return out, (k_cache, v_cache,
                     cursor + jnp.asarray(t, jnp.int32))

    def forward(self, params: ParamTree, state, x: Array, *,
                train: bool, rng=None, mask=None):
        if train:
            # training tier: fused flash kernel, no ring; gradients flow
            # through the Pallas custom vjp
            x = self.apply_dropout(x, train, rng)
            q, k, v = self._project(params, x)
            ctx = flash_attention(q, k, v, causal=True)
            return self._finish(params, ctx, x, mask), state
        # inference tier rides the SAME ring-dense math as decode (from
        # a zero ring) — this is what makes N decode steps bit-match
        # one full-sequence output() call
        out, _ = self.forward_seq(
            params, x, self.init_carry(x.shape[0], x.dtype,
                                       max(self.cache_len, x.shape[1])),
            train=False, rng=rng, mask=mask)
        return out, state
