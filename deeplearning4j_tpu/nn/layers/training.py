"""CenterLossOutputLayer.

TPU-native equivalent of the reference's
``nn/layers/training/CenterLossOutputLayer.java`` +
``nn/conf/layers/CenterLossOutputLayer.java`` (builder: ``alpha`` default
0.05, ``lambda`` default 2e-4, ``gradientCheck`` flag) and
``nn/params/CenterLossParamInitializer.java`` (param keys W, b, cL — the
per-class centers, shape (nClasses=n_out, n_in)).

Loss = supervised loss + (lambda/2) * ||features - center_{label}||^2.

The reference updates centers with their own EMA rate ``alpha`` rather than
the optimizer's learning rate: cL uses ``Updater.NONE`` with lr 1.0 and the
applied delta is ``deltaC_j = alpha * sum_{i: y_i=j}(c_j - x_i) /
(count_j + 1)``.  Here that is expressed with a split loss — the feature
path sees the lambda-scaled term against frozen centers (flows through the
normal updater), while the center path is a zero-valued gradient carrier
whose ``jax.grad`` equals deltaC exactly; ``direct_update_params`` then
routes cL around the updater so ``cL -= deltaC`` verbatim — all inside the
same XLA program.  With ``gradient_check=True`` both paths use the exact
lambda-scaled term (full gradient flow), which is what the numerical
gradient checker expects (reference ``gradientCheck`` flag).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import lossfunctions as _losses
from ..conf import serde
from ..weights import init_weights
from .base import Array, FeedForwardLayerConfig, ParamTree, StateTree


@serde.register("center_loss_output")
@dataclasses.dataclass
class CenterLossOutputLayer(FeedForwardLayerConfig):
    """Output layer with an auxiliary center-loss term pulling each class's
    penultimate features toward a learned per-class center."""

    # Scoring needs the layer *input* (the features), not just the
    # preactivation — MultiLayerNetwork._loss_fn routes accordingly.
    NEEDS_INPUT_FOR_SCORE = True

    activation: str = "softmax"
    loss: str = "mcxent"
    alpha: float = 0.05
    lambda_: float = 2e-4
    gradient_check: bool = False

    def param_order(self) -> tuple[str, ...]:
        return ("W", "b", "cL")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kw, _ = jax.random.split(rng)
        return {
            "W": init_weights(kw, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init or 0.0, dtype),
            # Centers start at zero (reference CenterLossParamInitializer).
            "cL": jnp.zeros((self.n_out, self.n_in), dtype),
        }

    def direct_update_params(self) -> tuple[str, ...]:
        # cL bypasses lr/updater entirely (reference Updater.NONE + lr 1.0);
        # under gradient_check the full-flow gradient is used instead.
        return () if self.gradient_check else ("cL",)

    def l1_by_param(self):
        # Centers are not regularized (reference excludes cL from l1/l2).
        return {"W": self.l1 or 0.0, "b": self.l1_bias or 0.0, "cL": 0.0}

    def l2_by_param(self):
        return {"W": self.l2 or 0.0, "b": self.l2_bias or 0.0, "cL": 0.0}

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        x = self.apply_dropout(x, train, rng)
        return self._activate(x @ params["W"] + params["b"]), state

    def pre_output(self, params: ParamTree, x: Array) -> Array:
        return x @ params["W"] + params["b"]

    def compute_score_with_input(self, params: ParamTree, labels: Array,
                                 x: Array, mask: Optional[Array] = None,
                                 average: bool = True) -> Array:
        preout = self.pre_output(params, x)
        supervised = _losses.score(self.loss, labels, preout,
                                   self.activation, mask, average)
        centers = params["cL"].astype(x.dtype)
        lab = labels.astype(x.dtype)
        if mask is not None:
            lab = lab * mask.reshape(lab.shape[0], *([1] * (lab.ndim - 1)))
        assigned = lab @ centers                         # (batch, n_in)
        if self.gradient_check:
            center_term = 0.5 * self.lambda_ * jnp.sum(
                (x - assigned) ** 2, axis=-1)
            if mask is not None:
                center_term = center_term * mask.reshape(center_term.shape)
            total_center = (jnp.mean(center_term) if average
                            else jnp.sum(center_term))
            return supervised + total_center
        # Feature path: lambda-scaled pull toward frozen centers, averaged
        # with the supervised loss (flows to W/b/earlier layers).
        feat_term = 0.5 * self.lambda_ * jnp.sum(
            (x - jax.lax.stop_gradient(assigned)) ** 2, axis=-1)
        if mask is not None:
            feat_term = feat_term * mask.reshape(feat_term.shape)
        total_center = jnp.mean(feat_term) if average else jnp.sum(feat_term)
        # Center path: zero-valued carrier whose gradient wrt cL is exactly
        # the reference delta alpha * labels^T(center - feature) with
        # per-class 1/(count_c + 1) normalization (CenterLossOutputLayer
        # .backpropGradient); NOT averaged over batch — direct_update_params
        # applies it verbatim, mirroring Updater.NONE + lr 1.0.
        counts = jnp.sum(lab, axis=0)                    # (n_out,)
        w = lab @ (1.0 / (counts + 1.0))                 # (batch,)
        carrier = 0.5 * self.alpha * jnp.sum(
            w * jnp.sum((jax.lax.stop_gradient(x) - assigned) ** 2, axis=-1))
        return (supervised + total_center
                + carrier - jax.lax.stop_gradient(carrier))

    def compute_score_examples_with_input(self, params: ParamTree,
                                          labels: Array, x: Array,
                                          mask: Optional[Array] = None
                                          ) -> Array:
        """Per-example scores: supervised loss + lambda/2 ||x - c_y||^2
        per example (reference ``CenterLossOutputLayer
        .computeScoreForExamples``)."""
        preout = self.pre_output(params, x)
        supervised = _losses.score_examples(self.loss, labels, preout,
                                            self.activation, mask)
        centers = params["cL"].astype(x.dtype)
        lab = labels.astype(x.dtype)
        if mask is not None:
            lab = lab * mask.reshape(lab.shape[0], *([1] * (lab.ndim - 1)))
        assigned = lab @ centers
        center_term = 0.5 * self.lambda_ * jnp.sum(
            (x - assigned) ** 2, axis=-1)
        if mask is not None:
            center_term = center_term * mask.reshape(center_term.shape)
        return supervised + center_term
