"""Recurrent layers: GravesLSTM, GravesBidirectionalLSTM, RnnOutputLayer.

TPU-native equivalents of the reference's
``nn/layers/recurrent/GravesLSTM.java`` + ``LSTMHelpers.java`` (501 LoC;
``activateHelper:58`` runs an explicit per-timestep Java loop),
``GravesBidirectionalLSTM.java`` (fwd + bwd passes summed at ``:227``), and
``nn/layers/recurrent/RnnOutputLayer.java``.

Semantics preserved from ``LSTMHelpers.java``:

- fused 4H-wide preactivation ``[block-input | forget | output | input-mod]``
  (``:176-206``; DL4J calls the input gate "input modulation")
- peephole connections stored as 3 extra columns of the recurrent weight
  matrix ``RW`` of shape (H, 4H+3): column 4H = wFF (forget gate, reads
  c_{t-1}), 4H+1 = wOO (output gate, reads c_t), 4H+2 = wGG (input-mod gate,
  reads c_{t-1}) — ``LSTMHelpers.java:104-106``
- block input uses the layer activation fn; the three gates use
  ``gate_activation_fn`` (default sigmoid)
- forget-gate bias initialized to ``forget_gate_bias_init`` — bias slice
  [H, 2H) (``GravesLSTMParamInitializer.java:100``)

TPU-first design: the Java timestep loop becomes ``lax.scan``; the input
projection ``x·W + b`` for ALL timesteps is hoisted out of the scan as one
big MXU matmul, so only the (H,4H) recurrent matmul lives in the scan body.
Data layout is time-major inside the scan, (batch, time, features) at the
API (the reference uses (batch, features, time); preprocessors adapt).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import activations as _activations
from .. import lossfunctions as _losses
from ..conf import inputs as _inputs
from ..conf import serde
from ..weights import init_weights
from .base import Array, BaseLayerConfig, ParamTree, StateTree

InputType = _inputs.InputType

# An LSTM carry is (h, c), each (batch, hidden).
Carry = Tuple[Array, Array]


def _match_varying(tree, ref: Array):
    """Pcast every leaf of ``tree`` to carry the same varying manual axes
    (shard_map vma) as ``ref``.

    Fresh ``jnp.zeros`` carries are unvarying; inside ``shard_map`` (the
    ParallelWrapper step) the scanned inputs are device-varying, and
    ``lax.scan`` requires carry-in and carry-out types to match.  Outside
    shard_map ``ref`` has no vma and this is a no-op.  On jax versions
    without ``jax.typeof``/``lax.pcast`` (no vma type system) it is
    always a no-op."""
    typeof = getattr(jax, "typeof", None)
    pcast = getattr(lax, "pcast", None)
    if typeof is None or pcast is None:
        return tree
    ref_vma = getattr(typeof(ref), "vma", frozenset())
    if not ref_vma:
        return tree

    def cast(leaf):
        missing = ref_vma - getattr(typeof(leaf), "vma", frozenset())
        return pcast(leaf, tuple(missing), to="varying") if missing \
            else leaf

    return jax.tree.map(cast, tree)


def lstm_scan(W: Array, RW: Array, b: Array, x: Array, carry: Carry, *,
              afn, gate_fn, mask: Optional[Array] = None,
              reverse: bool = False) -> Tuple[Array, Carry]:
    """Run the peephole LSTM over a (batch, time, n_in) sequence.

    Returns (outputs (batch, time, H), final (h, c)).  With a (batch, time)
    mask, masked steps pass the previous state through unchanged and emit
    zeros (the reference zeroes masked epsilons/activations via
    ``MaskedReductionUtil``).
    """
    # One big MXU matmul for every timestep's input projection.
    xw = jnp.einsum("bti,ij->btj", x, W) + b
    return lstm_scan_preact(RW, xw, carry, afn=afn, gate_fn=gate_fn,
                            mask=mask, reverse=reverse)


def lstm_scan_preact(RW: Array, xw: Array, carry: Carry, *,
                     afn, gate_fn, mask: Optional[Array] = None,
                     reverse: bool = False) -> Tuple[Array, Carry]:
    """The recurrent chain of :func:`lstm_scan`, taking the already-
    projected (batch, time, 4H) preactivations.  Split out so callers that
    reuse the projection across invocations (the sequence-parallel ring
    scan in ``parallel/sequence.py``) don't recompute it per round."""
    H = RW.shape[0]
    RWg = RW[:, :4 * H]
    w_ff = RW[:, 4 * H]       # forget-gate peephole (reads c_prev)
    w_oo = RW[:, 4 * H + 1]   # output-gate peephole (reads c_current)
    w_gg = RW[:, 4 * H + 2]   # input-mod-gate peephole (reads c_prev)

    xw_t = jnp.swapaxes(xw, 0, 1)                       # (time, batch, 4H)
    mask_t = (None if mask is None
              else jnp.swapaxes(mask, 0, 1))            # (time, batch)
    # Scan carries must be dtype-stable; under mixed precision (bf16
    # activations, f32 weights) the step body promotes, so promote the
    # incoming carry once up front.
    res_dtype = jnp.result_type(xw.dtype, RW.dtype)
    carry = jax.tree.map(lambda a: a.astype(res_dtype), carry)

    def step(c_prev_pair: Carry, inputs):
        h_prev, c_prev = c_prev_pair
        if mask_t is None:
            ifog = inputs
        else:
            ifog, m = inputs
        ifog = ifog + h_prev @ RWg
        z = afn(ifog[:, :H])                            # block input
        f = gate_fn(ifog[:, H:2 * H] + c_prev * w_ff)
        g = gate_fn(ifog[:, 3 * H:4 * H] + c_prev * w_gg)
        c = f * c_prev + g * z
        o = gate_fn(ifog[:, 2 * H:3 * H] + c * w_oo)
        h = o * afn(c)
        if mask_t is None:
            return (h, c), h
        keep = (m > 0)[:, None]
        h_new = jnp.where(keep, h, h_prev)
        c_new = jnp.where(keep, c, c_prev)
        return (h_new, c_new), jnp.where(keep, h, 0.0)

    xs = xw_t if mask_t is None else (xw_t, mask_t)
    carry = _match_varying(carry, xw_t)
    final, ys = lax.scan(step, carry, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), final


@dataclasses.dataclass
class BaseRecurrentLayer(BaseLayerConfig):
    """Layers consuming (batch, time, features) activations and optionally
    carrying hidden state across calls (tBPTT / ``rnnTimeStep``)."""

    INPUT_KIND = "rnn"
    # Whether hidden state can be meaningfully carried across time chunks.
    # False for bidirectional layers: the backward scan needs the whole
    # sequence (the reference GravesBidirectionalLSTM.rnnTimeStep throws
    # UnsupportedOperationException).
    SUPPORTS_CARRY = True

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in <= 0:
            if input_type.kind != "recurrent":
                raise ValueError(
                    f"{type(self).__name__} needs recurrent input, got "
                    f"{input_type.kind}")
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if input_type.kind == "recurrent" else -1
        return _inputs.recurrent(self.n_out, ts)

    # -- stateful-sequence contract ---------------------------------------
    def init_carry(self, batch: int, dtype) -> Carry:
        raise NotImplementedError

    def forward_seq(self, params: ParamTree, x: Array, carry, *,
                    train: bool, rng=None, mask: Optional[Array] = None):
        """(out, new_carry); carry threads tBPTT/streaming state."""
        raise NotImplementedError

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        out, _ = self.forward_seq(
            params, x, self.init_carry(x.shape[0], x.dtype),
            train=train, rng=rng, mask=mask)
        return out, state


@serde.register("graves_lstm")
@dataclasses.dataclass
class GravesLSTM(BaseRecurrentLayer):
    """Peephole LSTM (reference ``nn/conf/layers/GravesLSTM.java`` /
    ``nn/layers/recurrent/GravesLSTM.java``)."""

    forget_gate_bias_init: float = 1.0
    gate_activation_fn: str = "sigmoid"

    def param_order(self) -> tuple[str, ...]:
        # GravesLSTMParamInitializer.java:47-49 layout: W, RW, b.
        return ("W", "RW", "b")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kw, kr = jax.random.split(rng)
        H = self.n_out
        b = jnp.zeros((4 * H,), dtype)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        return {
            "W": init_weights(kw, (self.n_in, 4 * H),
                              self.weight_init or "xavier", self.dist, dtype),
            "RW": init_weights(kr, (H, 4 * H + 3),
                               self.weight_init or "xavier", self.dist, dtype),
            "b": b,
        }

    def init_carry(self, batch: int, dtype) -> Carry:
        H = self.n_out
        return (jnp.zeros((batch, H), dtype), jnp.zeros((batch, H), dtype))

    def forward_seq(self, params: ParamTree, x: Array, carry: Carry, *,
                    train: bool, rng=None, mask: Optional[Array] = None):
        x = self.apply_dropout(x, train, rng)
        return lstm_scan(
            params["W"], params["RW"], params["b"], x, carry,
            afn=_activations.get(self.activation),
            gate_fn=_activations.get(self.gate_activation_fn),
            mask=mask)


@serde.register("graves_bidirectional_lstm")
@dataclasses.dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    """Bidirectional peephole LSTM; forward and backward passes run the same
    cell and their outputs are SUMMED (reference
    ``GravesBidirectionalLSTM.java:227`` ``fwdOutput.addi(backOutput)``).
    Param keys WF/RWF/bF + WB/RWB/bB
    (``GravesBidirectionalLSTMParamInitializer.java:47-53``)."""

    SUPPORTS_CARRY = False

    forget_gate_bias_init: float = 1.0
    gate_activation_fn: str = "sigmoid"

    def param_order(self) -> tuple[str, ...]:
        return ("WF", "RWF", "bF", "WB", "RWB", "bB")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        H = self.n_out
        keys = jax.random.split(rng, 4)
        out: Dict[str, Array] = {}
        for d, (kw, kr) in zip("FB", ((keys[0], keys[1]),
                                      (keys[2], keys[3]))):
            b = jnp.zeros((4 * H,), dtype)
            b = b.at[H:2 * H].set(self.forget_gate_bias_init)
            out["W" + d] = init_weights(
                kw, (self.n_in, 4 * H), self.weight_init or "xavier",
                self.dist, dtype)
            out["RW" + d] = init_weights(
                kr, (H, 4 * H + 3), self.weight_init or "xavier", self.dist,
                dtype)
            out["b" + d] = b
        return out

    def init_carry(self, batch: int, dtype):
        H = self.n_out
        zero = lambda: (jnp.zeros((batch, H), dtype),
                        jnp.zeros((batch, H), dtype))
        return (zero(), zero())

    def forward_seq(self, params: ParamTree, x: Array, carry, *,
                    train: bool, rng=None, mask: Optional[Array] = None):
        x = self.apply_dropout(x, train, rng)
        afn = _activations.get(self.activation)
        gate = _activations.get(self.gate_activation_fn)
        fwd_carry, bwd_carry = carry
        out_f, new_f = lstm_scan(params["WF"], params["RWF"], params["bF"],
                                 x, fwd_carry, afn=afn, gate_fn=gate,
                                 mask=mask)
        out_b, new_b = lstm_scan(params["WB"], params["RWB"], params["bB"],
                                 x, bwd_carry, afn=afn, gate_fn=gate,
                                 mask=mask, reverse=True)
        return out_f + out_b, (new_f, new_b)


@serde.register("rnn_output")
@dataclasses.dataclass
class RnnOutputLayer(BaseRecurrentLayer):
    """Time-distributed dense + loss head (reference
    ``nn/conf/layers/RnnOutputLayer.java`` /
    ``nn/layers/recurrent/RnnOutputLayer.java``): the same W/b applied at
    every timestep, scored against (batch, time, classes) labels with an
    optional (batch, time) mask."""

    activation: str = "softmax"
    loss: str = "mcxent"

    def param_order(self) -> tuple[str, ...]:
        return ("W", "b")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kw, _ = jax.random.split(rng)
        return {
            "W": init_weights(kw, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init or 0.0, dtype),
        }

    def init_carry(self, batch: int, dtype):
        return ()

    def forward_seq(self, params: ParamTree, x: Array, carry, *,
                    train: bool, rng=None, mask=None):
        x = self.apply_dropout(x, train, rng)
        z = x @ params["W"] + params["b"]
        return self._activate(z), carry

    def pre_output(self, params: ParamTree, x: Array) -> Array:
        return x @ params["W"] + params["b"]

    def compute_score(self, labels: Array, preout: Array,
                      mask: Optional[Array] = None,
                      average: bool = True) -> Array:
        return _losses.score(self.loss, labels, preout, self.activation,
                             mask, average)

    def compute_score_examples(self, labels: Array, preout: Array,
                               mask: Optional[Array] = None) -> Array:
        """Per-example scores (reference
        ``BaseOutputLayer.computeScoreForExamples``)."""
        return _losses.score_examples(self.loss, labels, preout,
                                      self.activation, mask)
