"""Unsupervised pretraining layers: AutoEncoder and RBM.

TPU-native equivalents of the reference's
``nn/layers/feedforward/autoencoder/AutoEncoder.java`` and
``nn/layers/feedforward/rbm/RBM.java`` with param layout from
``nn/params/PretrainParamInitializer.java`` (keys ``W``, ``b``, ``vb`` —
the ``vb`` visible bias exists only for the unsupervised phase).

Design: each pretrainable layer exposes

- ``forward`` — the supervised-phase behavior (encode / propUp), identical
  to a DenseLayer with the layer's activation: used when the layer sits
  inside a backprop network;
- ``pretrain_grads(params, x, rng) -> (score, grads)`` — one unsupervised
  step's loss and parameter gradients, consumed by
  ``MultiLayerNetwork.pretrain`` (reference ``MultiLayerNetwork.java:991``)
  inside a jitted XLA step.

For the AutoEncoder the gradients are exact ``jax.grad`` of the
reconstruction loss (the reference hand-derives the same for its
sigmoid/cross-entropy default at ``AutoEncoder.java:120-135``); for the RBM
contrastive divergence is not the gradient of any loss, so ``pretrain_grads``
computes the CD-k statistics explicitly (reference
``RBM.java:101-190`` ``contrastiveDivergence``/``computeGradientAndScore``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import activations as _activations
from .. import lossfunctions as _losses
from ..conf import serde
from ..weights import init_weights
from .base import Array, FeedForwardLayerConfig, ParamTree, StateTree


@dataclasses.dataclass
class BasePretrainLayer(FeedForwardLayerConfig):
    """Shared contract (reference ``nn/layers/BasePretrainNetwork.java`` +
    ``nn/conf/layers/BasePretrainNetwork.java``)."""

    IS_PRETRAINABLE = True

    loss: str = "xent"  # reconstruction loss (RECONSTRUCTION_CROSSENTROPY)
    visible_bias_init: float = 0.0

    def param_order(self) -> tuple[str, ...]:
        return ("W", "b", "vb")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kw, _ = jax.random.split(rng)
        return {
            "W": init_weights(kw, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init or 0.0, dtype),
            "vb": jnp.full((self.n_in,), self.visible_bias_init, dtype),
        }

    def l1_by_param(self):
        return {k: ((self.l1_bias if k in ("b", "vb") else self.l1) or 0.0)
                for k in self.param_order()}

    def l2_by_param(self):
        return {k: ((self.l2_bias if k in ("b", "vb") else self.l2) or 0.0)
                for k in self.param_order()}

    # -- supervised phase: encode only (reference ``activate`` = propUp) ---
    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        x = self.apply_dropout(x, train, rng)
        return self._activate(x @ params["W"] + params["b"]), state

    # -- unsupervised phase ------------------------------------------------
    def pretrain_loss(self, params: ParamTree, x: Array,
                      rng: Optional[jax.Array]) -> Array:
        raise NotImplementedError

    def pretrain_grads(self, params: ParamTree, x: Array,
                       rng: Optional[jax.Array]):
        return jax.value_and_grad(self.pretrain_loss)(params, x, rng)


@serde.register("autoencoder")
@dataclasses.dataclass
class AutoEncoder(BasePretrainLayer):
    """Denoising autoencoder (reference ``nn/conf/layers/AutoEncoder.java``:
    ``corruptionLevel`` default 3e-1, ``sparsity``;
    ``nn/layers/feedforward/autoencoder/AutoEncoder.java``).

    encode: ``act(x W + b)``; decode: ``act(y W^T + vb)`` (tied weights, like
    the reference).  Pretrain loss is the configured reconstruction loss of
    decode(encode(corrupt(x))) against the *clean* input; corruption is
    masking noise (inputs zeroed with probability ``corruption_level``,
    reference ``getCorruptedInput``).
    """

    corruption_level: float = 0.3
    sparsity: float = 0.0

    def encode(self, params: ParamTree, x: Array) -> Array:
        return self._activate(x @ params["W"] + params["b"])

    def decode_preact(self, params: ParamTree, y: Array) -> Array:
        return y @ params["W"].T + params["vb"]

    def decode(self, params: ParamTree, y: Array) -> Array:
        return self._activate(self.decode_preact(params, y))

    def reconstruct(self, params: ParamTree, x: Array) -> Array:
        return self.decode(params, self.encode(params, x))

    def pretrain_loss(self, params: ParamTree, x: Array,
                      rng: Optional[jax.Array]) -> Array:
        corrupted = x
        if self.corruption_level > 0:
            if rng is None:
                raise ValueError("denoising AutoEncoder needs an rng key")
            keep = jax.random.bernoulli(
                rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        y = self.encode(params, corrupted)
        pre_z = self.decode_preact(params, y)
        loss = _losses.score(self.loss, x, pre_z, self.activation or "sigmoid",
                             None, True)
        if self.sparsity > 0:
            # KL(sparsity || mean activation) penalty on hidden units
            rho_hat = jnp.clip(jnp.mean(y, axis=0), 1e-7, 1 - 1e-7)
            rho = self.sparsity
            loss = loss + jnp.sum(rho * jnp.log(rho / rho_hat)
                                  + (1 - rho) * jnp.log((1 - rho)
                                                        / (1 - rho_hat)))
        return loss


@serde.register("rbm")
@dataclasses.dataclass
class RBM(BasePretrainLayer):
    """Restricted Boltzmann machine trained by CD-k (reference
    ``nn/conf/layers/RBM.java`` — HiddenUnit/VisibleUnit enums, ``k`` —
    and ``nn/layers/feedforward/rbm/RBM.java`` ``contrastiveDivergence``).

    Units: hidden ``binary`` (sigmoid probabilities, Bernoulli samples) or
    ``rectified``; visible ``binary`` or ``gaussian`` (identity mean,
    unit-variance noise).  The supervised-phase forward is propUp with the
    layer activation, like the reference.
    """

    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1
    sparsity: float = 0.0

    activation: Optional[str] = "sigmoid"

    def prop_up(self, params: ParamTree, v: Array) -> Array:
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "binary":
            return jax.nn.sigmoid(pre)
        if self.hidden_unit == "rectified":
            return jax.nn.relu(pre)
        raise ValueError(f"Unsupported hidden unit {self.hidden_unit!r}")

    def prop_down_pre(self, params: ParamTree, h: Array) -> Array:
        return h @ params["W"].T + params["vb"]

    def _visible_act(self, pre: Array) -> Array:
        if self.visible_unit == "binary":
            return jax.nn.sigmoid(pre)
        if self.visible_unit == "gaussian":
            return pre
        raise ValueError(f"Unsupported visible unit {self.visible_unit!r}")

    def prop_down(self, params: ParamTree, h: Array) -> Array:
        return self._visible_act(self.prop_down_pre(params, h))

    def _sample_h(self, rng, hprob: Array) -> Array:
        if self.hidden_unit == "binary":
            return jax.random.bernoulli(rng, hprob).astype(hprob.dtype)
        # rectified: N(mean, sigmoid(mean)) clipped at 0 (reference
        # RBM.java sampleHiddenGivenVisible RECTIFIED branch)
        noise = jax.random.normal(rng, hprob.shape, hprob.dtype)
        return jax.nn.relu(hprob + noise * jnp.sqrt(
            jax.nn.sigmoid(hprob) + 1e-8))

    def _sample_v(self, rng, vprob: Array) -> Array:
        if self.visible_unit == "binary":
            return jax.random.bernoulli(rng, vprob).astype(vprob.dtype)
        return vprob + jax.random.normal(rng, vprob.shape, vprob.dtype)

    def pretrain_grads(self, params: ParamTree, x: Array,
                       rng: Optional[jax.Array]):
        if rng is None:
            raise ValueError("RBM contrastive divergence needs an rng key")
        batch = x.shape[0]
        hprob0 = self.prop_up(params, x)
        keys = jax.random.split(rng, 2 * self.k + 1)
        hsamp = self._sample_h(keys[0], hprob0)
        vprob = x
        hprob = hprob0
        pre_vk = x
        for step in range(self.k):
            pre_vk = self.prop_down_pre(params, hsamp)
            vprob = self._visible_act(pre_vk)
            vsamp = (self._sample_v(keys[2 * step + 1], vprob)
                     if self.visible_unit == "binary" else vprob)
            hprob = self.prop_up(params, vsamp)
            hsamp = self._sample_h(keys[2 * step + 2], hprob)
        vk, hk = vprob, hprob
        # Likelihood ascent: Δθ ∝ (positive − negative) statistics; the
        # updater applies ``p -= update(g)`` so the gradient is the negation.
        grads = {
            "W": -(x.T @ hprob0 - vk.T @ hk) / batch,
            "b": -jnp.mean(hprob0 - hk, axis=0),
            "vb": -jnp.mean(x - vk, axis=0),
        }
        # Monitored score: reconstruction error against the chain's last
        # negative visible phase v_k (reference setScoreWithZ(negVSamples)) —
        # NOT one extra half-step from the post-loop hidden sample.
        act = "sigmoid" if self.visible_unit == "binary" else "identity"
        score = _losses.score(self.loss if self.visible_unit == "binary"
                              else "mse", x, pre_vk, act, None, True)
        return score, grads

    def free_energy(self, params: ParamTree, v: Array) -> Array:
        """Mean free energy F(v) = -v·vb - sum log(1+e^{vW+b}) (binary)."""
        pre = v @ params["W"] + params["b"]
        return jnp.mean(-v @ params["vb"]
                        - jnp.sum(jax.nn.softplus(pre), axis=-1))
