"""Global pooling layer.

Equivalent of the reference ``nn/layers/pooling/GlobalPoolingLayer.java``
(321 LoC; PoolingType MAX/AVG/SUM/PNORM — ``nn/conf/layers/PoolingType.java``).
Pools CNN activations over H,W or RNN activations over time, with optional
per-timestep mask support (reference ``MaskedReductionUtil``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..conf import inputs as _inputs
from ..conf import serde
from .base import Array, BaseLayerConfig, ParamTree, StateTree

InputType = _inputs.InputType


@serde.register("global_pooling")
@dataclasses.dataclass
class GlobalPoolingLayer(BaseLayerConfig):
    """pooling_type: max | avg | sum | pnorm; collapses spatial/time axes."""

    INPUT_KIND = "any"

    pooling_type: str = "avg"
    pnorm: int = 2
    collapse_dimensions: bool = True
    activation: str = "identity"

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind in ("cnn", "cnn_flat"):
            if not self.collapse_dimensions:
                return _inputs.convolutional(1, 1, input_type.channels)
            return _inputs.feed_forward(input_type.channels)
        if input_type.kind == "recurrent":
            if not self.collapse_dimensions:
                return _inputs.recurrent(input_type.size, 1)
            return _inputs.feed_forward(input_type.size)
        return input_type

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask: Optional[Array] = None):
        if x.ndim == 4:        # NHWC -> pool over H,W
            axes = (1, 2)
            m = None
        elif x.ndim == 3:      # (batch, time, features) -> pool over time
            axes = (1,)
            m = mask
        else:
            return x, state
        kind = self.pooling_type
        keep = not self.collapse_dimensions
        if m is not None:
            mm = m[..., None]  # (batch, time, 1)
            if kind == "max":
                neg = jnp.finfo(x.dtype).min
                out = jnp.max(jnp.where(mm > 0, x, neg), axis=axes)
            elif kind in ("avg", "sum"):
                total = jnp.sum(x * mm, axis=axes)
                if kind == "sum":
                    out = total
                else:
                    out = total / jnp.clip(jnp.sum(mm, axis=axes), 1.0, None)
            elif kind == "pnorm":
                powed = jnp.power(jnp.abs(x * mm), self.pnorm)
                out = jnp.power(jnp.sum(powed, axis=axes), 1.0 / self.pnorm)
            else:
                raise ValueError(f"Unknown pooling type '{kind}'")
        else:
            if kind == "max":
                out = jnp.max(x, axis=axes)
            elif kind == "avg":
                out = jnp.mean(x, axis=axes)
            elif kind == "sum":
                out = jnp.sum(x, axis=axes)
            elif kind == "pnorm":
                out = jnp.power(
                    jnp.sum(jnp.power(jnp.abs(x), self.pnorm), axis=axes),
                    1.0 / self.pnorm)
            else:
                raise ValueError(f"Unknown pooling type '{kind}'")
        if keep:
            # collapseDimensions=false keeps unit pooled axes (reference
            # GlobalPoolingLayer: [n,c,1,1] for CNN, [n,f,1] for RNN).
            out = jnp.expand_dims(out, axes)
        return self._activate(out), state
