"""Core feed-forward layers: Dense, Output, Loss, Activation, Dropout,
Embedding.

Equivalents of the reference configs in ``nn/conf/layers/`` (DenseLayer,
OutputLayer, LossLayer, ActivationLayer, DropoutLayer, EmbeddingLayer) and
their impls under ``nn/layers/feedforward/`` + ``nn/layers/BaseLayer.java``
(generic ``W·x + b`` preOutput at ``BaseLayer.java:356``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import lossfunctions as _losses
from ..conf import inputs as _inputs
from ..conf import serde
from .base import (Array, BaseLayerConfig, FeedForwardLayerConfig, ParamTree,
                   StateTree)

InputType = _inputs.InputType


@serde.register("dense")
@dataclasses.dataclass
class DenseLayer(FeedForwardLayerConfig):
    """Fully connected layer (reference ``nn/conf/layers/DenseLayer.java`` /
    ``nn/layers/feedforward/dense/DenseLayer.java``).

    Forward: ``activation(x @ W + b)`` — one MXU matmul; the activation fuses
    into the same XLA computation.
    """

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        x = self.apply_dropout(x, train, rng)
        z = x @ params["W"] + params["b"]
        return self._activate(z), state


@serde.register("output")
@dataclasses.dataclass
class OutputLayer(FeedForwardLayerConfig):
    """Dense + loss head (reference ``nn/conf/layers/OutputLayer.java`` /
    ``nn/layers/OutputLayer.java``).  ``activation`` defaults to softmax with
    MCXENT loss, matching the reference defaults."""

    activation: str = "softmax"
    loss: str = "mcxent"

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        x = self.apply_dropout(x, train, rng)
        z = x @ params["W"] + params["b"]
        return self._activate(z), state

    def pre_output(self, params: ParamTree, x: Array) -> Array:
        return x @ params["W"] + params["b"]

    def compute_score(self, labels: Array, preout: Array,
                      mask: Optional[Array] = None,
                      average: bool = True) -> Array:
        return _losses.score(self.loss, labels, preout, self.activation,
                             mask, average)

    def compute_score_examples(self, labels: Array, preout: Array,
                               mask: Optional[Array] = None) -> Array:
        """Per-example scores (reference
        ``BaseOutputLayer.computeScoreForExamples``)."""
        return _losses.score_examples(self.loss, labels, preout,
                                      self.activation, mask)


@serde.register("loss")
@dataclasses.dataclass
class LossLayer(BaseLayerConfig):
    """Loss-only layer with no params (reference
    ``nn/conf/layers/LossLayer.java`` / ``nn/layers/LossLayer.java``)."""

    activation: str = "identity"
    loss: str = "mse"

    INPUT_KIND = "any"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        return self._activate(x), state

    def pre_output(self, params: ParamTree, x: Array) -> Array:
        return x

    def compute_score(self, labels: Array, preout: Array,
                      mask: Optional[Array] = None,
                      average: bool = True) -> Array:
        return _losses.score(self.loss, labels, preout, self.activation,
                             mask, average)

    def compute_score_examples(self, labels: Array, preout: Array,
                               mask: Optional[Array] = None) -> Array:
        """Per-example scores (reference
        ``BaseOutputLayer.computeScoreForExamples``)."""
        return _losses.score_examples(self.loss, labels, preout,
                                      self.activation, mask)


@serde.register("activation")
@dataclasses.dataclass
class ActivationLayer(BaseLayerConfig):
    """Standalone activation (reference ``nn/conf/layers/ActivationLayer.java``)."""

    INPUT_KIND = "any"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        return self._activate(x), state


@serde.register("dropout_layer")
@dataclasses.dataclass
class DropoutLayer(BaseLayerConfig):
    """Standalone dropout (reference ``nn/conf/layers/DropoutLayer.java``);
    identity at inference."""

    activation: str = "identity"

    INPUT_KIND = "any"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        return self._activate(self.apply_dropout(x, train, rng)), state


@serde.register("embedding")
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayerConfig):
    """Index -> vector lookup (reference
    ``nn/conf/layers/EmbeddingLayer.java`` /
    ``nn/layers/feedforward/embedding/EmbeddingLayer.java``).

    Input is an integer index array of shape ``(batch,)`` or ``(batch, 1)``
    (the reference takes a column of indices).  The lookup is a gather — XLA
    lowers it to an HBM-friendly dynamic-slice rather than the reference's
    row-view copy.
    """

    activation: str = "identity"

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        z = params["W"][idx] + params["b"]
        return self._activate(z), state
