"""Base layer contract.

TPU-native equivalent of the reference's ``nn/api/Layer.java:37`` +
``nn/conf/layers/Layer.java`` pair.  The reference splits declarative config
(Jackson POJO) from the imperative layer object holding param views; here the
two merge into one dataclass: serializable hyperparameters plus pure
functions ``init_params`` / ``forward``.  Backprop (the reference's
``backpropGradient``) is not hand-written — the whole network forward composes
into one differentiable function and ``jax.grad`` supplies exact gradients,
compiled with the forward into a single XLA program.

State (e.g. batch-norm running statistics) is threaded explicitly:
``forward(params, state, x, train, rng) -> (out, new_state)``, keeping every
layer jit/pjit/scan-compatible.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import activations as _activations
from ..updaters import UpdaterConfig
from ..weights import Distribution, init_weights
from ..conf import inputs as _inputs

Array = jax.Array
ParamTree = Dict[str, Array]
StateTree = Dict[str, Array]
InputType = _inputs.InputType


@dataclasses.dataclass
class BaseLayerConfig:
    """Hyperparameters shared by every layer (reference
    ``nn/conf/layers/Layer.java`` fields + per-layer overrides of the global
    ``NeuralNetConfiguration.Builder`` values, builder methods at
    ``NeuralNetConfiguration.java:521-900``)."""

    # ``None`` means "inherit the network-level default" — the reference
    # clones global builder values into each layer conf unless the layer
    # overrides them; ``finalize_defaults`` performs that resolution here.
    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    dropout: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    updater: Optional[UpdaterConfig] = None  # None -> network default
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    # frozen layers take no parameter updates (transfer-learning feature
    # extractors — reference FrozenLayer semantics); forward/dropout/
    # regularization reporting behave normally
    frozen: bool = False

    _INHERITABLE = ("activation", "weight_init", "dist", "bias_init",
                    "dropout", "l1", "l2", "l1_bias", "l2_bias", "updater",
                    "gradient_normalization")

    def finalize_defaults(self, defaults: "Dict[str, object]") -> None:
        """Fill unset (None) inheritable fields from network-level defaults."""
        for field in self._INHERITABLE:
            if getattr(self, field, None) is None and field in defaults:
                setattr(self, field, defaults[field])

    # ---- shape inference -------------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def set_n_in(self, input_type: InputType) -> None:
        """Infer and set n_in from the incoming InputType (no-op for layers
        without explicit fan-in)."""

    # ---- params / state --------------------------------------------------
    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        return {}

    def init_state(self, dtype=jnp.float32) -> StateTree:
        return {}

    def param_order(self) -> tuple[str, ...]:
        """Deterministic param ordering inside the flat parameter vector
        (the reference's ParamInitializer layout, e.g. W then b —
        ``nn/params/DefaultParamInitializer.java``)."""
        return ()

    # ---- forward ---------------------------------------------------------
    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng: Optional[jax.Array] = None,
                mask: Optional[Array] = None) -> Tuple[Array, StateTree]:
        raise NotImplementedError

    def direct_update_params(self) -> tuple[str, ...]:
        """Param names whose gradient is applied directly (``p -= g``),
        bypassing lr/updater/grad-norm — the reference's ``Updater.NONE`` +
        lr 1.0 per-param override (e.g. center-loss cL,
        ``CenterLossOutputLayer.getUpdaterByParam``)."""
        return ()

    # ---- regularization wiring ------------------------------------------
    def l1_by_param(self) -> Dict[str, float]:
        out = {}
        for k in self.param_order():
            out[k] = (self.l1_bias if k == "b" else self.l1) or 0.0
        return out

    def l2_by_param(self) -> Dict[str, float]:
        out = {}
        for k in self.param_order():
            out[k] = (self.l2_bias if k == "b" else self.l2) or 0.0
        return out

    # ---- helpers ---------------------------------------------------------
    def _activate(self, z: Array) -> Array:
        return _activations.get(self.activation)(z)

    def apply_dropout(self, x: Array, train: bool,
                      rng: Optional[jax.Array]) -> Array:
        """Inverted dropout on the layer *input* during training (reference
        ``BaseLayer.applyDropOutIfNecessary:486`` / ``util/Dropout.java``)."""
        if not train or not self.dropout or self.dropout <= 0.0:
            return x
        if rng is None:
            raise ValueError(
                f"Layer {self.name or type(self).__name__}: dropout requires "
                "an rng key at training time")
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


@dataclasses.dataclass
class FeedForwardLayerConfig(BaseLayerConfig):
    """Base for layers with explicit n_in/n_out (reference
    ``nn/conf/layers/FeedForwardLayer.java``)."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return _inputs.feed_forward(self.n_out)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in <= 0:
            self.n_in = input_type.flat_size()

    def param_order(self) -> tuple[str, ...]:
        return ("W", "b")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kw, _ = jax.random.split(rng)
        return {
            "W": init_weights(kw, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init or 0.0, dtype),
        }
