"""Layer configs/implementations.

Importing this package registers every built-in layer type with the config
serde registry (the analogue of the reference's Jackson subtype list), so
JSON round-trips work regardless of which layer module the user touched
first.
"""

from . import attention  # noqa: F401
from . import base  # noqa: F401
from . import convolution  # noqa: F401
from . import core  # noqa: F401
from . import normalization  # noqa: F401
from . import pooling  # noqa: F401
from . import pretrain  # noqa: F401
from . import recurrent  # noqa: F401
from . import training  # noqa: F401
from . import variational  # noqa: F401
