"""Variational autoencoder layer + pluggable reconstruction distributions.

TPU-native equivalent of the reference's
``nn/layers/variational/VariationalAutoencoder.java`` (1063 LoC) and the
distribution classes under ``nn/conf/layers/variational/``:
Gaussian/Bernoulli/Exponential/Composite/LossFunctionWrapper.

Semantics (reference ``computeGradientAndScore`` at
``VariationalAutoencoder.java:101-200``):

- encoder MLP -> preactivations of q(z|x) mean and log sigma^2 (two heads
  off the last encoder activation; ``pzxActivationFn`` applied to both);
- score = KL[q(z|x) || N(0, I)] (analytic, computed once)
  + (1/numSamples) * sum over MC samples of the reconstruction
  negative log probability, averaged over the minibatch, + l1/l2;
- z = mu + sigma * eps reparameterization, decoder MLP -> distribution
  preactivations, ``ReconstructionDistribution.negLogProbability``.

The whole pretrain loss is one differentiable function: ``jax.grad``
replaces the reference's 250-line hand-written backprop, and the MC loop
(numSamples, default 1) unrolls into the same XLA program.

The supervised-phase ``forward`` returns ``pzxActivationFn(mean preout)``
exactly like the reference's ``activate`` (``VariationalAutoencoder.java:
425-431``): a VAE inside a backprop net contributes its posterior mean.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import activations as _activations
from .. import lossfunctions as _losses
from ..conf import serde
from ..weights import init_weights
from .base import Array, FeedForwardLayerConfig, ParamTree, StateTree

_NEG_HALF_LOG_2PI = -0.5 * float(np.log(2.0 * np.pi))


# --------------------------------------------------------------------------
# Reconstruction distributions (reference nn/conf/layers/variational/*)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ReconstructionDistribution:
    """p(x|z) parameterized by decoder preactivations."""

    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        """Sum over batch+features of -log p(x|preout)."""
        raise NotImplementedError

    def generate_at_mean(self, preout: Array) -> Array:
        raise NotImplementedError

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        raise NotImplementedError


@serde.register("gaussian_reconstruction")
@dataclasses.dataclass
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Reference ``GaussianReconstructionDistribution.java``: preout is
    ``[mean | log sigma^2]`` (2x data size), activation applied to both."""

    def input_size(self, data_size: int) -> int:
        return 2 * data_size

    def _params(self, preout: Array) -> Tuple[Array, Array]:
        out = _activations.get(self.activation)(preout)
        size = preout.shape[-1] // 2
        return out[..., :size], out[..., size:]

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        mean, log_sigma2 = self._params(preout)
        sigma2 = jnp.exp(log_sigma2)
        log_prob = (x.shape[0] * (preout.shape[-1] // 2) * _NEG_HALF_LOG_2PI
                    - 0.5 * jnp.sum(log_sigma2)
                    - jnp.sum((x - mean) ** 2 / (2.0 * sigma2)))
        return -log_prob

    def generate_at_mean(self, preout: Array) -> Array:
        return self._params(preout)[0]

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        mean, log_sigma2 = self._params(preout)
        return mean + jnp.exp(0.5 * log_sigma2) * jax.random.normal(
            rng, mean.shape, mean.dtype)


@serde.register("bernoulli_reconstruction")
@dataclasses.dataclass
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Reference ``BernoulliReconstructionDistribution.java`` (sigmoid)."""

    activation: str = "sigmoid"

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        if self.activation == "sigmoid":
            # Numerically stable fused sigmoid + BCE on the preactivation.
            return jnp.sum(jax.nn.softplus(preout) - x * preout)
        p = jnp.clip(_activations.get(self.activation)(preout), 1e-10,
                     1 - 1e-10)
        return -jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log1p(-p))

    def generate_at_mean(self, preout: Array) -> Array:
        return _activations.get(self.activation)(preout)

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        p = _activations.get(self.activation)(preout)
        return jax.random.bernoulli(rng, p).astype(preout.dtype)


@serde.register("exponential_reconstruction")
@dataclasses.dataclass
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Reference ``ExponentialReconstructionDistribution.java``: network
    models gamma = log(lambda); log p(x) = gamma - lambda * x."""

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        gamma = _activations.get(self.activation)(preout)
        lam = jnp.exp(gamma)
        return -jnp.sum(gamma - lam * x)

    def generate_at_mean(self, preout: Array) -> Array:
        gamma = _activations.get(self.activation)(preout)
        return jnp.exp(-gamma)  # mean = 1/lambda

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        gamma = _activations.get(self.activation)(preout)
        u = jax.random.uniform(rng, gamma.shape, gamma.dtype, 1e-10, 1.0)
        return -jnp.log(u) * jnp.exp(-gamma)


@serde.register("loss_wrapper_reconstruction")
@dataclasses.dataclass
class LossFunctionWrapper(ReconstructionDistribution):
    """Reference ``LossFunctionWrapper.java``: treat an ILossFunction as an
    (improper) reconstruction "distribution" — no probabilistic
    interpretation, just loss-per-example summed."""

    loss: str = "mse"

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        return _losses.score(self.loss, x, preout, self.activation, None,
                             False)

    def generate_at_mean(self, preout: Array) -> Array:
        return _activations.get(self.activation)(preout)

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        return self.generate_at_mean(preout)


@serde.register("composite_reconstruction")
@dataclasses.dataclass
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Reference ``CompositeReconstructionDistribution.java``: different
    distributions over slices of the data vector.  ``parts`` is a list of
    ``(data_size, distribution)`` pairs."""

    parts: Sequence[Tuple[int, ReconstructionDistribution]] = ()

    def __post_init__(self):
        # JSON round-trip support: parts arrive as [[size, {"type": ...}]].
        decoded = []
        for size, dist in self.parts:
            if isinstance(dist, dict):
                dist = serde.from_dict(dist)
            decoded.append((int(size), dist))
        self.parts = tuple(decoded)

    def input_size(self, data_size: int) -> int:
        total = sum(size for size, _ in self.parts)
        if total != data_size:
            raise ValueError(
                f"Composite parts cover {total} features, data has "
                f"{data_size}")
        return sum(dist.input_size(size) for size, dist in self.parts)

    def _slices(self):
        x_off = p_off = 0
        for size, dist in self.parts:
            p_size = dist.input_size(size)
            yield (slice(x_off, x_off + size),
                   slice(p_off, p_off + p_size), dist)
            x_off += size
            p_off += p_size

    def neg_log_prob(self, x: Array, preout: Array) -> Array:
        total = jnp.asarray(0.0, preout.dtype)
        for xs, ps, dist in self._slices():
            total = total + dist.neg_log_prob(x[..., xs], preout[..., ps])
        return total

    def generate_at_mean(self, preout: Array) -> Array:
        return jnp.concatenate(
            [dist.generate_at_mean(preout[..., ps])
             for _, ps, dist in self._slices()], axis=-1)

    def sample(self, rng: jax.Array, preout: Array) -> Array:
        keys = jax.random.split(rng, max(1, len(self.parts)))
        return jnp.concatenate(
            [dist.sample(keys[i], preout[..., ps])
             for i, (_, ps, dist) in enumerate(self._slices())], axis=-1)


# --------------------------------------------------------------------------
# The layer
# --------------------------------------------------------------------------


@serde.register("variational_autoencoder")
@dataclasses.dataclass
class VariationalAutoencoder(FeedForwardLayerConfig):
    """Reference ``nn/conf/layers/variational/VariationalAutoencoder.java``
    (builder: encoderLayerSizes/decoderLayerSizes/reconstructionDistribution/
    pzxActivationFunction/numSamples) + the 1063-LoC impl.

    ``n_out`` is the latent size.  Param keys mirror
    ``VariationalAutoencoderParamInitializer``: ``e{i}W/e{i}b`` encoder,
    ``pZXMeanW/pZXMeanb/pZXLogStd2W/pZXLogStd2b`` posterior heads,
    ``d{i}W/d{i}b`` decoder, ``pXZW/pXZb`` reconstruction head.
    """

    IS_PRETRAINABLE = True

    encoder_layer_sizes: Sequence[int] = (100,)
    decoder_layer_sizes: Sequence[int] = (100,)
    pzx_activation: str = "identity"
    reconstruction_distribution: ReconstructionDistribution = \
        dataclasses.field(default_factory=GaussianReconstructionDistribution)
    num_samples: int = 1

    def param_order(self) -> tuple[str, ...]:
        order: List[str] = []
        for i in range(len(self.encoder_layer_sizes)):
            order += [f"e{i}W", f"e{i}b"]
        order += ["pZXMeanW", "pZXMeanb", "pZXLogStd2W", "pZXLogStd2b"]
        for i in range(len(self.decoder_layer_sizes)):
            order += [f"d{i}W", f"d{i}b"]
        order += ["pXZW", "pXZb"]
        return tuple(order)

    def l1_by_param(self):
        return {k: ((self.l1_bias if k.endswith("b") else self.l1) or 0.0)
                for k in self.param_order()}

    def l2_by_param(self):
        return {k: ((self.l2_bias if k.endswith("b") else self.l2) or 0.0)
                for k in self.param_order()}

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        params: ParamTree = {}
        wi = self.weight_init or "xavier"
        bias = self.bias_init or 0.0
        sizes = list(self.encoder_layer_sizes)
        keys = jax.random.split(rng, len(sizes)
                                + len(self.decoder_layer_sizes) + 3)
        k = 0
        n_prev = self.n_in
        for i, h in enumerate(sizes):
            params[f"e{i}W"] = init_weights(keys[k], (n_prev, h), wi,
                                            self.dist, dtype)
            params[f"e{i}b"] = jnp.full((h,), bias, dtype)
            n_prev = h
            k += 1
        params["pZXMeanW"] = init_weights(keys[k], (n_prev, self.n_out), wi,
                                          self.dist, dtype)
        params["pZXMeanb"] = jnp.full((self.n_out,), bias, dtype)
        k += 1
        params["pZXLogStd2W"] = init_weights(keys[k], (n_prev, self.n_out),
                                             wi, self.dist, dtype)
        params["pZXLogStd2b"] = jnp.full((self.n_out,), bias, dtype)
        k += 1
        n_prev = self.n_out
        for i, h in enumerate(self.decoder_layer_sizes):
            params[f"d{i}W"] = init_weights(keys[k], (n_prev, h), wi,
                                            self.dist, dtype)
            params[f"d{i}b"] = jnp.full((h,), bias, dtype)
            n_prev = h
            k += 1
        out_size = self.reconstruction_distribution.input_size(self.n_in)
        params["pXZW"] = init_weights(keys[k], (n_prev, out_size), wi,
                                      self.dist, dtype)
        params["pXZb"] = jnp.full((out_size,), bias, dtype)
        return params

    # ------------------------------------------------------------- pieces
    def _encode(self, params: ParamTree, x: Array) -> Array:
        afn = _activations.get(self.activation or "tanh")
        for i in range(len(self.encoder_layer_sizes)):
            x = afn(x @ params[f"e{i}W"] + params[f"e{i}b"])
        return x

    def _posterior(self, params: ParamTree, x: Array) -> Tuple[Array, Array]:
        enc = self._encode(params, x)
        pzx_fn = _activations.get(self.pzx_activation)
        mean = pzx_fn(enc @ params["pZXMeanW"] + params["pZXMeanb"])
        log_sigma2 = pzx_fn(enc @ params["pZXLogStd2W"]
                            + params["pZXLogStd2b"])
        return mean, log_sigma2

    def _decode(self, params: ParamTree, z: Array) -> Array:
        afn = _activations.get(self.activation or "tanh")
        x = z
        for i in range(len(self.decoder_layer_sizes)):
            x = afn(x @ params[f"d{i}W"] + params[f"d{i}b"])
        return x @ params["pXZW"] + params["pXZb"]

    # ---------------------------------------------------------- supervised
    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None) -> Tuple[Array, StateTree]:
        x = self.apply_dropout(x, train, rng)
        enc = self._encode(params, x)
        pzx_fn = _activations.get(self.pzx_activation)
        return pzx_fn(enc @ params["pZXMeanW"] + params["pZXMeanb"]), state

    # --------------------------------------------------------- unsupervised
    def pretrain_loss(self, params: ParamTree, x: Array,
                      rng: Optional[jax.Array]) -> Array:
        if rng is None:
            raise ValueError("VAE pretraining needs an rng key")
        batch = x.shape[0]
        mean, log_sigma2 = self._posterior(params, x)
        sigma2 = jnp.exp(log_sigma2)
        # KL[q(z|x) || N(0,I)], averaged over the minibatch (reference
        # scorePt1 at VariationalAutoencoder.java:160-166).
        kl = (-0.5 / batch) * jnp.sum(1.0 + log_sigma2 - mean * mean
                                      - sigma2)
        sigma = jnp.sqrt(sigma2)
        nll = jnp.asarray(0.0, x.dtype)
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean + sigma * eps
            preout = self._decode(params, z)
            nll = nll + self.reconstruction_distribution.neg_log_prob(
                x, preout)
        return kl + nll / (self.num_samples * batch)

    def pretrain_grads(self, params: ParamTree, x: Array,
                       rng: Optional[jax.Array]):
        return jax.value_and_grad(self.pretrain_loss)(params, x, rng)

    # ----------------------------------------------------------- public API
    def reconstruction_log_probability(self, params: ParamTree, x: Array,
                                       num_samples: int,
                                       rng: jax.Array) -> Array:
        """Per-example log P(x) IS estimate (reference
        ``reconstructionLogProbability:869-905``): log mean_s p(x|z_s) with
        z_s ~ q(z|x)."""
        mean, log_sigma2 = self._posterior(params, x)
        sigma = jnp.exp(0.5 * log_sigma2)

        per_sample = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean + sigma * eps
            preout = self._decode(params, z)
            # per-example log prob: re-express the summed NLL per example
            per = -jax.vmap(
                lambda xe, pe: self.reconstruction_distribution.neg_log_prob(
                    xe[None], pe[None]))(x, preout)
            per_sample.append(per)
        stacked = jnp.stack(per_sample)           # (S, batch)
        return jax.nn.logsumexp(stacked, axis=0) - jnp.log(
            float(num_samples))

    def generate_at_mean_given_z(self, params: ParamTree, z: Array) -> Array:
        return self.reconstruction_distribution.generate_at_mean(
            self._decode(params, z))

    def generate_random_given_z(self, params: ParamTree, z: Array,
                                rng: jax.Array) -> Array:
        return self.reconstruction_distribution.sample(
            rng, self._decode(params, z))
