"""Normalization layers: BatchNormalization and LocalResponseNormalization.

Equivalents of the reference ``nn/conf/layers/BatchNormalization.java`` /
``nn/layers/normalization/BatchNormalization.java`` (452 LoC) and
``LocalResponseNormalization.java``, with the cuDNN helper tier replaced by
fused XLA elementwise ops (``ops.convolution.batch_norm_*``).

State-layout note (serialization-parity gotcha, SURVEY.md §2.1): the
reference stores the non-trainable running mean/var *inside the param
vector* (``BatchNormalizationParamInitializer.java:26,66-76`` — order gamma,
beta, mean, var).  Here they live in the layer ``state`` pytree (pure-function
friendly); the ModelSerializer stores them in a separate ``state.bin`` entry.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ...ops import convolution as conv_ops
from ..conf import inputs as _inputs
from ..conf import serde
from .base import Array, BaseLayerConfig, ParamTree, StateTree

InputType = _inputs.InputType


@serde.register("batch_norm")
@dataclasses.dataclass
class BatchNormalization(BaseLayerConfig):
    """Batch normalization over the feature/channel axis.

    Defaults mirror the reference config: decay 0.9 (running-average
    momentum), eps 1e-5, optional gamma/beta locking (``lockGammaBeta`` —
    fixed values, no learning).
    """

    INPUT_KIND = "any"

    n_out: int = 0            # feature/channel count (inferred)
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma_init: float = 1.0
    beta_init: float = 0.0
    activation: str = "identity"

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_out <= 0:
            if input_type.kind in ("cnn", "cnn_flat"):
                self.n_out = input_type.channels
            else:
                self.n_out = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def param_order(self) -> tuple[str, ...]:
        return () if self.lock_gamma_beta else ("gamma", "beta")

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((self.n_out,), self.gamma_init, dtype),
            "beta": jnp.full((self.n_out,), self.beta_init, dtype),
        }

    def init_state(self, dtype=jnp.float32) -> StateTree:
        return {
            "mean": jnp.zeros((self.n_out,), dtype),
            "var": jnp.ones((self.n_out,), dtype),
        }

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        gamma = params.get("gamma",
                           jnp.asarray(self.gamma_init, x.dtype))
        beta = params.get("beta", jnp.asarray(self.beta_init, x.dtype))
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        if train:
            out, mean, var = conv_ops.batch_norm_train(
                x, gamma, beta, axes, self.eps)
            d = self.decay
            # Cast to the state dtype: batch stats arrive in the compute
            # dtype (possibly bf16), and dtype drift in the carried state
            # would force a retrace+recompile of the donated train step.
            new_state = {
                "mean": (d * state["mean"] + (1.0 - d) * mean).astype(
                    state["mean"].dtype),
                "var": (d * state["var"] + (1.0 - d) * var).astype(
                    state["var"].dtype),
            }
            return self._activate(out), new_state
        out = conv_ops.batch_norm_inference(
            x, gamma, beta, state["mean"], state["var"], self.eps)
        return self._activate(out), state


@serde.register("lrn")
@dataclasses.dataclass
class LocalResponseNormalization(BaseLayerConfig):
    """Cross-channel LRN (reference
    ``nn/conf/layers/LocalResponseNormalization.java``; defaults k=2, n=5,
    alpha=1e-4, beta=0.75 as in the reference config)."""

    INPUT_KIND = "cnn"

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    activation: str = "identity"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        out = conv_ops.local_response_normalization(
            x, self.k, self.n, self.alpha, self.beta)
        return out, state
