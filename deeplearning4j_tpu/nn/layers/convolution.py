"""Convolution and subsampling (pooling) layers.

Equivalents of the reference configs ``nn/conf/layers/ConvolutionLayer.java``
and ``SubsamplingLayer.java`` and impls
``nn/layers/convolution/ConvolutionLayer.java`` (im2col+gemm at :172-185) /
``subsampling/SubsamplingLayer.java``.  The compute goes through
``ops.convolution`` — XLA convs on the MXU rather than im2col, and the
backward pass is ``jax.grad``'s transposed conv (the analogue of the cuDNN
helper's backward-data/backward-filter calls at
``CudnnConvolutionHelper.java``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...ops import convolution as conv_ops
from ..conf import inputs as _inputs
from ..conf import serde
from ..weights import init_weights
from .base import Array, BaseLayerConfig, ParamTree, StateTree

InputType = _inputs.InputType


@serde.register("convolution")
@dataclasses.dataclass
class ConvolutionLayer(BaseLayerConfig):
    """2-D convolution (reference ``nn/conf/layers/ConvolutionLayer.java``).

    ``n_in`` = input channels (inferred), ``n_out`` = filters.  Kernel is
    stored HWIO; the flat-param exporter transposes to the reference's
    (out, in, kh, kw) order for serialization parity.
    """

    INPUT_KIND = "cnn"

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"   # truncate | same | strict
    has_bias: bool = True

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in <= 0:
            if input_type.kind not in ("cnn", "cnn_flat"):
                raise ValueError(
                    f"ConvolutionLayer needs convolutional input, got "
                    f"{input_type.kind}")
            self.n_in = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        h = conv_ops.conv_output_size(
            input_type.height, self.kernel_size[0], self.stride[0],
            self.padding[0], self.convolution_mode, self.dilation[0])
        w = conv_ops.conv_output_size(
            input_type.width, self.kernel_size[1], self.stride[1],
            self.padding[1], self.convolution_mode, self.dilation[1])
        return _inputs.convolutional(h, w, self.n_out)

    def param_order(self) -> tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> ParamTree:
        kh, kw = self.kernel_size
        params = {
            "W": init_weights(rng, (kh, kw, self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init or 0.0,
                                   dtype)
        return params

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        x = self.apply_dropout(x, train, rng)
        z = conv_ops.conv2d(x, params["W"], self.stride, self.padding,
                            self.convolution_mode, self.dilation)
        if self.has_bias:
            z = z + params["b"]
        return self._activate(z), state


@serde.register("subsampling")
@dataclasses.dataclass
class SubsamplingLayer(BaseLayerConfig):
    """Pooling layer (reference ``nn/conf/layers/SubsamplingLayer.java`` /
    ``nn/layers/convolution/subsampling/SubsamplingLayer.java``).
    ``pooling_type``: max | avg | sum | pnorm."""

    INPUT_KIND = "cnn"

    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2
    activation: str = "identity"

    def output_type(self, input_type: InputType) -> InputType:
        h = conv_ops.conv_output_size(
            input_type.height, self.kernel_size[0], self.stride[0],
            self.padding[0], self.convolution_mode)
        w = conv_ops.conv_output_size(
            input_type.width, self.kernel_size[1], self.stride[1],
            self.padding[1], self.convolution_mode)
        return _inputs.convolutional(h, w, input_type.channels)

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        x = self.apply_dropout(x, train, rng)
        out = conv_ops.pool2d(x, self.pooling_type, self.kernel_size,
                              self.stride, self.padding,
                              self.convolution_mode, self.pnorm)
        return out, state


@serde.register("zero_padding")
@dataclasses.dataclass
class ZeroPaddingLayer(BaseLayerConfig):
    """Explicit spatial zero padding (reference later adds
    ``ZeroPaddingLayer``; needed for exact ResNet-style stem parity)."""

    INPUT_KIND = "cnn"

    padding: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top,bottom,left,right
    activation: str = "identity"

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.padding
        return _inputs.convolutional(input_type.height + t + b,
                                     input_type.width + l + r,
                                     input_type.channels)

    def forward(self, params: ParamTree, state: StateTree, x: Array, *,
                train: bool, rng=None, mask=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state
