"""Activation functions.

TPU-native equivalent of ND4J's ``IActivation`` SPI (consumed by DL4J at
``nn/conf/NeuralNetConfiguration`` via the ``Activation`` enum — see reference
``deeplearning4j-nn`` imports surveyed in SURVEY.md §2.10).  In the reference,
each activation carries value + gradient; here every activation is a pure
``jnp`` function and the gradient comes for free from ``jax.grad``, so the
whole set stays fusable into a single XLA program.

Activations are referenced by lowercase string name in layer configs (the JSON
round-trip representation, mirroring DL4J's enum serialization).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


def identity(x: Array) -> Array:
    return x


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def relu(x: Array) -> Array:
    return jax.nn.relu(x)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def softmax(x: Array) -> Array:
    """Row-wise softmax over the last (feature) axis."""
    return jax.nn.softmax(x, axis=-1)


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return jax.nn.soft_sign(x)


def elu(x: Array, alpha: float = 1.0) -> Array:
    return jax.nn.elu(x, alpha=alpha)


def cube(x: Array) -> Array:
    return x * x * x


def rationaltanh(x: Array) -> Array:
    """Rational approximation of tanh (ND4J ``ActivationRationalTanh``).

    tanh(y) ~ sgn(y) * (1 - 1/(1 + |y| + y^2 + 1.41645 * y^4)) with y = 0.66667*x.
    """
    y = 0.66667 * x
    ay = jnp.abs(y)
    approx = 1.0 - 1.0 / (1.0 + ay + y * y + 1.41645 * (y ** 4))
    return 1.7159 * jnp.sign(y) * approx


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def selu(x: Array) -> Array:
    return jax.nn.selu(x)


def gelu(x: Array) -> Array:
    return jax.nn.gelu(x)


def swish(x: Array) -> Array:
    return jax.nn.silu(x)


_ACTIVATIONS: dict[str, Callable[[Array], Array]] = {
    "identity": identity,
    "linear": identity,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "relu": relu,
    "leakyrelu": leakyrelu,
    "softmax": softmax,
    "softplus": softplus,
    "softsign": softsign,
    "elu": elu,
    "cube": cube,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "hardtanh": hardtanh,
    "hardsigmoid": hardsigmoid,
    "selu": selu,
    "gelu": gelu,
    "swish": swish,
}


_BUILTIN_ACTIVATIONS = frozenset(_ACTIVATIONS)


def register(name: str, fn: Callable[[Array], Array],
             overwrite: bool = False) -> None:
    """Register a user-defined activation under a (case-insensitive)
    name so layer configs can refer to it like any built-in (reference
    custom-``IActivation`` extension point, ``CustomActivation`` in the
    reference test tier).  The function must be jax-traceable; its
    gradient comes from autodiff.

    Shadowing a BUILT-IN name silently changes every model in the
    process (including ``from_json`` restores), so it raises unless
    ``overwrite=True`` is explicit."""
    key = name.lower()
    if key in _BUILTIN_ACTIVATIONS and not overwrite:
        raise ValueError(
            f"'{key}' is a built-in activation; registering over it "
            "would change every model in this process — pass "
            "overwrite=True if that is really intended")
    _ACTIVATIONS[key] = fn


def get(name: str) -> Callable[[Array], Array]:
    """Resolve an activation by (case-insensitive) name.

    Mirrors DL4J's ``Activation.fromString`` lookup.
    """
    key = name.lower()
    if key not in _ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(_ACTIVATIONS)}"
        )
    return _ACTIVATIONS[key]


def available() -> list[str]:
    return sorted(_ACTIVATIONS)
