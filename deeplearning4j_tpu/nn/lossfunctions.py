"""Loss functions.

TPU-native equivalent of ND4J's ``ILossFunction`` SPI (``computeScore`` /
``computeGradient``).  The full set exercised by the reference's
``LossFunctionGradientCheck`` (see SURVEY.md §2.10) is provided: MCXENT, XENT,
NEGATIVELOGLIKELIHOOD, MSE, L1, L2, MAE, MAPE, MSLE, HINGE, SQUARED_HINGE,
KL_DIVERGENCE, POISSON, COSINE_PROXIMITY.

Each loss is a pure function ``loss(labels, preoutput, activation, mask) ->
per-example scores`` of shape ``(batch,)``; gradients come from ``jax.grad`` of
the mean, so there is no hand-written ``computeGradient`` twin.  Losses receive
the *pre-activation* output plus the output-layer activation name so that
numerically-fused forms (softmax+MCXENT -> log_softmax, sigmoid+XENT ->
log_sigmoid) compile to stable XLA code — the reference achieves the same
fusion inside ``LossMCXENT.computeGradient`` by special-casing softmax.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from . import activations

Array = jax.Array

_EPS = 1e-7


def _activate(preout: Array, activation: str) -> Array:
    return activations.get(activation)(preout)


def _reduce_features(per_element: Array) -> Array:
    """Sum per-element scores over all non-batch axes -> (batch,)."""
    axes = tuple(range(1, per_element.ndim))
    return jnp.sum(per_element, axis=axes) if axes else per_element


def mcxent(labels: Array, preout: Array, activation: str = "softmax",
           mask: Optional[Array] = None) -> Array:
    """Multi-class cross entropy: -sum(labels * log(p))."""
    if activation == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(_activate(preout, activation), _EPS, 1.0))
    per = -labels * logp
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def negativeloglikelihood(labels: Array, preout: Array,
                          activation: str = "softmax",
                          mask: Optional[Array] = None) -> Array:
    """Alias of MCXENT in the reference (``LossNegativeLogLikelihood``)."""
    return mcxent(labels, preout, activation, mask)


def xent(labels: Array, preout: Array, activation: str = "sigmoid",
         mask: Optional[Array] = None) -> Array:
    """Binary cross entropy: -[y log p + (1-y) log(1-p)] per output unit."""
    if activation == "sigmoid":
        # log(sigmoid(x)) = -softplus(-x); log(1-sigmoid(x)) = -softplus(x)
        logp = -jax.nn.softplus(-preout)
        log1mp = -jax.nn.softplus(preout)
    else:
        p = jnp.clip(_activate(preout, activation), _EPS, 1.0 - _EPS)
        logp, log1mp = jnp.log(p), jnp.log1p(-p)
    per = -(labels * logp + (1.0 - labels) * log1mp)
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def mse(labels: Array, preout: Array, activation: str = "identity",
        mask: Optional[Array] = None) -> Array:
    """Mean squared error per example: mean over output units of (y-yhat)^2.

    DL4J's ``LossMSE`` is ``LossL2 / nOut`` (average over the output dim).
    """
    return l2(labels, preout, activation, mask) / labels.shape[-1]


def l2(labels: Array, preout: Array, activation: str = "identity",
       mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.square(out - labels)
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def l1(labels: Array, preout: Array, activation: str = "identity",
       mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.abs(out - labels)
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def mae(labels: Array, preout: Array, activation: str = "identity",
        mask: Optional[Array] = None) -> Array:
    """DL4J ``LossMAE`` = LossL1 / nOut."""
    return l1(labels, preout, activation, mask) / labels.shape[-1]


def mape(labels: Array, preout: Array, activation: str = "identity",
         mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = 100.0 * jnp.abs((out - labels) / jnp.where(labels == 0, _EPS, labels))
    per = _apply_mask(per, mask)
    return _reduce_features(per) / labels.shape[-1]


def msle(labels: Array, preout: Array, activation: str = "identity",
         mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.square(jnp.log1p(out) - jnp.log1p(labels))
    per = _apply_mask(per, mask)
    return _reduce_features(per) / labels.shape[-1]


def hinge(labels: Array, preout: Array, activation: str = "identity",
          mask: Optional[Array] = None) -> Array:
    """Hinge loss; labels expected in {-1, +1} (reference ``LossHinge``)."""
    out = _activate(preout, activation)
    per = jnp.maximum(0.0, 1.0 - labels * out)
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def squared_hinge(labels: Array, preout: Array, activation: str = "identity",
                  mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.square(jnp.maximum(0.0, 1.0 - labels * out))
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def kl_divergence(labels: Array, preout: Array, activation: str = "softmax",
                  mask: Optional[Array] = None) -> Array:
    out = jnp.clip(_activate(preout, activation), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = labels * (jnp.log(lab) - jnp.log(out))
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def poisson(labels: Array, preout: Array, activation: str = "identity",
            mask: Optional[Array] = None) -> Array:
    out = jnp.clip(_activate(preout, activation), _EPS, None)
    per = out - labels * jnp.log(out)
    per = _apply_mask(per, mask)
    return _reduce_features(per)


def cosine_proximity(labels: Array, preout: Array, activation: str = "identity",
                     mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    if mask is not None:
        out = _apply_mask(out, mask)
        labels = _apply_mask(labels, mask)
    dot = jnp.sum(labels * out, axis=-1)
    norm = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
    sim = dot / jnp.clip(norm, _EPS, None)
    if sim.ndim > 1:  # time-distributed: sum over remaining axes
        sim = jnp.sum(sim, axis=tuple(range(1, sim.ndim)))
    return -sim


def _apply_mask(per: Array, mask: Optional[Array]) -> Array:
    """Apply a per-example or per-timestep mask by broadcasting.

    Reference masking semantics: score contributions of masked-out steps are
    zeroed (``MaskedReductionUtil`` / ``BaseOutputLayer.applyMask``).
    """
    if mask is None:
        return per
    m = mask
    while m.ndim < per.ndim:
        m = m[..., None]
    return per * m


_LOSSES: dict[str, Callable[..., Array]] = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "mse": mse,
    "l1": l1,
    "l2": l2,
    "mae": mae,
    "mape": mape,
    "msle": msle,
    "hinge": hinge,
    "squaredhinge": squared_hinge,
    "squared_hinge": squared_hinge,
    "kld": kl_divergence,
    "kl_divergence": kl_divergence,
    "poisson": poisson,
    "cosineproximity": cosine_proximity,
    "cosine_proximity": cosine_proximity,
}


def get(name: str) -> Callable[..., Array]:
    key = name.lower()
    if key not in _LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(_LOSSES)}")
    return _LOSSES[key]


def score(name: str, labels: Array, preout: Array, activation: str,
          mask: Optional[Array] = None, average: bool = True) -> Array:
    """Scalar score for a minibatch (mean over examples when ``average``).

    Matches ``BaseOutputLayer.computeScore`` -> ``ILossFunction.computeScore``.
    """
    per = get(name)(labels, preout, activation, mask)
    total = jnp.sum(per)
    if not average:
        return total
    if mask is not None and mask.ndim >= 2:
        # Time-series masking: average over present timesteps (reference
        # divides by number of unmasked elements via score array reduction).
        denom = jnp.clip(jnp.sum(mask), 1.0, None)
        return total / denom
    return total / per.shape[0]


def score_examples(name: str, labels: Array, preout: Array,
                   activation: str,
                   mask: Optional[Array] = None) -> Array:
    """Per-example scores, shape (batch,) — no averaging/summing over the
    batch (reference ``ILossFunction.computeScoreArray``, consumed by
    ``MultiLayerNetwork.scoreExamples:1757``).  Time-series losses sum
    over unmasked steps per example."""
    return get(name)(labels, preout, activation, mask)


def available() -> list[str]:
    return sorted(_LOSSES)
