"""Updaters, learning-rate policies, and gradient normalization.

TPU-native equivalent of the reference's ``nn/updater/LayerUpdater.java`` plus
ND4J's ``GradientUpdater`` implementations (Sgd/Adam/AdaDelta/Nesterovs/
RmsProp/AdaGrad/NoOp — reference ``LayerUpdater.java:240-270``).  The DL4J
order of operations is reproduced exactly (reference ``BaseUpdater.update``):

1. l1/l2 regularization added to the raw gradient per param
   (``LayerUpdater.java:104``: ``gradient += l2 * param + l1 * sign(param)``)
2. gradient normalization (``LayerUpdater.java:182-225``):
   RenormalizeL2PerLayer / RenormalizeL2PerParamType /
   ClipElementWiseAbsoluteValue / ClipL2PerLayer / ClipL2PerParamType
3. learning-rate policy applied for the current iteration
   (``LayerUpdater.java:135-154``)
4. per-param updater transform producing the step that the step function
   subtracts from the params in place.

Everything is a pure function of ``(grads, params, state, iteration)`` so the
whole update fuses into the jitted train step (one XLA program — the "single
HLO graph" north star).  Updater state is a pytree mirroring the params,
which flattens to the single contiguous ``updaterState.bin`` view for
serialization parity (reference ``BaseUpdater.setStateViewArray:34-48``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from .conf import serde as _serde

Array = jax.Array
ParamTree = Dict[str, Array]

_EPS_ADAGRAD = 1e-6
_EPS_ADAM = 1e-8
_EPS_ADADELTA = 1e-6
_EPS_RMSPROP = 1e-8


@_serde.register("updater_conf", custom=True)
@dataclasses.dataclass
class UpdaterConfig:
    """Serializable updater hyperparameters (subset of
    ``NeuralNetConfiguration`` fields that feed ``LayerUpdater``)."""

    updater: str = "sgd"              # sgd|adam|adadelta|nesterovs|rmsprop|adagrad|lars|none
    learning_rate: float = 0.1
    # lr policy (reference LearningRatePolicy enum)
    lr_policy: str = "none"           # none|exponential|inverse|step|poly|sigmoid|schedule
    lr_policy_decay_rate: float = 0.0
    lr_policy_power: float = 1.0
    lr_policy_steps: float = 1.0
    max_num_iterations: int = 1       # for poly
    lr_schedule: Optional[Dict[int, float]] = None  # iteration -> lr
    # momentum (nesterovs)
    momentum: float = 0.9
    momentum_schedule: Optional[Dict[int, float]] = None
    # adam
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    # rmsprop
    rms_decay: float = 0.95
    # adadelta
    rho: float = 0.95
    epsilon: float = 1e-6
    # lars (beyond the 2016 reference; the large-batch layer-wise
    # adaptive-rate technique of the MLPerf-on-TPU-pods literature)
    lars_trust_coefficient: float = 0.001
    lars_weight_decay: float = 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        # JSON object keys are strings; keep schedules serializable
        for k in ("lr_schedule", "momentum_schedule"):
            if d[k] is not None:
                d[k] = {str(i): v for i, v in d[k].items()}
        return d

    @staticmethod
    def from_dict(d: dict) -> "UpdaterConfig":
        d = dict(d)
        for k in ("lr_schedule", "momentum_schedule"):
            if d.get(k):
                d[k] = {int(i): v for i, v in d[k].items()}
        return UpdaterConfig(**d)


# ---------------------------------------------------------------------------
# Learning-rate policies (reference LayerUpdater.applyLrDecayPolicy)
# ---------------------------------------------------------------------------

def learning_rate_for(conf: UpdaterConfig, iteration: Array) -> Array:
    """Effective lr at ``iteration`` (traced scalar -> jit friendly)."""
    lr = jnp.asarray(conf.learning_rate, jnp.float32)
    it = jnp.asarray(iteration, jnp.float32)
    policy = conf.lr_policy.lower()
    if policy in ("none", ""):
        return lr
    decay = conf.lr_policy_decay_rate
    if policy == "exponential":
        return lr * jnp.power(decay, it)
    if policy == "inverse":
        return lr / jnp.power(1.0 + decay * it, conf.lr_policy_power)
    if policy == "step":
        return lr * jnp.power(decay, jnp.floor(it / conf.lr_policy_steps))
    if policy == "torchstep":
        # reference: every `steps` iterations multiply by decay
        return lr * jnp.power(decay, jnp.floor(it / conf.lr_policy_steps))
    if policy == "poly":
        frac = jnp.clip(it / max(conf.max_num_iterations, 1), 0.0, 1.0)
        return lr * jnp.power(1.0 - frac, conf.lr_policy_power)
    if policy == "sigmoid":
        return lr / (1.0 + jnp.exp(-decay * (it - conf.lr_policy_steps)))
    if policy == "schedule":
        # piecewise-constant: last schedule entry with key <= iteration wins
        sched = sorted((conf.lr_schedule or {}).items())
        out = lr
        for step, value in sched:
            out = jnp.where(it >= step, jnp.asarray(value, jnp.float32), out)
        return out
    raise ValueError(f"Unknown lr policy '{conf.lr_policy}'")


def momentum_for(conf: UpdaterConfig, iteration: Array) -> Array:
    mu = jnp.asarray(conf.momentum, jnp.float32)
    if conf.momentum_schedule:
        it = jnp.asarray(iteration, jnp.float32)
        for step, value in sorted(conf.momentum_schedule.items()):
            mu = jnp.where(it >= step, jnp.asarray(value, jnp.float32), mu)
    return mu


# ---------------------------------------------------------------------------
# Gradient normalization (reference LayerUpdater.java:182-225)
# ---------------------------------------------------------------------------

def normalize_gradients(grads: ParamTree, mode: Optional[str],
                        threshold: float = 1.0) -> ParamTree:
    """Apply a DL4J ``GradientNormalization`` mode over one layer's grads."""
    if not mode or mode.lower() in ("none",):
        return grads
    mode = mode.lower()
    leaves = jax.tree_util.tree_leaves(grads)
    if mode == "renormalizel2perlayer":
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = 1.0 / jnp.clip(norm, 1e-12, None)
        return jax.tree.map(lambda g: g * scale, grads)
    if mode == "renormalizel2perparamtype":
        return jax.tree.map(
            lambda g: g / jnp.clip(jnp.linalg.norm(g.ravel()), 1e-12, None),
            grads)
    if mode == "clipelementwiseabsolutevalue":
        return jax.tree.map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode == "clipl2perlayer":
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.where(norm > threshold, threshold / norm, 1.0)
        return jax.tree.map(lambda g: g * scale, grads)
    if mode == "clipl2perparamtype":
        def clip_one(g):
            norm = jnp.linalg.norm(g.ravel())
            return g * jnp.where(norm > threshold, threshold / norm, 1.0)
        return jax.tree.map(clip_one, grads)
    raise ValueError(f"Unknown gradient normalization '{mode}'")


# ---------------------------------------------------------------------------
# Per-param updaters (ND4J GradientUpdater equivalents)
# ---------------------------------------------------------------------------

MASTER_KEY = "_master"


def init_state(conf: UpdaterConfig, params: ParamTree,
               policy=None) -> ParamTree:
    """Zero-initialized updater state mirroring the param tree.

    Mirrors ND4J ``BaseUpdater`` state layout: adam keeps (m, v), nesterovs
    keeps velocity, adagrad keeps historical sum, etc.  State for stateless
    updaters is an empty tuple so the pytree stays jit-stable.

    With a mixed :class:`~..precision.PrecisionPolicy` the moments are
    stored in ``policy.updater_dtype`` (fp32 even for bf16 params) and an
    extra ``"_master"`` tree of fp32 master weights rides alongside —
    inside the updater state so it is donated/carried/sharded/serialized
    exactly like the moments (docs/PERFORMANCE.md).
    """
    name = conf.updater.lower()
    if policy is not None:
        sdtype = jnp.dtype(policy.updater_dtype)
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), sdtype), params)
    else:
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
    if name in ("sgd", "none", "noop"):
        state: ParamTree = {}
    elif name == "nesterovs":
        state = {"v": zeros()}
    elif name == "adagrad":
        state = {"h": zeros()}
    elif name == "rmsprop":
        state = {"cache": zeros()}
    elif name == "adam":
        state = {"m": zeros(), "v": zeros()}
    elif name == "adadelta":
        state = {"msg": zeros(), "msdx": zeros()}
    elif name == "lars":
        state = {"v": zeros()}
    else:
        raise ValueError(f"Unknown updater '{conf.updater}'")
    if policy is not None and policy.master_weights and any(
            jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)
            and jnp.asarray(p).dtype.itemsize < 4
            for p in jax.tree_util.tree_leaves(params)):
        state[MASTER_KEY] = jax.tree.map(
            lambda p: jnp.asarray(p, jnp.float32), params)
    return state


def compute_update(conf: UpdaterConfig, grads: ParamTree, state: ParamTree,
                   iteration: Array,
                   params: Optional[ParamTree] = None
                   ) -> tuple[ParamTree, ParamTree]:
    """Turn raw (regularized, normalized) grads into the step to subtract.

    Returns ``(updates, new_state)``; caller does ``params -= updates``
    (reference ``NegativeGradientStepFunction`` semantics).  ``params``
    is only consulted by updaters whose step depends on the weights
    themselves (lars); tree-structure must then match ``grads``.
    """
    name = conf.updater.lower()
    lr = learning_rate_for(conf, iteration)

    if name in ("none", "noop"):
        return grads, state
    if name == "sgd":
        return jax.tree.map(lambda g: lr * g, grads), state
    if name == "nesterovs":
        mu = momentum_for(conf, iteration)
        v_prev = state["v"]
        v_new = jax.tree.map(lambda v, g: mu * v - lr * g, v_prev, grads)
        # reference Nesterovs.getGradient: step = mu*vPrev - (1+mu)*vNew,
        # subtracted from params by the step function
        updates = jax.tree.map(
            lambda vp, vn: mu * vp - (1.0 + mu) * vn, v_prev, v_new)
        return updates, {"v": v_new}
    if name == "adagrad":
        h_new = jax.tree.map(lambda h, g: h + jnp.square(g),
                             state["h"], grads)
        updates = jax.tree.map(
            lambda g, h: lr * g / (jnp.sqrt(h) + _EPS_ADAGRAD), grads, h_new)
        return updates, {"h": h_new}
    if name == "rmsprop":
        d = conf.rms_decay
        cache = jax.tree.map(
            lambda c, g: d * c + (1.0 - d) * jnp.square(g),
            state["cache"], grads)
        updates = jax.tree.map(
            lambda g, c: lr * g / (jnp.sqrt(c) + _EPS_RMSPROP), grads, cache)
        return updates, {"cache": cache}
    if name == "adam":
        b1, b2 = conf.adam_mean_decay, conf.adam_var_decay
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                         state["m"], grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g),
                         state["v"], grads)
        # bias-corrected step (reference Adam.getGradient)
        alpha = lr * jnp.sqrt(1 - jnp.power(b2, t)) / (1 - jnp.power(b1, t))
        updates = jax.tree.map(
            lambda m_, v_: alpha * m_ / (jnp.sqrt(v_) + _EPS_ADAM), m, v)
        return updates, {"m": m, "v": v}
    if name == "adadelta":
        rho, eps = conf.rho, conf.epsilon or _EPS_ADADELTA
        msg = jax.tree.map(
            lambda a, g: rho * a + (1 - rho) * jnp.square(g),
            state["msg"], grads)
        updates = jax.tree.map(
            lambda g, a, d: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
            grads, msg, state["msdx"])
        msdx = jax.tree.map(
            lambda d, u: rho * d + (1 - rho) * jnp.square(u),
            state["msdx"], updates)
        return updates, {"msg": msg, "msdx": msdx}
    if name == "lars":
        # Layer-wise Adaptive Rate Scaling (You et al. 2017), the
        # large-batch recipe of the MLPerf TPU-pod scaling literature:
        # per-tensor trust ratio eta*||w|| / (||g|| + wd*||w||) scales the
        # momentum step so every layer moves proportionally to its
        # weight scale.
        if params is None:
            raise ValueError("lars needs the params tree (trust ratios "
                             "are weight-norm relative)")
        eta = conf.lars_trust_coefficient
        wd = conf.lars_weight_decay
        mu = momentum_for(conf, iteration)

        def one(w, g, v):
            w_norm = jnp.linalg.norm(w.ravel())
            g_norm = jnp.linalg.norm(g.ravel())
            trust = jnp.where(
                (w_norm > 0) & (g_norm > 0),
                eta * w_norm / (g_norm + wd * w_norm + 1e-12), 1.0)
            v_new = mu * v + lr * trust * (g + wd * w)
            return v_new

        v_new = jax.tree.map(one, params, grads, state["v"])
        return v_new, {"v": v_new}
    raise ValueError(f"Unknown updater '{conf.updater}'")


def updatable_params(layer, params: ParamTree) -> ParamTree:
    """Subset of a layer's params that go through the updater (excludes
    ``direct_update_params`` — those have no updater state, mirroring the
    reference's per-param ``Updater.NONE`` which is stateless)."""
    direct = set(layer.direct_update_params())
    if not direct:
        return params
    return {k: v for k, v in params.items() if k not in direct}


def apply_layer_updates(uconf: UpdaterConfig, layer, params: ParamTree,
                        state: ParamTree, grads: ParamTree,
                        iteration: Array) -> tuple[ParamTree, ParamTree]:
    """Full DL4J-order update for one layer's param tree: l1/l2 into grads,
    gradient normalization, per-param updater rule — with any
    ``layer.direct_update_params()`` routed around all of it and applied
    verbatim (``p -= g``; reference per-param ``Updater.NONE`` + lr 1.0,
    e.g. center-loss cL).

    When the updater state carries fp32 masters (mixed-precision policy,
    see :func:`init_state`), ALL updater math runs against the masters in
    fp32 and the storage-dtype params are re-derived by one cast at the
    end — the "cast-on-apply" step.  The bf16 params the forward pass
    reads are therefore always exactly ``master.astype(bf16)``.
    """
    if getattr(layer, "frozen", False):
        # feature-extractor layer: parameters (and updater state) fixed
        return dict(params), state
    masters = state.get(MASTER_KEY) if isinstance(state, dict) else None
    g = dict(grads)
    g_direct = {k: g.pop(k) for k in layer.direct_update_params() if k in g}
    if masters is not None:
        work = {k: masters[k] for k in g}
        g = {k: jnp.asarray(v, jnp.float32) for k, v in g.items()}
        mstate = {k: v for k, v in state.items() if k != MASTER_KEY}
    else:
        work = {k: params[k] for k in g}
        mstate = state
    g = regularize(g, work, layer.l1_by_param(), layer.l2_by_param())
    g = normalize_gradients(g, layer.gradient_normalization,
                            layer.gradient_normalization_threshold)
    updates, new_state = compute_update(
        uconf, g, mstate, iteration, params=work)
    new_params = dict(params)
    if masters is not None:
        new_masters = dict(masters)
        for k, u in updates.items():
            new_masters[k] = work[k] - u
            new_params[k] = new_masters[k].astype(params[k].dtype)
        new_state = dict(new_state)
        new_state[MASTER_KEY] = new_masters
    else:
        for k, u in updates.items():
            new_params[k] = params[k] - u
    for k, gd in g_direct.items():
        p = params[k]
        if (jnp.issubdtype(p.dtype, jnp.floating) and p.dtype.itemsize < 4):
            # sub-fp32 storage: accumulate the direct step in fp32 too
            new_params[k] = (p.astype(jnp.float32)
                             - jnp.asarray(gd, jnp.float32)).astype(p.dtype)
        else:
            new_params[k] = p - gd
    return new_params, new_state


def regularize(grads: ParamTree, params: ParamTree,
               l1_by_param: Dict[str, float],
               l2_by_param: Dict[str, float]) -> ParamTree:
    """Add l1/l2 penalties to raw grads, per param name.

    Reference ``LayerUpdater.postApply``: ``gradient += l2 * param`` and
    ``gradient += l1 * sign(param)`` — applied to weights but not biases
    unless bias regularization is configured (``getL1ByParam``).
    """
    out = {}
    for k, g in grads.items():
        l1 = l1_by_param.get(k, 0.0)
        l2 = l2_by_param.get(k, 0.0)
        if l2:
            g = g + l2 * params[k]
        if l1:
            g = g + l1 * jnp.sign(params[k])
        out[k] = g
    return out


def regularization_score(params: ParamTree, l1_by_param: Dict[str, float],
                         l2_by_param: Dict[str, float]) -> Array:
    """l1/l2 penalty term added to the loss score (reference
    ``BaseLayer.calcL2``/``calcL1``: 0.5*l2*||w||^2 + l1*||w||_1)."""
    total = jnp.asarray(0.0, jnp.float32)
    for k, p in params.items():
        l1 = l1_by_param.get(k, 0.0)
        l2 = l2_by_param.get(k, 0.0)
        if l2:
            total = total + 0.5 * l2 * jnp.sum(jnp.square(p))
        if l1:
            total = total + l1 * jnp.sum(jnp.abs(p))
    return total
