"""Weight initialization schemes and distributions.

TPU-native equivalent of the reference's ``nn/weights/WeightInit.java`` /
``WeightInitUtil.java`` and ``nn/conf/distribution/``.  Each scheme is a pure
function of a JAX PRNG key, so replica initialization under SPMD is
deterministic given the seed (the analogue of DL4J's shared ``Nd4j.getRandom``
seed when ``ParallelWrapper`` clones a model per device).

Shapes follow the JAX convention ``(fan_in, fan_out)`` for dense kernels and
``(H, W, C_in, C_out)`` (HWIO) for conv kernels; fan computation mirrors
``WeightInitUtil.initWeights``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .conf import serde as _serde

Array = jax.Array


@_serde.register("distribution", custom=True)
@dataclasses.dataclass
class Distribution:
    """Config-serializable sampling distribution (``nn/conf/distribution/``).

    kind: "normal" (mean/std), "uniform" (lower/upper), "binomial"
    (n_trials/prob_success).
    """

    kind: str = "normal"
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    n_trials: int = 1
    prob_success: float = 0.5

    def sample(self, rng: jax.Array, shape: Sequence[int],
               dtype=jnp.float32) -> Array:
        if _is_sub_fp32(dtype):
            # Sample in fp32 and round once: identical draws whatever the
            # storage dtype (the mixed-precision policy's bf16 params start
            # exactly at round(fp32 init), matching the fp32 masters).
            return self.sample(rng, shape, jnp.float32).astype(dtype)
        if self.kind == "normal" or self.kind == "gaussian":
            return self.mean + self.std * jax.random.normal(rng, shape, dtype)
        if self.kind == "uniform":
            return jax.random.uniform(rng, shape, dtype, self.lower, self.upper)
        if self.kind == "binomial":
            return jax.random.binomial(
                rng, self.n_trials, self.prob_success, shape).astype(dtype)
        raise ValueError(f"Unknown distribution kind '{self.kind}'")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "Distribution":
        return Distribution(**d)


def _is_sub_fp32(dtype) -> bool:
    d = jnp.dtype(dtype)
    return jnp.issubdtype(d, jnp.floating) and d.itemsize < 4


def _fans(shape: Sequence[int]) -> tuple[float, float]:
    """(fan_in, fan_out) for dense (I,O) or conv HWIO kernels.

    Mirrors ``WeightInitUtil`` fan computation: for conv, receptive-field size
    multiplies channel fans.
    """
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    if len(shape) >= 3:
        receptive = 1.0
        for s in shape[:-2]:
            receptive *= s
        return receptive * shape[-2], receptive * shape[-1]
    return float(shape[0]), float(shape[0])


def init_weights(rng: jax.Array, shape: Sequence[int], scheme: str = "xavier",
                 distribution: Optional[Distribution] = None,
                 dtype=jnp.float32) -> Array:
    """Initialize a weight tensor per a DL4J ``WeightInit`` scheme name.

    Supported (case-insensitive): zero, ones, xavier, xavier_uniform,
    xavier_fan_in, xavier_legacy, relu, relu_uniform, sigmoid_uniform,
    uniform, lecun_normal, lecun_uniform, normal, distribution, identity,
    var_scaling_* aliases.
    """
    if _is_sub_fp32(dtype):
        # Sample in fp32, round once to the storage dtype — bf16 params are
        # then exactly round(fp32 init), bit-matching the fp32 master copies
        # the mixed-precision updater carries (nn/precision.py).
        return init_weights(rng, shape, scheme, distribution,
                            jnp.float32).astype(dtype)
    scheme = scheme.lower()
    fan_in, fan_out = _fans(shape)
    shape = tuple(shape)

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("WeightInit 'distribution' requires a Distribution")
        return distribution.sample(rng, shape, dtype)
    if scheme == "xavier":
        # Gaussian with var = 2/(fanIn+fanOut) (WeightInitUtil XAVIER)
        std = jnp.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(rng, shape, dtype)
    if scheme == "xavier_uniform":
        a = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "xavier_fan_in":
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(fan_in)
    if scheme == "xavier_legacy":
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(
            1.0 / (fan_in + fan_out))
    if scheme in ("relu", "he_normal"):
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(2.0 / fan_in)
    if scheme in ("relu_uniform", "he_uniform"):
        a = jnp.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        a = 4.0 * jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "uniform":
        # DL4J legacy UNIFORM: U(-a, a) with a = 1/sqrt(fanIn)
        a = 1.0 / jnp.sqrt(fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "lecun_normal":
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(fan_in)
    if scheme == "lecun_uniform":
        a = jnp.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "normal":
        return jax.random.normal(rng, shape, dtype)
    raise ValueError(f"Unknown WeightInit scheme '{scheme}'")
