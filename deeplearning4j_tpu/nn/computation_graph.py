"""ComputationGraph: arbitrary-DAG network container.

TPU-native equivalent of the reference's ``nn/graph/ComputationGraph.java``
(2276 LoC): ``init():267``, topo-order forward loop at ``:1048-1049``,
``fit`` variants ``:650-810``, ``calcBackpropGradients:1175``,
``output:1099-1123``.

Where the reference walks materialized vertex objects per call, here one
traced pure function executes the DAG in the (build-time) topological order;
jit compiles forward + loss + backward + updater into a single XLA program.
Multi-input/multi-output batches are :class:`MultiDataSet` pytrees.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import precision as _precision
from . import updaters as _updaters
from .. import monitor as _monitor
from .conf.computation_graph import (ComputationGraphConfiguration,
                                     DuplicateToTimeSeriesVertex,
                                     LastTimeStepVertex, LayerVertex)
from ..datasets.dataset import DataSet, MultiDataSet, wire_of

Array = jax.Array


def _as_multi(data) -> MultiDataSet:
    if isinstance(data, MultiDataSet):
        return data
    if isinstance(data, DataSet):
        mds = MultiDataSet(
            features=[data.features], labels=[data.labels],
            features_masks=(None if data.features_mask is None
                            else [data.features_mask]),
            labels_masks=(None if data.labels_mask is None
                          else [data.labels_mask]))
        wire = wire_of(data)
        if wire is not None:
            # per-input wire list (ingest.multi_window_wire): a wrapped
            # DataSet wires its single input
            mds._wires = [wire]
        return mds
    raise TypeError(f"Expected DataSet/MultiDataSet, got {type(data)}")


class ComputationGraph:
    """DAG network with named vertices (reference ``ComputationGraph``)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self.vertices = conf.vertices
        self.params: Dict[str, Dict[str, Array]] = {}
        self.net_state: Dict[str, Dict[str, Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._init_done = False
        self._score = float("nan")
        self._rng_key: Optional[jax.Array] = None
        self._pretrain_step_cache: Dict[str, Any] = {}
        self._pretrain_done = False
        self._rnn_carries: Optional[Dict[str, Any]] = None
        self._rnn_carry_batch = -1
        self._decode_grow_cache: Dict[int, Any] = {}
        self._precision: Optional[_precision.PrecisionPolicy] = None

    def _pol(self) -> _precision.PrecisionPolicy:
        """The precision policy, resolved once per network instance
        (docs/PERFORMANCE.md)."""
        p = self._precision
        if p is None:
            p = self._precision = _precision.resolve_policy(self.conf.conf)
        return p

    @functools.cached_property
    def _solver(self):
        """Line-search solver when ``optimization_algo`` asks for one
        (reference ``Solver.java``); None selects the jitted SGD path."""
        from ..optimize.solvers import SGD, Solver
        algo = (self.conf.conf.optimization_algo or SGD).lower()
        if algo == SGD:
            return None
        if getattr(self.conf, "backprop_type", "standard") == "tbptt":
            raise ValueError(
                f"optimization_algo {algo!r} is incompatible with tBPTT; "
                "use stochastic_gradient_descent")
        return Solver(self, algo)

    # ------------------------------------------------------------------ init
    def init(self) -> "ComputationGraph":
        if self._init_done:
            return self
        pol = self._pol()
        _precision.publish(pol)
        dtype = jnp.dtype(pol.param_dtype)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        self._rng_key = key
        names = [n for n in self.topo
                 if isinstance(self.vertices[n], LayerVertex)]
        keys = jax.random.split(key, max(len(names), 1))
        for n, k in zip(names, keys):
            layer = self.vertices[n].layer
            self.params[n] = layer.init_params(k, dtype)
            self.net_state[n] = layer.init_state(dtype)
            self.updater_state[n] = _updaters.init_state(
                self._updater_conf(n),
                _updaters.updatable_params(layer, self.params[n]),
                policy=pol)
        self._init_done = True
        return self

    def _updater_conf(self, name: str):
        return (self.vertices[name].layer.updater
                or self.conf.conf.updater)

    def _layer_names(self) -> List[str]:
        return [n for n in self.topo
                if isinstance(self.vertices[n], LayerVertex)]

    def _output_layer_vertices(self) -> List[str]:
        return list(self.conf.network_outputs)

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, inputs: Sequence[Array], *,
                 train: bool, rng: Optional[jax.Array],
                 input_masks: Optional[Dict[str, Array]] = None,
                 preoutput_outputs: bool = False, carries=None):
        """Execute the DAG (reference forward loop ``:1048``).  Returns
        (activations dict, new_state dict, new_carries dict).

        ``carries`` is a dict of per-recurrent-vertex carry pytrees; when
        given, recurrent layer vertices run ``forward_seq`` with explicit
        state in/out (the graph analogues of ``rnnTimeStep:1789`` /
        ``rnnActivateUsingStoredState``)."""
        conf = self.conf
        acts: Dict[str, Array] = {}
        pol = self._pol()
        compute_dtype = jnp.dtype(pol.compute_dtype)
        for name, x in zip(conf.network_inputs, inputs):
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(compute_dtype)
            acts[name] = x
        if compute_dtype != jnp.dtype(pol.param_dtype):
            params = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        new_state = dict(net_state)
        layer_names = self._layer_names()
        keys = (jax.random.split(rng, max(len(layer_names), 1))
                if rng is not None else [None] * max(len(layer_names), 1))
        key_of = dict(zip(layer_names, keys))
        # Per-vertex propagated time masks (feedForwardMaskArray analogue):
        # input masks flow along the DAG for per-timestep layers.
        masks: Dict[str, Optional[Array]] = dict(input_masks or {})
        new_carries = dict(carries) if carries is not None else {}

        for name in self.topo:
            v = self.vertices[name]
            xs = [acts[i] for i in v.inputs]
            in_masks = [masks.get(i) for i in v.inputs]
            mask = next((m for m in in_masks if m is not None), None)
            if isinstance(v, LayerVertex):
                x = xs[0]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                layer = v.layer
                if preoutput_outputs and name in conf.network_outputs \
                        and hasattr(layer, "pre_output"):
                    if layer.dropout and train:
                        x = layer.apply_dropout(x, train, key_of[name])
                    out = layer.pre_output(params[name], x)
                elif (pol.downcasts_output and name in conf.network_outputs
                      and hasattr(layer, "pre_output")
                      and hasattr(layer, "_activate")):
                    # fp32 logits contract, head half: output-head logits
                    # are cast fp32 BEFORE softmax/sigmoid so serving
                    # probabilities are fp32-exact, not bf16-rounded.
                    # Applies even when the vertex is in ``carries``
                    # (rnn_step / decode_step): the only recurrent head
                    # with pre_output is RnnOutputLayer, whose carry is
                    # () — forward_seq would be the same math minus the
                    # fp32 cast, and skipping it must not change the
                    # carry.  Without this, N single-token decode calls
                    # drift from output() under mixed_bf16.
                    x = layer.apply_dropout(x, train, key_of[name])
                    out = layer._activate(
                        layer.pre_output(params[name], x)
                        .astype(jnp.float32))
                elif carries is not None and name in carries:
                    out, new_carries[name] = layer.forward_seq(
                        params[name], x, carries[name], train=train,
                        rng=key_of[name], mask=mask)
                else:
                    out, new_state[name] = layer.forward(
                        params[name], net_state[name], x, train=train,
                        rng=key_of[name], mask=mask)
                acts[name] = out
                masks[name] = mask
            elif isinstance(v, DuplicateToTimeSeriesVertex):
                ref = v.reference_input
                acts[name] = v.apply(*xs, masks=masks,
                                     timesteps=acts[ref].shape[1])
                masks[name] = masks.get(ref)
            elif isinstance(v, LastTimeStepVertex):
                acts[name] = v.apply(*xs, masks=masks)
                masks[name] = None
            else:
                acts[name] = v.apply(*xs, masks=masks)
                masks[name] = mask
        if pol.downcasts_output:
            # fp32 logits contract: loss/softmax/metrics accumulation and
            # serving all consume fp32 even under bf16 storage.
            for out in conf.network_outputs:
                acts[out] = acts[out].astype(jnp.float32)
        return acts, new_state, new_carries

    # ------------------------------------------------------------------ loss
    def _loss_fn(self, params, net_state, features, labels, features_masks,
                 labels_masks, rng, train: bool, carries=None,
                 per_example: bool = False):
        """``per_example`` accumulates the unreduced (batch,) score vector
        across output layers (reference ``computeScoreForExamples``)
        instead of the scalar batch loss."""
        input_masks = None
        if features_masks is not None:
            input_masks = {n: m for n, m in zip(self.conf.network_inputs,
                                                features_masks)
                           if m is not None}
        acts, new_state, new_carries = self._forward(
            params, net_state, features, train=train, rng=rng,
            input_masks=input_masks, preoutput_outputs=True,
            carries=carries)
        total = (jnp.zeros((features[0].shape[0],), jnp.float32)
                 if per_example else jnp.asarray(0.0, jnp.float32))
        for i, out_name in enumerate(self.conf.network_outputs):
            v = self.vertices[out_name]
            layer = v.layer
            lmask = None if labels_masks is None else labels_masks[i]
            if getattr(layer, "NEEDS_INPUT_FOR_SCORE", False):
                # Center-loss-style heads score against their input
                # activations; those are already in the DAG's acts.
                x = acts[v.inputs[0]]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                if layer.dropout and train and rng is not None:
                    x = layer.apply_dropout(
                        x, train, jax.random.fold_in(rng, 100_000 + i))
                if per_example:
                    total = total + layer.compute_score_examples_with_input(
                        params[out_name], labels[i], x, lmask)
                else:
                    total = total + layer.compute_score_with_input(
                        params[out_name], labels[i], x, lmask,
                        average=self.conf.conf.mini_batch)
                continue
            if not hasattr(layer, "compute_score"):
                raise ValueError(
                    f"Output vertex '{out_name}' is not an output layer")
            if per_example:
                total = total + layer.compute_score_examples(
                    labels[i], acts[out_name], lmask)
            else:
                total = total + layer.compute_score(
                    labels[i], acts[out_name], lmask,
                    average=self.conf.conf.mini_batch)
        return total, (new_state, new_carries)

    def _reg_score(self, params) -> Array:
        total = jnp.asarray(0.0, jnp.float32)
        for name in self._layer_names():
            layer = self.vertices[name].layer
            total = total + _updaters.regularization_score(
                params[name], layer.l1_by_param(), layer.l2_by_param())
        return total

    # ------------------------------------------------------------ train step
    def _apply_updates(self, params, updater_state, grads, iteration):
        new_params, new_ustate = {}, {}
        for name in self._layer_names():
            layer = self.vertices[name].layer
            g = grads[name]
            if g:
                new_params[name], new_ustate[name] = \
                    _updaters.apply_layer_updates(
                        self._updater_conf(name), layer, params[name],
                        updater_state[name], g, iteration)
            else:
                new_params[name] = params[name]
                new_ustate[name] = updater_state[name]
        return new_params, new_ustate

    def _build_train_step(self, health: bool):
        """Graph train step builder; ``health=True`` adds the packed
        per-layer stats vector + in-jit divergence guard
        (``monitor/health.py``), with per-vertex stats keyed in
        ``_layer_names()`` topo order."""
        from ..monitor import health as _health

        def step(params, updater_state, net_state, iteration, features,
                 labels, features_masks, labels_masks, base_rng):
            rng = jax.random.fold_in(base_rng, iteration)
            (data_loss, (new_state, _)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(
                    params, net_state, features, labels, features_masks,
                    labels_masks, rng, True)
            new_params, new_ustate = self._apply_updates(
                params, updater_state, grads, iteration)
            score = data_loss + self._reg_score(params)
            if not health:
                return new_params, new_ustate, new_state, score
            hvec, bad = _health.layer_stats(params, new_params, grads,
                                            data_loss,
                                            order=self._layer_names())
            new_params, new_ustate, new_state = _health.guard_select(
                bad, (new_params, new_ustate, new_state),
                (params, updater_state, net_state))
            return new_params, new_ustate, new_state, score, hvec

        return _monitor.watched_jit(step, name="cg.train_step",
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _train_step(self):
        """Plain 4-output graph step (external callers)."""
        return self._build_train_step(health=False)

    @functools.cached_property
    def _train_step_h(self):
        """Health-instrumented graph step; the ``fit`` paths use this."""
        return self._build_train_step(health=True)

    def _build_multi_train_step(self, health: bool):
        """S sequential graph train steps in ONE XLA program via
        ``lax.scan`` over per-input stacked (S, B, ...) batches — the graph
        twin of ``MultiLayerNetwork._multi_train_step``.  One dispatch runs
        the whole loop on-chip, so throughput is set by the MXU rather
        than by host→device dispatch latency (the reference's inner loop
        is host-driven, ``StochasticGradientDescent.java:50-72``).
        ``health=True`` stacks the packed per-step stats vector as a
        second scan output riding the same dispatch."""

        from . import ingest
        from ..monitor import health as _health

        def multi(params, updater_state, net_state, iteration, features,
                  labels, features_masks, labels_masks, base_rng,
                  wires=None):
            def body(carry, xs):
                p, u, s, it = carry
                f, l, fm, lm = xs
                if wires is not None:
                    f = [ingest.device_decode(fi, w)
                         for fi, w in zip(f, wires)]
                rng = jax.random.fold_in(base_rng, it)
                (data_loss, (new_s, _)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(
                        p, s, f, l, fm, lm, rng, True)
                new_p, new_u = self._apply_updates(p, u, grads, it)
                score = data_loss + self._reg_score(p)
                if not health:
                    return (new_p, new_u, new_s, it + 1), score
                hvec, bad = _health.layer_stats(
                    p, new_p, grads, data_loss,
                    order=self._layer_names())
                new_p, new_u, new_s = _health.guard_select(
                    bad, (new_p, new_u, new_s), (p, u, s))
                return (new_p, new_u, new_s, it + 1), (score, hvec)

            init = (params, updater_state, net_state,
                    jnp.asarray(iteration, jnp.int32))
            (params, updater_state, net_state, _), out = jax.lax.scan(
                body, init,
                (features, labels, features_masks, labels_masks))
            if not health:
                return params, updater_state, net_state, out
            scores, hstack = out
            return params, updater_state, net_state, scores, hstack

        return _monitor.watched_jit(multi, name="cg.multi_train_step",
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _multi_train_step(self):
        """Plain 4-output graph scan step (AOT benches, profilers)."""
        return self._build_multi_train_step(health=False)

    @functools.cached_property
    def _multi_train_step_h(self):
        """Health-instrumented graph scan step; ``fit`` paths use this."""
        return self._build_multi_train_step(health=True)

    def _build_gather_train_step(self, health: bool):
        """Device-cached-epoch graph train step, v2 (see
        ``MultiLayerNetwork._gather_train_step``): the epoch permutation
        is derived ON DEVICE from ``fold_in(shuffle_key, epoch)`` and up
        to ``fused`` epochs scan in one XLA program, each step gathering
        its minibatch from HBM-resident per-input dataset arrays —
        steady-state epochs move zero bytes host->device.  ``wires`` is
        the per-input ``(denom, mult, add)``/None tuple fusing the uint8
        wire decode into the gathered batch.  ``health=True`` adds the
        per-step stats stack as a second scan output, keeping the fused
        multi-epoch program at ONE dispatch per call.

        ``start``/``run`` (static) carve a sub-range of one epoch's
        steps for checkpoint-cadence chunking and mid-epoch resume —
        same bit-identity guarantee as the MLN gather step (identical
        per-step HLO; the carry chain crosses dispatches exactly)."""
        from . import ingest
        from ..monitor import health as _health

        def multi(params, updater_state, net_state, iteration, data_fs,
                  data_ls, base_rng, shuffle_key, first_epoch, fused,
                  steps, batch, shuffle, tail, wires, start=0, run=None):
            n = data_fs[0].shape[0]
            span = steps if run is None else run

            def epoch_rows(e):
                if shuffle:
                    perm = jax.random.permutation(
                        jax.random.fold_in(shuffle_key, e), n)
                else:
                    perm = jnp.arange(n)
                if tail:
                    return perm[steps * batch:].reshape(1, tail)
                return perm[start * batch:(start + span) * batch] \
                    .reshape(span, batch)

            rows = jax.vmap(epoch_rows)(first_epoch + jnp.arange(fused))
            rows = rows.reshape((-1,) + rows.shape[2:])

            def body(carry, idx_row):
                p, u, s, it = carry
                f = [ingest.device_decode(jnp.take(d, idx_row, axis=0), w)
                     for d, w in zip(data_fs, wires)]
                l = [jnp.take(d, idx_row, axis=0) for d in data_ls]
                rng = jax.random.fold_in(base_rng, it)
                (data_loss, (new_s, _)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(
                        p, s, f, l, None, None, rng, True)
                new_p, new_u = self._apply_updates(p, u, grads, it)
                score = data_loss + self._reg_score(p)
                if not health:
                    return (new_p, new_u, new_s, it + 1), score
                hvec, bad = _health.layer_stats(
                    p, new_p, grads, data_loss,
                    order=self._layer_names())
                new_p, new_u, new_s = _health.guard_select(
                    bad, (new_p, new_u, new_s), (p, u, s))
                return (new_p, new_u, new_s, it + 1), (score, hvec)

            init = (params, updater_state, net_state,
                    jnp.asarray(iteration, jnp.int32))
            (params, updater_state, net_state, _), out = jax.lax.scan(
                body, init, rows)
            if not health:
                return params, updater_state, net_state, out
            scores, hstack = out
            return params, updater_state, net_state, scores, hstack

        return _monitor.watched_jit(multi, name="cg.gather_train_step",
                                    static_argnums=(9, 10, 11, 12, 13,
                                                    15, 16),
                                    donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _gather_train_step(self):
        """Plain 4-output gather step (profilers, external callers)."""
        return self._build_gather_train_step(health=False)

    @functools.cached_property
    def _gather_train_step_h(self):
        """Health-instrumented gather step; ``_fit_device_cached`` uses
        this one."""
        return self._build_gather_train_step(health=True)

    def _fit_device_cached(self, source, epochs: int,
                           start_step: int = 0, ckpt=None):
        """Graph twin of ``MultiLayerNetwork._fit_device_cached``:
        ``source`` is a vetted ``ListDataSetIterator`` (single-input
        DataSets); the dataset lives on device across fits (uint8 wire
        form when the source carries one) and consecutive epochs fuse
        into single gather-scan dispatches via the shared
        ``ingest.run_device_cached_fit`` driver, which also owns the
        ``start_step`` resume offset and ``ckpt`` save cadence."""
        from . import ingest

        dev_f, dev_l, wire = ingest.device_cached_arrays(
            self, source._ds, source.get_preprocessor())
        data_fs, data_ls = (dev_f,), (dev_l,)
        shuffle_key = jax.random.fold_in(self._rng_key, 0xFFFFFFFF)
        steps = source._ds.num_examples() // source._batch

        def dispatch(first_epoch, fused, tail, start=0, run=None):
            (self.params, self.updater_state, self.net_state,
             scores, health) = self._gather_train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, data_fs, data_ls, self._rng_key,
                shuffle_key, first_epoch, fused, steps, source._batch,
                bool(source._shuffle), tail, (wire,), start,
                steps if run is None else run)
            _monitor.health.record_dispatch(self, health, self.iteration)
            return scores

        return ingest.run_device_cached_fit(self, source, epochs, dispatch,
                                            start_step=start_step,
                                            ckpt=ckpt)

    def _fit_windowed(self, iterator, epochs: int, window: int,
                      ckpt=None):
        """Graph twin of ``MultiLayerNetwork._fit_windowed``: stream
        (Multi)DataSets in multi-batch windows, host stacking and
        transfer overlapping the previous window's on-chip scan.
        ``ckpt`` saves at epoch boundaries (mid-epoch offsets are not
        replayable on this path)."""
        from . import ingest
        from ..resilience import faults as _faults

        replay = ingest.ScoreReplayer(self)

        def dispatch(buf):
            t0 = time.perf_counter()
            features, labels, fms, lms = ingest.stack_multi_window(buf)
            cdt = self._pol().compute_name
            u8s, wires = ingest.multi_window_wire(buf, len(features))
            features = [
                u8s[i] if u8s is not None and u8s[i] is not None
                else ingest.cast_for_transfer(f, cdt)
                for i, f in enumerate(features)]
            features = [jnp.asarray(f) for f in features]
            labels = [jnp.asarray(l) for l in labels]
            fms = (None if fms is None else [
                None if m is None else jnp.asarray(m) for m in fms])
            lms = (None if lms is None else [
                None if m is None else jnp.asarray(m) for m in lms])
            _monitor.gauge(
                "ingest_staged_bytes",
                "bytes uploaded to the device per staging event").set(
                sum(f.nbytes for f in features)
                + sum(l.nbytes for l in labels), path="window")
            t1 = time.perf_counter()
            _monitor.observe_phase("data", t1 - t0)
            (self.params, self.updater_state, self.net_state,
             scores, health) = self._multi_train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, fms, lms, self._rng_key,
                wires)
            _monitor.health.record_dispatch(self, health, self.iteration)
            replay.add(self.iteration, scores)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            _monitor.counter("train_iterations_total",
                             "supervised train iterations").inc(len(buf))
            self.iteration += len(buf)
            self.last_batch_size = buf[0].num_examples()

        it_mark = self.iteration
        for _ in range(epochs):
            with _monitor.span("fit/epoch", epoch=self.epoch,
                               path="window"):
                for listener in self.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(self)
                if hasattr(iterator, "reset"):
                    iterator.reset()
                buf, sig = [], None
                for ds in iterator:
                    mds = _as_multi(ds)
                    s = ingest.multi_window_signature(mds)
                    if buf and (s != sig or len(buf) >= window):
                        dispatch(buf)
                        buf = []
                    sig = s
                    buf.append(mds)
                if buf:
                    dispatch(buf)
                if self.listeners:
                    t2 = time.perf_counter()
                    replay.replay()
                    _monitor.observe_phase("listener",
                                           time.perf_counter() - t2)
                for listener in self.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(self)
                self.epoch += 1
            if ckpt is not None:
                ckpt.note_steps(self.iteration - it_mark)
                it_mark = self.iteration
                if ckpt.due(epoch_boundary=True):
                    replay.replay()
                    ckpt.save(self, step_in_epoch=0)
            _faults.maybe_die(self.iteration)
        if ckpt is not None:
            replay.replay()
            ckpt.save_if_progress(self, step_in_epoch=0)
            ckpt.flush()
        replay.finish()
        return self

    def fit_scan(self, batches) -> "np.ndarray":
        """Fit a list of same-shaped DataSet/MultiDataSet minibatches in one
        device dispatch (scan-based inner loop); returns per-step scores.
        Listeners fire once at the end.  Standard-backprop regime only —
        tBPTT / pretraining / num_iterations>1 / solver configs raise."""
        self.init()
        if getattr(self.conf, "backprop_type", "standard") == "tbptt":
            raise ValueError("fit_scan does not support tBPTT; use fit()")
        if self.conf.pretrain and not self._pretrain_done:
            raise ValueError("fit_scan does not run pretraining; call "
                             "pretrain() (or fit()) first")
        if self.conf.conf.num_iterations != 1:
            raise ValueError("fit_scan runs one update per batch; "
                             "num_iterations > 1 must use fit()")
        if self._solver is not None:
            raise ValueError("fit_scan supports the SGD path only; this "
                             "config uses a line-search solver")
        mbs = [_as_multi(b) for b in batches]

        def stack_inputs(get, count):
            return [jnp.stack([jnp.asarray(get(m)[i]) for m in mbs])
                    for i in range(count)]

        def stack_masks(get, count):
            if all(get(m) is None for m in mbs):
                return None
            # presence must agree per input INDEX across batches: batch 0
            # is not a template (masks are Sequence[Optional[array]])
            out = []
            for i in range(count):
                present = [get(m) is not None and get(m)[i] is not None
                           for m in mbs]
                if not any(present):
                    out.append(None)
                    continue
                if not all(present):
                    raise ValueError(
                        f"Mixed mask presence across batches for input "
                        f"{i} in fit_scan; provide masks on all batches "
                        f"or none")
                out.append(jnp.stack([jnp.asarray(get(m)[i]) for m in mbs]))
            return out

        n_in = len(mbs[0].features)
        n_out = len(mbs[0].labels)
        features = stack_inputs(lambda m: m.features, n_in)
        labels = stack_inputs(lambda m: m.labels, n_out)
        fmasks = stack_masks(lambda m: m.features_masks, n_in)
        lmasks = stack_masks(lambda m: m.labels_masks, n_out)
        t1 = time.perf_counter()
        (self.params, self.updater_state, self.net_state,
         scores, health) = self._multi_train_step_h(
            self.params, self.updater_state, self.net_state, self.iteration,
            features, labels, fmasks, lmasks, self._rng_key)
        _monitor.health.record_dispatch(self, health, self.iteration)
        _monitor.observe_phase("step", time.perf_counter() - t1)
        _monitor.counter("train_iterations_total",
                         "supervised train iterations").inc(len(mbs))
        self.iteration += len(mbs)
        self._score = scores[-1]
        self.last_batch_size = mbs[0].num_examples()
        self._fire_listeners()
        return np.asarray(scores)

    @functools.cached_property
    def _tbptt_step(self):
        """Truncated-BPTT window step for the graph (reference graph tBPTT
        path in ``ComputationGraph.doTruncatedBPTT:1936``): one
        fwd+bwd+update over a time window with recurrent carries in from
        the previous window, gradients stopped at the window boundary."""

        def step(params, updater_state, net_state, carries, iteration,
                 features, labels, features_masks, labels_masks, base_rng):
            rng = jax.random.fold_in(base_rng, iteration)
            carries = jax.lax.stop_gradient(carries)

            def loss(p, ns, f, l, fm, lm, r):
                return self._loss_fn(p, ns, f, l, fm, lm, r, True,
                                     carries=carries)

            (data_loss, (new_state, new_carries)), grads = \
                jax.value_and_grad(loss, has_aux=True)(
                    params, net_state, features, labels, features_masks,
                    labels_masks, rng)
            new_params, new_ustate = self._apply_updates(
                params, updater_state, grads, iteration)
            score = data_loss + self._reg_score(params)
            return (new_params, new_ustate, new_state, new_carries, score)

        return _monitor.watched_jit(step, name="cg.tbptt_step",
                                    donate_argnums=(0, 1, 2, 3))

    @functools.cached_property
    def _advance_fn(self):
        """Carry-advance without gradients or updates: used to roll state
        over the leading ``fwd - back`` steps of a window when
        ``tbptt_back_length < tbptt_fwd_length`` (the reference truncates
        the LSTM backward iteration to backLength steps from the window
        end, ``LSTMHelpers`` truncated loop), and by ``rnn_time_step``."""

        def run(params, net_state, carries, features, features_masks):
            input_masks = None
            if features_masks is not None:
                input_masks = {
                    n: m for n, m in zip(self.conf.network_inputs,
                                         features_masks) if m is not None}
            acts, _, new_carries = self._forward(
                params, net_state, features, train=False, rng=None,
                input_masks=input_masks, carries=carries)
            return [acts[o] for o in self.conf.network_outputs], new_carries

        return _monitor.watched_jit(run, name="cg.advance")

    @functools.cached_property
    def _decode_step_fn(self):
        """Autoregressive decode step: the ``cg.advance`` contract over
        generalized state trees (RNN carries AND KV-cache rings), under
        its own jit name so the serving sanitizer can budget
        ``serving.decode_step`` separately (one dispatch per token)."""
        def run(params, net_state, carries, features):
            acts, _, new_carries = self._forward(
                params, net_state, features, train=False, rng=None,
                carries=carries)
            return ([acts[o] for o in self.conf.network_outputs],
                    new_carries)
        return _monitor.watched_jit(run, name="cg.decode_step")

    def _decode_grow_fn(self, cache_len: int):
        """Jitted state-tree growth to a larger KV ring capacity — ONE
        dispatch per (shape, target) pair (the serving bucket hop)."""
        from .layers.recurrent import BaseRecurrentLayer
        if cache_len not in self._decode_grow_cache:
            def grow(carries):
                out = {}
                for n, c in carries.items():
                    layer = self.vertices[n].layer
                    if (isinstance(layer, BaseRecurrentLayer)
                            and getattr(layer, "HAS_KV_RING", False)):
                        out[n] = layer.grow_carry(c, cache_len)
                    else:
                        out[n] = c
                return out
            self._decode_grow_cache[cache_len] = _monitor.watched_jit(
                grow, name="cg.decode_grow")
        return self._decode_grow_cache[cache_len]

    @functools.cached_property
    def _output_fn(self):
        def run(params, net_state, features, features_masks):
            input_masks = None
            if features_masks is not None:
                input_masks = {
                    n: m for n, m in zip(self.conf.network_inputs,
                                         features_masks) if m is not None}
            acts, _, _ = self._forward(params, net_state, features,
                                       train=False, rng=None,
                                       input_masks=input_masks)
            return [acts[o] for o in self.conf.network_outputs]
        return _monitor.watched_jit(run, name="cg.output")

    @functools.cached_property
    def _eval_argmax_fn(self):
        """Single-output inference forward + argmax in one program:
        evaluation transfers int32 class indices, not logits."""
        def run(params, net_state, features, features_masks):
            input_masks = None
            if features_masks is not None:
                input_masks = {
                    n: m for n, m in zip(self.conf.network_inputs,
                                         features_masks) if m is not None}
            acts, _, _ = self._forward(params, net_state, features,
                                       train=False, rng=None,
                                       input_masks=input_masks)
            out = acts[self.conf.network_outputs[0]]
            return jnp.argmax(out, axis=-1).astype(jnp.int32)
        return _monitor.watched_jit(run, name="cg.eval_argmax")

    @functools.cached_property
    def _score_fn(self):
        def score(params, net_state, features, labels, features_masks,
                  labels_masks):
            data_loss, _ = self._loss_fn(
                params, net_state, features, labels, features_masks,
                labels_masks, None, False)
            return data_loss + self._reg_score(params)
        return _monitor.watched_jit(score, name="cg.score")

    @functools.cached_property
    def _score_examples_fn(self):
        @functools.partial(_monitor.watched_jit,
                           name="cg.score_examples", static_argnums=(6,))
        def run(params, net_state, features, labels, features_masks,
                labels_masks, add_reg):
            per, _ = self._loss_fn(params, net_state, features, labels,
                                   features_masks, labels_masks, None,
                                   False, per_example=True)
            if add_reg:
                per = per + self._reg_score(params)
            return per
        return run

    def score_examples(self, data,
                       add_regularization_terms: bool = True) -> np.ndarray:
        """Per-example loss vector, summed over output layers, no batch
        averaging (reference ``ComputationGraph.scoreExamples:1486-1520``).
        ``data`` is a DataSet/MultiDataSet or an iterator of either,
        streamed batch by batch."""
        self.init()
        batches = ([data] if isinstance(data, (DataSet, MultiDataSet))
                   else iter(data))
        out = []
        for b in batches:
            mds = _as_multi(b)
            feats = tuple(jnp.asarray(f) for f in mds.features)
            labels = tuple(jnp.asarray(l) for l in mds.labels)
            fmasks = (None if mds.features_masks is None else tuple(
                None if m is None else jnp.asarray(m)
                for m in mds.features_masks))
            lmasks = (None if mds.labels_masks is None else tuple(
                None if m is None else jnp.asarray(m)
                for m in mds.labels_masks))
            out.append(np.asarray(self._score_examples_fn(
                self.params, self.net_state, feats, labels, fmasks,
                lmasks, bool(add_regularization_terms))))
        if not out:
            return np.zeros((0,), np.float32)
        return np.concatenate(out)

    # -------------------------------------------------------------- pretrain
    def _pretrain_step(self, name: str):
        """Jitted unsupervised step for one layer vertex (reference
        ``ComputationGraph.pretrain:510-555``)."""
        if name not in self._pretrain_step_cache:
            v = self.vertices[name]
            layer = v.layer
            uconf = self._updater_conf(name)

            def step(params, ustate, net_state, iteration, features,
                     base_rng):
                rng = jax.random.fold_in(base_rng, iteration)
                acts, _, _ = self._forward(params, net_state, features,
                                           train=False, rng=None)
                x = acts[v.inputs[0]]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                x = jax.lax.stop_gradient(x)
                score, grads = layer.pretrain_grads(params[name], x, rng)
                grads = _updaters.regularize(grads, params[name],
                                             layer.l1_by_param(),
                                             layer.l2_by_param())
                grads = _updaters.normalize_gradients(
                    grads, layer.gradient_normalization,
                    layer.gradient_normalization_threshold)
                updates, new_ustate = _updaters.compute_update(
                    uconf, grads, ustate, iteration,
                    params={k: params[name][k] for k in grads})
                new_p = jax.tree.map(lambda p, u: p - u, params[name],
                                     updates)
                score = score + _updaters.regularization_score(
                    params[name], layer.l1_by_param(), layer.l2_by_param())
                return new_p, new_ustate, score

            self._pretrain_step_cache[name] = _monitor.watched_jit(
                step, name=f"cg.pretrain_step_{name}", donate_argnums=(1,))
        return self._pretrain_step_cache[name]

    def pretrain(self, data, epochs: int = 1) -> "ComputationGraph":
        """Greedy layer-wise pretraining of every pretrainable layer vertex
        in topological order (reference ``ComputationGraph.pretrain:510``)."""
        self.init()
        if not isinstance(data, (DataSet, MultiDataSet)) \
                and not hasattr(data, "reset"):
            data = list(data)  # one-shot iterable: each layer needs a pass
        for name in self._layer_names():
            if getattr(self.vertices[name].layer, "IS_PRETRAINABLE", False):
                self.pretrain_layer(name, data, epochs)
        # fit() must not re-run pretraining (and the flag serializes, so a
        # restored model doesn't re-pretrain over fine-tuned weights)
        self._pretrain_done = True
        return self

    def pretrain_layer(self, name: str, data,
                       epochs: int = 1) -> "ComputationGraph":
        self.init()
        if not getattr(self.vertices[name].layer, "IS_PRETRAINABLE", False):
            return self
        if getattr(self.vertices[name].layer, "frozen", False):
            return self          # frozen extractor: pretraining is a no-op
        step = self._pretrain_step(name)
        batches = ([data] if isinstance(data, (DataSet, MultiDataSet))
                   else data)
        for _ in range(epochs):
            if hasattr(batches, "reset"):
                batches.reset()
            for ds in batches:
                mds = _as_multi(ds)
                features = tuple(jnp.asarray(f) for f in mds.features)
                (self.params[name], self.updater_state[name],
                 score) = step(self.params, self.updater_state[name],
                               self.net_state, self.iteration, features,
                               self._rng_key)
                self._score = score
                self.iteration += 1
                self._fire_listeners()
        return self

    # ------------------------------------------------------------------- fit
    def _resolve_resilience(self, checkpoint, resume_from, epochs):
        """(manager, start_step, remaining_epochs) for ``fit``'s
        ``checkpoint=``/``resume_from=`` hooks; the no-resilience call
        stays import-free."""
        if checkpoint is None and resume_from is None:
            return None, 0, epochs
        from ..resilience.checkpoint import resolve_fit_resilience
        return resolve_fit_resilience(self, checkpoint, resume_from,
                                      epochs)

    def _warn_partial_epoch_restart(self, start_step: int,
                                    path: str) -> None:
        """Mid-epoch resume offsets are only replayable on the
        epoch-cache path (the shuffle lives in the on-device threefry
        stream); other paths restart the interrupted epoch."""
        if start_step:
            import warnings
            warnings.warn(
                f"resume_from checkpoint was taken mid-epoch "
                f"(step_in_epoch={start_step}) but the {path} path "
                "cannot seek into an epoch; restarting the epoch from "
                "step 0 (at-least-once semantics)", RuntimeWarning)

    def fit(self, data, labels=None, epochs: int = 1,
            ingest: str = "auto", window: int = 16, checkpoint=None,
            resume_from=None) -> "ComputationGraph":
        """Train (reference ``fit`` variants ``:650-810``).  ``data`` may be
        a (Multi)DataSet, an iterator of them, or features with ``labels``.

        With ``conf.pretrain=True`` the first call pretrains every
        pretrainable layer vertex; ``conf.backprop=False`` skips the
        supervised phase (reference ``fit:740`` + ``pretrain:510``).

        ``ingest``/``window``: iterator data-path selection, same
        semantics as :meth:`MultiLayerNetwork.fit` — ``"auto"`` picks
        the device-resident epoch cache when the dataset fits HBM, else
        windowed double-buffered staging; listeners fire by exact
        per-step score replay.

        ``checkpoint=``/``resume_from=``: preemption-safe checkpointing
        and resume, same semantics as :meth:`MultiLayerNetwork.fit`
        (``epochs`` is the TOTAL epoch target when resuming; see
        ``docs/RESILIENCE.md``)."""
        if ingest not in ("auto", "cache", "window", "batch"):
            raise ValueError(
                f"unknown ingest mode {ingest!r}; expected 'auto', "
                "'cache', 'window', or 'batch'")
        self.init()
        ckpt, start_step, epochs = self._resolve_resilience(
            checkpoint, resume_from, epochs)
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
            iterator = None
        else:
            iterator = data
            batches = None
        from ..optimize.listeners.listeners import finalize_listeners
        try:
            if self.conf.pretrain and not self._pretrain_done:
                if batches is None and not hasattr(iterator, "reset"):
                    # One-shot iterable: materialize so layer-wise
                    # pretraining and the supervised phase each see the
                    # full data.
                    batches = list(iterator)
                    iterator = None
                self.pretrain(batches if batches is not None else iterator)
                self._pretrain_done = True
            if not getattr(self.conf, "backprop", True):
                return self
            if (iterator is not None and ingest != "batch"
                    and self._solver is None
                    and getattr(self.conf, "backprop_type",
                                "standard") != "tbptt"
                    and self.conf.conf.num_iterations == 1):
                from . import ingest as ingest_mod
                if ingest in ("auto", "cache"):
                    source = ingest_mod.cacheable_source(iterator)
                    if source is not None:
                        return self._fit_device_cached(
                            source, epochs, start_step=start_step,
                            ckpt=ckpt)
                    if ingest == "cache":
                        raise ValueError(
                            "ingest='cache' but the iterator is not "
                            "device-cacheable (see nn/ingest.py "
                            "eligibility)")
                self._warn_partial_epoch_restart(start_step, "window")
                return self._fit_windowed(iterator, epochs, window,
                                          ckpt=ckpt)
            self._warn_partial_epoch_restart(start_step, "batch")
            from ..resilience import faults as _faults
            it_mark = self.iteration
            for _ in range(epochs):
                with _monitor.span("fit/epoch", epoch=self.epoch,
                                   path="batch"):
                    for listener in self.listeners:
                        if hasattr(listener, "on_epoch_start"):
                            listener.on_epoch_start(self)
                    it = batches if batches is not None else iterator
                    if hasattr(it, "reset"):
                        it.reset()
                    for ds in it:
                        self._fit_batch(_as_multi(ds))
                    for listener in self.listeners:
                        if hasattr(listener, "on_epoch_end"):
                            listener.on_epoch_end(self)
                    self.epoch += 1
                if ckpt is not None:
                    ckpt.note_steps(self.iteration - it_mark)
                    it_mark = self.iteration
                    if ckpt.due(epoch_boundary=True):
                        ckpt.save(self, step_in_epoch=0)
                _faults.maybe_die(self.iteration)
            if ckpt is not None:
                ckpt.save_if_progress(self, step_in_epoch=0)
                ckpt.flush()
            return self
        finally:
            finalize_listeners(self.listeners)

    def _fire_listeners(self) -> None:
        """Per-iteration listener callbacks, timed as the ``listener``
        phase (they run on the host and may force a device score fetch)."""
        if not self.listeners:
            return
        t0 = time.perf_counter()
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)
        _monitor.observe_phase("listener", time.perf_counter() - t0)

    def _fit_batch(self, mds: MultiDataSet) -> None:
        self.last_batch_size = mds.num_examples()
        t0 = time.perf_counter()
        features = tuple(jnp.asarray(f) for f in mds.features)
        labels = tuple(jnp.asarray(l) for l in mds.labels)
        fmasks = (None if mds.features_masks is None else tuple(
            None if m is None else jnp.asarray(m)
            for m in mds.features_masks))
        lmasks = (None if mds.labels_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in mds.labels_masks))
        _monitor.observe_phase("data", time.perf_counter() - t0)
        iters = _monitor.counter("train_iterations_total",
                                 "supervised train iterations")
        if self._solver is not None:
            for _ in range(self.conf.conf.num_iterations):
                t1 = time.perf_counter()
                self._score = self._solver.optimize(features, labels,
                                                    fmasks, lmasks)
                _monitor.observe_phase("step", time.perf_counter() - t1)
                self.iteration += 1
                iters.inc()
                self._fire_listeners()
            return
        if getattr(self.conf, "backprop_type", "standard") == "tbptt":
            for _ in range(self.conf.conf.num_iterations):
                self._fit_tbptt(features, labels, fmasks, lmasks)
            return
        for _ in range(self.conf.conf.num_iterations):
            t1 = time.perf_counter()
            (self.params, self.updater_state, self.net_state,
             score, health) = self._train_step_h(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, fmasks, lmasks,
                self._rng_key)
            _monitor.health.record_dispatch(self, health, self.iteration)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            self._score = score
            self.iteration += 1
            iters.inc()
            self._fire_listeners()

    # ---------------------------------------------------------------- tBPTT
    def _fit_tbptt(self, features, labels, fmasks, lmasks) -> None:
        """Graph truncated BPTT (reference
        ``ComputationGraph.doTruncatedBPTT:1936`` +
        ``rnnUpdateStateWithTBPTTState``): slice every 3-D input/label along
        time into ``tbptt_fwd_length`` windows, carrying recurrent vertex
        state across windows.  When ``tbptt_back_length <
        tbptt_fwd_length``, the leading ``fwd - back`` steps of each window
        advance state without gradients (the reference instead truncates
        the LSTM backward iteration at backLength steps from the window
        end — recurrent truncation is identical; feedforward-parameter
        gradients from those leading steps are not accumulated here)."""
        self._require_carry_support("truncated BPTT")
        if any(l.ndim > 3 for l in labels):
            raise ValueError(
                "Graph tBPTT supports (batch, time, features) labels only; "
                "got a label of rank "
                f"{max(l.ndim for l in labels)} (4-D per-timestep targets "
                "are not time-sliceable here)")
        seq = [l for l in labels if l.ndim == 3]
        if not seq:
            raise ValueError(
                "Truncated BPTT needs per-timestep labels (batch, time, "
                "...); use standard backprop for sequence-level labels.")
        T = seq[0].shape[1]
        window = self.conf.tbptt_fwd_length
        back = self.conf.tbptt_back_length or window
        if back > window:
            raise ValueError(
                f"tbptt_back_length ({back}) > tbptt_fwd_length "
                f"({window}) is not meaningful")
        carries = self._init_carries(features[0].shape[0])

        def _t(arrs, sl, masks=False):
            # time axis is 1 for 3-D (batch, time, feat) arrays and for
            # 2-D (batch, time) masks; 2-D labels/static inputs and 4-D
            # image inputs pass through whole (an image whose height
            # happens to equal T must not be cropped)
            def want(a):
                return (a.ndim == 3 or (masks and a.ndim == 2)) \
                    and a.shape[1] == T
            return tuple(None if a is None
                         else (a[:, sl] if want(a) else a) for a in arrs)

        scores = []
        for start in range(0, T, window):
            stop = min(start + window, T)
            adv = max(0, (stop - start) - back)
            if adv:
                asl = slice(start, start + adv)
                _, carries = self._advance_fn(
                    self.params, self.net_state, carries,
                    _t(features, asl),
                    None if fmasks is None else _t(fmasks, asl,
                                                   masks=True))
                start = start + adv
            sl = slice(start, stop)
            t1 = time.perf_counter()
            (self.params, self.updater_state, self.net_state, carries,
             score) = self._tbptt_step(
                self.params, self.updater_state, self.net_state, carries,
                self.iteration, _t(features, sl), _t(labels, sl),
                None if fmasks is None else _t(fmasks, sl, masks=True),
                None if lmasks is None else _t(lmasks, sl, masks=True),
                self._rng_key)
            _monitor.observe_phase("step", time.perf_counter() - t1)
            scores.append(score)
            self.iteration += 1
            _monitor.counter("train_iterations_total",
                             "supervised train iterations").inc()
            self._fire_listeners()
        self._score = scores[-1] if scores else self._score

    def _recurrent_vertex_names(self) -> List[str]:
        from .layers.recurrent import BaseRecurrentLayer
        return [n for n in self._layer_names()
                if isinstance(self.vertices[n].layer, BaseRecurrentLayer)]

    def _require_carry_support(self, what: str) -> None:
        """Bidirectional layers cannot carry state across time chunks
        (reference graph rnnTimeStep throws for them too)."""
        from .layers.recurrent import BaseRecurrentLayer
        for n in self._layer_names():
            layer = self.vertices[n].layer
            if (isinstance(layer, BaseRecurrentLayer)
                    and not layer.SUPPORTS_CARRY):
                raise ValueError(
                    f"Vertex '{n}' ({type(layer).__name__}) does not "
                    f"support {what}: its backward pass needs the full "
                    "sequence")

    def _init_carries(self, batch: int,
                      cache_len: Optional[int] = None) -> Dict[str, Any]:
        """Zero carries per recurrent vertex; ``cache_len`` overrides
        KV-ring capacities (the serving (batch, cache_len) bucket
        ladder) and is ignored by RNN carries."""
        dtype = jnp.dtype(self._pol().compute_dtype)
        out = {}
        for n in self._recurrent_vertex_names():
            layer = self.vertices[n].layer
            if cache_len is not None and getattr(layer, "HAS_KV_RING",
                                                 False):
                out[n] = layer.init_carry(batch, dtype,
                                          cache_len=cache_len)
            else:
                out[n] = layer.init_carry(batch, dtype)
        return out

    def has_kv_ring(self) -> bool:
        """Whether any vertex carries a KV-cache ring (selects the
        ``serving.decode_step`` sanitizer scenario)."""
        return any(getattr(self.vertices[n].layer, "HAS_KV_RING", False)
                   for n in self._layer_names())

    def max_cache_len(self) -> int:
        """Largest KV-ring capacity across vertices (0 without rings)."""
        return max((int(self.vertices[n].layer.cache_len)
                    for n in self._layer_names()
                    if getattr(self.vertices[n].layer, "HAS_KV_RING",
                               False)), default=0)

    # --------------------------------------------- rnn streaming state API
    def rnn_time_step(self, *features):
        """Stateful streaming inference (reference
        ``ComputationGraph.rnnTimeStep:1789``): feed one or more timesteps
        per input, carrying every recurrent vertex's hidden state between
        calls.  2-D inputs (batch, features) are single timesteps and the
        matching outputs come back 2-D; 3-D inputs return full
        (batch, time, n_out) sequences."""
        self.init()
        self._require_carry_support("rnn_time_step")
        xs = [jnp.asarray(f) for f in features]
        squeeze = xs[0].ndim == 2
        xs = [x[:, None, :] if x.ndim == 2 else x for x in xs]
        batch = xs[0].shape[0]
        if self._rnn_carries is None:
            self._rnn_carries = self._init_carries(batch)
            self._rnn_carry_batch = batch
        elif self._rnn_carry_batch != batch:
            raise ValueError(
                f"rnn_time_step batch size {batch} != stored state batch "
                f"size {self._rnn_carry_batch}; call "
                "rnn_clear_previous_state() between unrelated sequences")
        outs, self._rnn_carries = self._advance_fn(
            self.params, self.net_state, self._rnn_carries, tuple(xs),
            None)
        outs = [np.asarray(o) for o in outs]
        if squeeze:
            outs = [o[:, -1] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_stateless_step(self, carries, *features, params=None,
                           net_state=None):
        """Explicit-carry streaming step (re-entrant twin of
        :meth:`rnn_time_step`): advance the given carry dict by the input
        timesteps and return ``(outs, new_carries)`` without touching the
        graph's own hidden-state slot — the primitive behind
        ``serving.SessionCache``'s N-concurrent-sessions-per-model.
        ``carries=None`` starts from zero state; inputs must be 3-D
        ``(batch, time, n_in)``; ``outs`` is always a list (one per
        graph output) and each call is ONE dispatch of the jitted
        ``cg.advance`` program.  ``params``/``net_state`` override the
        weight operands (same shapes/dtypes → jit cache hit, no
        recompile) so a serving session can stay pinned to the weight
        version its carries came from across a hot-swap
        (docs/DEPLOY.md)."""
        self.init()
        self._require_carry_support("rnn_stateless_step")
        xs = tuple(jnp.asarray(f) for f in features)
        for x in xs:
            if x.ndim != 3:
                raise ValueError(
                    f"rnn_stateless_step expects (batch, time, features) "
                    f"inputs, got shape {x.shape}")
        if carries is None:
            carries = self._init_carries(int(xs[0].shape[0]))
        return self._advance_fn(
            self.params if params is None else params,
            self.net_state if net_state is None else net_state,
            carries, xs, None)

    def decode_step(self, carries, *features, params=None,
                    net_state=None):
        """Autoregressive decode step: :meth:`rnn_stateless_step`
        generalized to arbitrary per-session state trees (RNN carries
        and KV-cache rings) under the ``cg.decode_step`` jit name.
        Returns ``(outs, new_carries)`` with ``outs`` a list (one per
        graph output); N single-token calls BIT-match one full-sequence
        ``output()`` with the fp32-logits contract intact.  Inputs must
        be 3-D; ``carries=None`` starts a fresh state tree;
        ``params``/``net_state`` pin a weight version (same shapes →
        jit cache hit)."""
        self.init()
        self._require_carry_support("decode_step")
        # jit commits np inputs itself; an eager device_put per token
        # would dominate the single-token dispatch (bench.py --decode).
        xs = tuple(f if hasattr(f, "ndim") else np.asarray(f)
                   for f in features)
        for x in xs:
            if x.ndim != 3:
                raise ValueError(
                    f"decode_step expects (batch, time, features) "
                    f"inputs, got shape {x.shape}")
        if carries is None:
            carries = self._init_carries(int(xs[0].shape[0]))
        return self._decode_step_fn(
            self.params if params is None else params,
            self.net_state if net_state is None else net_state,
            carries, xs)

    def grow_decode_carries(self, carries, cache_len: int):
        """Pad every KV ring in ``carries`` up to ``cache_len`` slots
        (ONE jitted dispatch; non-ring carries pass through) — the
        serving cache-len bucket hop."""
        self.init()
        return self._decode_grow_fn(int(cache_len))(carries)

    def rnn_clear_previous_state(self) -> None:
        """Reference ``rnnClearPreviousState()``."""
        self._rnn_carries = None
        self._rnn_carry_batch = -1

    def rnn_get_previous_state(self, vertex_name: str):
        """Carry pytree for one recurrent vertex (reference
        ``rnnGetPreviousState(String)``)."""
        return (None if self._rnn_carries is None
                else self._rnn_carries.get(vertex_name))

    def rnn_set_previous_state(self, vertex_name: str, state) -> None:
        if self._rnn_carries is None:
            raise ValueError("No rnn state yet; call rnn_time_step first")
        if vertex_name not in self._rnn_carries:
            raise KeyError(f"'{vertex_name}' is not a recurrent vertex")
        self._rnn_carries[vertex_name] = state

    # ------------------------------------------------------------- inference
    def output(self, *features, features_masks=None):
        """Forward to all outputs (reference ``output:1099-1123``).  Returns
        a single array for single-output graphs, else a list."""
        self.init()
        feats = tuple(jnp.asarray(f) for f in features)
        fmasks = (None if features_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in features_masks))
        outs = [np.asarray(o) for o in self._output_fn(
            self.params, self.net_state, feats, fmasks)]
        return outs[0] if len(outs) == 1 else outs

    def compile_output(self, feature_shapes, dtype=None, mask_shapes=None,
                       mask_dtype=None, params=None, net_state=None):
        """AOT-compile the inference forward for one concrete shape per
        graph input (``.lower().compile()`` through
        ``monitor.watched_jit`` → counted in
        ``jit_compiles_total{fn="cg.output"}``); the ``ComputationGraph``
        face of the serving bucket-warmup primitive — see
        ``MultiLayerNetwork.compile_output``.

        ``feature_shapes`` is one shape tuple per network input;
        ``mask_shapes`` (optional) one shape-or-None per input.  Call the
        result as ``compiled(params, net_state, features_tuple,
        masks_tuple_or_None)``; it returns the output list.
        ``params``/``net_state`` override the lowering operands (pass
        device-committed copies to pin the executable to a device).
        """
        self.init()
        if params is None:
            params = self.params
        if net_state is None:
            net_state = self.net_state
        dt = jnp.dtype(dtype if dtype is not None else self.conf.conf.dtype)
        avals = tuple(
            jax.ShapeDtypeStruct(tuple(int(d) for d in s), dt)
            for s in feature_shapes)
        mavals = None
        if mask_shapes is not None:
            mdt = jnp.dtype(mask_dtype if mask_dtype is not None else dt)
            mavals = tuple(
                None if s is None
                else jax.ShapeDtypeStruct(tuple(int(d) for d in s), mdt)
                for s in mask_shapes)
        return self._output_fn.lower(params, net_state, avals,
                                     mavals).compile()

    def score(self, data=None) -> float:
        if data is None:
            return float(self._score)
        self.init()
        mds = _as_multi(data)
        fmasks = (None if mds.features_masks is None else tuple(
            None if m is None else jnp.asarray(m)
            for m in mds.features_masks))
        lmasks = (None if mds.labels_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in mds.labels_masks))
        return float(self._score_fn(
            self.params, self.net_state,
            tuple(jnp.asarray(f) for f in mds.features),
            tuple(jnp.asarray(l) for l in mds.labels), fmasks, lmasks))

    def do_evaluation(self, iterator, *evaluators):
        """Run one forward pass per batch, feeding every evaluator
        (reference ``doEvaluation``); single-output graphs only.  Returns
        the evaluators."""
        if len(self.conf.network_outputs) != 1:
            raise ValueError("do_evaluation() requires a single-output "
                             "graph")
        from ..eval.evaluation import Evaluation
        if isinstance(iterator, (DataSet, MultiDataSet)):
            iterator = [iterator]
        if hasattr(iterator, "reset"):
            iterator.reset()
        fast = bool(evaluators) and all(
            type(ev) is Evaluation and ev.top_n == 1 for ev in evaluators)
        bytes_moved = 0
        for ds in iterator:
            mds = _as_multi(ds)
            labels = np.asarray(mds.labels[0])
            mask = None
            if mds.labels_masks is not None:
                mask = mds.labels_masks[0]
            elif mds.features_masks is not None:
                mask = mds.features_masks[0]
            mask = None if mask is None else np.asarray(mask)
            if fast:
                self.init()
                feats = tuple(jnp.asarray(f) for f in mds.features)
                fmasks = (None if mds.features_masks is None else tuple(
                    None if m is None else jnp.asarray(m)
                    for m in mds.features_masks))
                guess = np.asarray(self._eval_argmax_fn(
                    self.params, self.net_state, feats, fmasks))
                bytes_moved += guess.nbytes
                actual = labels.argmax(-1)
                if labels.ndim == 3:
                    actual, guess = actual.reshape(-1), guess.reshape(-1)
                    if mask is not None:
                        keep = mask.reshape(-1) > 0
                        actual, guess = actual[keep], guess[keep]
                for ev in evaluators:
                    ev.eval_class_indices(actual, guess, labels.shape[-1])
                continue
            out = self.output(*mds.features,
                              features_masks=mds.features_masks)
            bytes_moved += out.nbytes
            for ev in evaluators:
                if out.ndim == 3:
                    ev.eval_time_series(labels, out, mask)
                else:
                    ev.eval(labels, out)
        _monitor.gauge(
            "eval_bytes_transferred",
            "device->host bytes moved by the most recent do_evaluation",
        ).set(bytes_moved, path="indices" if fast else "logits")
        return evaluators

    def evaluate(self, iterator):
        """Single-output classification evaluation (reference
        ``SparkComputationGraph``-style ``evaluate``)."""
        from ..eval.evaluation import Evaluation
        return self.do_evaluation(iterator, Evaluation())[0]

    def evaluate_roc(self, iterator, threshold_steps: int = 30):
        """Binary ROC (reference ``evaluateROC``)."""
        from ..eval.roc import ROC
        return self.do_evaluation(iterator, ROC(threshold_steps))[0]

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 30):
        """One-vs-all ROC (reference ``evaluateROCMultiClass``)."""
        from ..eval.roc import ROCMultiClass
        return self.do_evaluation(iterator,
                                  ROCMultiClass(threshold_steps))[0]

    def evaluate_regression(self, iterator):
        """Per-column regression stats (reference
        ``evaluateRegression``)."""
        from ..eval.regression import RegressionEvaluation
        return self.do_evaluation(iterator, RegressionEvaluation())[0]

    def predict(self, *features) -> np.ndarray:
        out = self.output(*features)
        if isinstance(out, list):
            raise ValueError("predict() requires a single-output graph")
        return np.argmax(out, axis=-1)

    # ------------------------------------------------ flat-param invariant
    def param_table(self) -> Dict[str, np.ndarray]:
        from ..utils.device import fetch_all
        self.init()
        dev = {}
        for name in self._layer_names():
            for p in self.vertices[name].layer.param_order():
                dev[f"{name}_{p}"] = self.params[name][p]
        # fetch_all: per-array synchronous np.asarray costs one full
        # host<->device round trip EACH (~320 arrays x ~100 ms tunnel
        # RTT = ~30 s per StatsListener post on ResNet-50).
        return dict(zip(dev, fetch_all(dev.values())))

    def num_params(self) -> int:
        self.init()
        return sum(int(np.prod(p.shape))
                   for tree in self.params.values()
                   for p in jax.tree_util.tree_leaves(tree))

    def get_flat_params(self) -> np.ndarray:
        from ..utils.device import fetch_all
        self.init()
        dev = [self.params[name][p]
               for name in self._layer_names()
               for p in self.vertices[name].layer.param_order()]
        chunks = [a.ravel() for a in fetch_all(dev)]
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_flat_params(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for name in self._layer_names():
            for p in self.vertices[name].layer.param_order():
                shape = self.params[name][p].shape
                size = int(np.prod(shape))
                self.params[name][p] = jnp.asarray(
                    flat[offset:offset + size].reshape(shape),
                    self.params[name][p].dtype)
                offset += size
        if offset != flat.size:
            raise ValueError(
                f"Flat param size mismatch: expected {offset}, got "
                f"{flat.size}")
        self._sync_masters_from_params()

    def _sync_masters_from_params(self) -> None:
        """Re-derive fp32 masters after a direct param write; checkpoint
        restore overwrites them with the saved fp32 values afterwards."""
        for name, tree in self.updater_state.items():
            if isinstance(tree, dict) and _updaters.MASTER_KEY in tree:
                tree[_updaters.MASTER_KEY] = {
                    k: jnp.asarray(self.params[name][k], jnp.float32)
                    for k in tree[_updaters.MASTER_KEY]}

    def get_flat_updater_state(self) -> np.ndarray:
        self.init()
        leaves = []
        for name in self._layer_names():
            leaves.extend(
                np.asarray(l).ravel()
                for l in jax.tree_util.tree_leaves(self.updater_state[name]))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_updater_state(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for name in self._layer_names():
            tree = self.updater_state[name]
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            new_leaves = []
            for leaf in leaves:
                size = int(np.prod(leaf.shape))
                new_leaves.append(jnp.asarray(
                    flat[offset:offset + size].reshape(leaf.shape),
                    leaf.dtype))
                offset += size
            self.updater_state[name] = jax.tree_util.tree_unflatten(
                treedef, new_leaves)

    # -------------------------------------------------------------- misc API
    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def clone(self) -> "ComputationGraph":
        import copy
        other = ComputationGraph(copy.deepcopy(self.conf))
        other.init()
        other.params = jax.tree.map(jnp.copy, self.params)
        other.net_state = jax.tree.map(jnp.copy, self.net_state)
        other.updater_state = jax.tree.map(jnp.copy, self.updater_state)
        other.iteration = self.iteration
        other._pretrain_done = self._pretrain_done
        return other
