"""ComputationGraph: arbitrary-DAG network container.

TPU-native equivalent of the reference's ``nn/graph/ComputationGraph.java``
(2276 LoC): ``init():267``, topo-order forward loop at ``:1048-1049``,
``fit`` variants ``:650-810``, ``calcBackpropGradients:1175``,
``output:1099-1123``.

Where the reference walks materialized vertex objects per call, here one
traced pure function executes the DAG in the (build-time) topological order;
jit compiles forward + loss + backward + updater into a single XLA program.
Multi-input/multi-output batches are :class:`MultiDataSet` pytrees.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import updaters as _updaters
from .conf.computation_graph import (ComputationGraphConfiguration,
                                     DuplicateToTimeSeriesVertex,
                                     LastTimeStepVertex, LayerVertex)
from ..datasets.dataset import DataSet, MultiDataSet

Array = jax.Array


def _as_multi(data) -> MultiDataSet:
    if isinstance(data, MultiDataSet):
        return data
    if isinstance(data, DataSet):
        return MultiDataSet(
            features=[data.features], labels=[data.labels],
            features_masks=(None if data.features_mask is None
                            else [data.features_mask]),
            labels_masks=(None if data.labels_mask is None
                          else [data.labels_mask]))
    raise TypeError(f"Expected DataSet/MultiDataSet, got {type(data)}")


class ComputationGraph:
    """DAG network with named vertices (reference ``ComputationGraph``)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self.vertices = conf.vertices
        self.params: Dict[str, Dict[str, Array]] = {}
        self.net_state: Dict[str, Dict[str, Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._init_done = False
        self._score = float("nan")
        self._rng_key: Optional[jax.Array] = None
        self._pretrain_step_cache: Dict[str, Any] = {}
        self._pretrain_done = False

    # ------------------------------------------------------------------ init
    def init(self) -> "ComputationGraph":
        if self._init_done:
            return self
        dtype = jnp.dtype(self.conf.conf.dtype)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        self._rng_key = key
        names = [n for n in self.topo
                 if isinstance(self.vertices[n], LayerVertex)]
        keys = jax.random.split(key, max(len(names), 1))
        for n, k in zip(names, keys):
            layer = self.vertices[n].layer
            self.params[n] = layer.init_params(k, dtype)
            self.net_state[n] = layer.init_state(dtype)
            self.updater_state[n] = _updaters.init_state(
                self._updater_conf(n),
                _updaters.updatable_params(layer, self.params[n]))
        self._init_done = True
        return self

    def _updater_conf(self, name: str):
        return (self.vertices[name].layer.updater
                or self.conf.conf.updater)

    def _layer_names(self) -> List[str]:
        return [n for n in self.topo
                if isinstance(self.vertices[n], LayerVertex)]

    def _output_layer_vertices(self) -> List[str]:
        return list(self.conf.network_outputs)

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, inputs: Sequence[Array], *,
                 train: bool, rng: Optional[jax.Array],
                 input_masks: Optional[Dict[str, Array]] = None,
                 preoutput_outputs: bool = False):
        """Execute the DAG (reference forward loop ``:1048``).  Returns
        (activations dict, new_state dict)."""
        conf = self.conf
        acts: Dict[str, Array] = {}
        compute_dtype = conf.conf.compute_dtype
        in_dtype = jnp.dtype(compute_dtype or conf.conf.dtype)
        for name, x in zip(conf.network_inputs, inputs):
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(in_dtype)
            acts[name] = x
        if compute_dtype:
            cast = jnp.dtype(compute_dtype)
            params = jax.tree.map(
                lambda p: p.astype(cast)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        new_state = dict(net_state)
        layer_names = self._layer_names()
        keys = (jax.random.split(rng, max(len(layer_names), 1))
                if rng is not None else [None] * max(len(layer_names), 1))
        key_of = dict(zip(layer_names, keys))
        # Per-vertex propagated time masks (feedForwardMaskArray analogue):
        # input masks flow along the DAG for per-timestep layers.
        masks: Dict[str, Optional[Array]] = dict(input_masks or {})

        for name in self.topo:
            v = self.vertices[name]
            xs = [acts[i] for i in v.inputs]
            in_masks = [masks.get(i) for i in v.inputs]
            mask = next((m for m in in_masks if m is not None), None)
            if isinstance(v, LayerVertex):
                x = xs[0]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                layer = v.layer
                if preoutput_outputs and name in conf.network_outputs \
                        and hasattr(layer, "pre_output"):
                    if layer.dropout and train:
                        x = layer.apply_dropout(x, train, key_of[name])
                    out = layer.pre_output(params[name], x)
                else:
                    out, new_state[name] = layer.forward(
                        params[name], net_state[name], x, train=train,
                        rng=key_of[name], mask=mask)
                acts[name] = out
                masks[name] = mask
            elif isinstance(v, DuplicateToTimeSeriesVertex):
                ref = v.reference_input
                acts[name] = v.apply(*xs, masks=masks,
                                     timesteps=acts[ref].shape[1])
                masks[name] = masks.get(ref)
            elif isinstance(v, LastTimeStepVertex):
                acts[name] = v.apply(*xs, masks=masks)
                masks[name] = None
            else:
                acts[name] = v.apply(*xs, masks=masks)
                masks[name] = mask
        if compute_dtype:
            for out in conf.network_outputs:
                acts[out] = acts[out].astype(jnp.float32)
        return acts, new_state

    # ------------------------------------------------------------------ loss
    def _loss_fn(self, params, net_state, features, labels, features_masks,
                 labels_masks, rng, train: bool):
        input_masks = None
        if features_masks is not None:
            input_masks = {n: m for n, m in zip(self.conf.network_inputs,
                                                features_masks)
                           if m is not None}
        acts, new_state = self._forward(
            params, net_state, features, train=train, rng=rng,
            input_masks=input_masks, preoutput_outputs=True)
        total = jnp.asarray(0.0, jnp.float32)
        for i, out_name in enumerate(self.conf.network_outputs):
            v = self.vertices[out_name]
            layer = v.layer
            lmask = None if labels_masks is None else labels_masks[i]
            if getattr(layer, "NEEDS_INPUT_FOR_SCORE", False):
                # Center-loss-style heads score against their input
                # activations; those are already in the DAG's acts.
                x = acts[v.inputs[0]]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                if layer.dropout and train and rng is not None:
                    x = layer.apply_dropout(
                        x, train, jax.random.fold_in(rng, 100_000 + i))
                total = total + layer.compute_score_with_input(
                    params[out_name], labels[i], x, lmask,
                    average=self.conf.conf.mini_batch)
                continue
            if not hasattr(layer, "compute_score"):
                raise ValueError(
                    f"Output vertex '{out_name}' is not an output layer")
            total = total + layer.compute_score(
                labels[i], acts[out_name], lmask,
                average=self.conf.conf.mini_batch)
        return total, new_state

    def _reg_score(self, params) -> Array:
        total = jnp.asarray(0.0, jnp.float32)
        for name in self._layer_names():
            layer = self.vertices[name].layer
            total = total + _updaters.regularization_score(
                params[name], layer.l1_by_param(), layer.l2_by_param())
        return total

    # ------------------------------------------------------------ train step
    def _apply_updates(self, params, updater_state, grads, iteration):
        new_params, new_ustate = {}, {}
        for name in self._layer_names():
            layer = self.vertices[name].layer
            g = grads[name]
            if g:
                new_params[name], new_ustate[name] = \
                    _updaters.apply_layer_updates(
                        self._updater_conf(name), layer, params[name],
                        updater_state[name], g, iteration)
            else:
                new_params[name] = params[name]
                new_ustate[name] = updater_state[name]
        return new_params, new_ustate

    @functools.cached_property
    def _train_step(self):
        def step(params, updater_state, net_state, iteration, features,
                 labels, features_masks, labels_masks, base_rng):
            rng = jax.random.fold_in(base_rng, iteration)
            (data_loss, new_state), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(
                    params, net_state, features, labels, features_masks,
                    labels_masks, rng, True)
            new_params, new_ustate = self._apply_updates(
                params, updater_state, grads, iteration)
            score = data_loss + self._reg_score(params)
            return new_params, new_ustate, new_state, score

        return jax.jit(step, donate_argnums=(0, 1, 2))

    @functools.cached_property
    def _output_fn(self):
        def run(params, net_state, features, features_masks):
            input_masks = None
            if features_masks is not None:
                input_masks = {
                    n: m for n, m in zip(self.conf.network_inputs,
                                         features_masks) if m is not None}
            acts, _ = self._forward(params, net_state, features, train=False,
                                    rng=None, input_masks=input_masks)
            return [acts[o] for o in self.conf.network_outputs]
        return jax.jit(run)

    @functools.cached_property
    def _score_fn(self):
        def score(params, net_state, features, labels, features_masks,
                  labels_masks):
            data_loss, _ = self._loss_fn(
                params, net_state, features, labels, features_masks,
                labels_masks, None, False)
            return data_loss + self._reg_score(params)
        return jax.jit(score)

    # -------------------------------------------------------------- pretrain
    def _pretrain_step(self, name: str):
        """Jitted unsupervised step for one layer vertex (reference
        ``ComputationGraph.pretrain:510-555``)."""
        if name not in self._pretrain_step_cache:
            v = self.vertices[name]
            layer = v.layer
            uconf = self._updater_conf(name)

            def step(params, ustate, net_state, iteration, features,
                     base_rng):
                rng = jax.random.fold_in(base_rng, iteration)
                acts, _ = self._forward(params, net_state, features,
                                        train=False, rng=None)
                x = acts[v.inputs[0]]
                if v.preprocessor is not None:
                    x = v.preprocessor(x)
                x = jax.lax.stop_gradient(x)
                score, grads = layer.pretrain_grads(params[name], x, rng)
                grads = _updaters.regularize(grads, params[name],
                                             layer.l1_by_param(),
                                             layer.l2_by_param())
                grads = _updaters.normalize_gradients(
                    grads, layer.gradient_normalization,
                    layer.gradient_normalization_threshold)
                updates, new_ustate = _updaters.compute_update(
                    uconf, grads, ustate, iteration)
                new_p = jax.tree.map(lambda p, u: p - u, params[name],
                                     updates)
                score = score + _updaters.regularization_score(
                    params[name], layer.l1_by_param(), layer.l2_by_param())
                return new_p, new_ustate, score

            self._pretrain_step_cache[name] = jax.jit(step,
                                                      donate_argnums=(1,))
        return self._pretrain_step_cache[name]

    def pretrain(self, data, epochs: int = 1) -> "ComputationGraph":
        """Greedy layer-wise pretraining of every pretrainable layer vertex
        in topological order (reference ``ComputationGraph.pretrain:510``)."""
        self.init()
        if not isinstance(data, (DataSet, MultiDataSet)) \
                and not hasattr(data, "reset"):
            data = list(data)  # one-shot iterable: each layer needs a pass
        for name in self._layer_names():
            if getattr(self.vertices[name].layer, "IS_PRETRAINABLE", False):
                self.pretrain_layer(name, data, epochs)
        return self

    def pretrain_layer(self, name: str, data,
                       epochs: int = 1) -> "ComputationGraph":
        self.init()
        if not getattr(self.vertices[name].layer, "IS_PRETRAINABLE", False):
            return self
        step = self._pretrain_step(name)
        batches = ([data] if isinstance(data, (DataSet, MultiDataSet))
                   else data)
        for _ in range(epochs):
            if hasattr(batches, "reset"):
                batches.reset()
            for ds in batches:
                mds = _as_multi(ds)
                features = tuple(jnp.asarray(f) for f in mds.features)
                (self.params[name], self.updater_state[name],
                 score) = step(self.params, self.updater_state[name],
                               self.net_state, self.iteration, features,
                               self._rng_key)
                self._score = score
                self.iteration += 1
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration)
        return self

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1) -> "ComputationGraph":
        """Train (reference ``fit`` variants ``:650-810``).  ``data`` may be
        a (Multi)DataSet, an iterator of them, or features with ``labels``.

        With ``conf.pretrain=True`` the first call pretrains every
        pretrainable layer vertex; ``conf.backprop=False`` skips the
        supervised phase (reference ``fit:740`` + ``pretrain:510``)."""
        self.init()
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
            iterator = None
        else:
            iterator = data
            batches = None
        if self.conf.pretrain and not self._pretrain_done:
            if batches is None and not hasattr(iterator, "reset"):
                # One-shot iterable: materialize so layer-wise pretraining
                # and the supervised phase each see the full data.
                batches = list(iterator)
                iterator = None
            self.pretrain(batches if batches is not None else iterator)
            self._pretrain_done = True
        if not getattr(self.conf, "backprop", True):
            return self
        for _ in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            it = batches if batches is not None else iterator
            if hasattr(it, "reset"):
                it.reset()
            for ds in it:
                self._fit_batch(_as_multi(ds))
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
            self.epoch += 1
        return self

    def _fit_batch(self, mds: MultiDataSet) -> None:
        self.last_batch_size = mds.num_examples()
        features = tuple(jnp.asarray(f) for f in mds.features)
        labels = tuple(jnp.asarray(l) for l in mds.labels)
        fmasks = (None if mds.features_masks is None else tuple(
            None if m is None else jnp.asarray(m)
            for m in mds.features_masks))
        lmasks = (None if mds.labels_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in mds.labels_masks))
        for _ in range(self.conf.conf.num_iterations):
            (self.params, self.updater_state, self.net_state,
             score) = self._train_step(
                self.params, self.updater_state, self.net_state,
                self.iteration, features, labels, fmasks, lmasks,
                self._rng_key)
            self._score = score
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    # ------------------------------------------------------------- inference
    def output(self, *features, features_masks=None):
        """Forward to all outputs (reference ``output:1099-1123``).  Returns
        a single array for single-output graphs, else a list."""
        self.init()
        feats = tuple(jnp.asarray(f) for f in features)
        fmasks = (None if features_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in features_masks))
        outs = [np.asarray(o) for o in self._output_fn(
            self.params, self.net_state, feats, fmasks)]
        return outs[0] if len(outs) == 1 else outs

    def score(self, data=None) -> float:
        if data is None:
            return float(self._score)
        self.init()
        mds = _as_multi(data)
        fmasks = (None if mds.features_masks is None else tuple(
            None if m is None else jnp.asarray(m)
            for m in mds.features_masks))
        lmasks = (None if mds.labels_masks is None else tuple(
            None if m is None else jnp.asarray(m) for m in mds.labels_masks))
        return float(self._score_fn(
            self.params, self.net_state,
            tuple(jnp.asarray(f) for f in mds.features),
            tuple(jnp.asarray(l) for l in mds.labels), fmasks, lmasks))

    def evaluate(self, iterator):
        """Single-output classification evaluation (reference
        ``SparkComputationGraph``-style ``evaluate``)."""
        from ..eval.evaluation import Evaluation
        if len(self.conf.network_outputs) != 1:
            raise ValueError("evaluate() requires a single-output graph")
        ev = Evaluation()
        if isinstance(iterator, (DataSet, MultiDataSet)):
            iterator = [iterator]
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            mds = _as_multi(ds)
            out = self.output(*mds.features,
                              features_masks=mds.features_masks)
            labels = np.asarray(mds.labels[0])
            if out.ndim == 3:
                mask = None
                if mds.labels_masks is not None:
                    mask = mds.labels_masks[0]
                elif mds.features_masks is not None:
                    mask = mds.features_masks[0]
                ev.eval_time_series(
                    labels, out, None if mask is None else np.asarray(mask))
            else:
                ev.eval(labels, out)
        return ev

    def predict(self, *features) -> np.ndarray:
        out = self.output(*features)
        if isinstance(out, list):
            raise ValueError("predict() requires a single-output graph")
        return np.argmax(out, axis=-1)

    # ------------------------------------------------ flat-param invariant
    def param_table(self) -> Dict[str, np.ndarray]:
        self.init()
        out = {}
        for name in self._layer_names():
            for p in self.vertices[name].layer.param_order():
                out[f"{name}_{p}"] = np.asarray(self.params[name][p])
        return out

    def num_params(self) -> int:
        self.init()
        return sum(int(np.prod(p.shape))
                   for tree in self.params.values()
                   for p in jax.tree_util.tree_leaves(tree))

    def get_flat_params(self) -> np.ndarray:
        self.init()
        chunks = []
        for name in self._layer_names():
            for p in self.vertices[name].layer.param_order():
                chunks.append(np.asarray(self.params[name][p]).ravel())
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_flat_params(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for name in self._layer_names():
            for p in self.vertices[name].layer.param_order():
                shape = self.params[name][p].shape
                size = int(np.prod(shape))
                self.params[name][p] = jnp.asarray(
                    flat[offset:offset + size].reshape(shape),
                    self.params[name][p].dtype)
                offset += size
        if offset != flat.size:
            raise ValueError(
                f"Flat param size mismatch: expected {offset}, got "
                f"{flat.size}")

    def get_flat_updater_state(self) -> np.ndarray:
        self.init()
        leaves = []
        for name in self._layer_names():
            leaves.extend(
                np.asarray(l).ravel()
                for l in jax.tree_util.tree_leaves(self.updater_state[name]))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_updater_state(self, flat: np.ndarray) -> None:
        self.init()
        flat = np.asarray(flat)
        offset = 0
        for name in self._layer_names():
            tree = self.updater_state[name]
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            new_leaves = []
            for leaf in leaves:
                size = int(np.prod(leaf.shape))
                new_leaves.append(jnp.asarray(
                    flat[offset:offset + size].reshape(leaf.shape),
                    leaf.dtype))
                offset += size
            self.updater_state[name] = jax.tree_util.tree_unflatten(
                treedef, new_leaves)

    # -------------------------------------------------------------- misc API
    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def clone(self) -> "ComputationGraph":
        import copy
        other = ComputationGraph(copy.deepcopy(self.conf))
        other.init()
        other.params = jax.tree.map(jnp.copy, self.params)
        other.net_state = jax.tree.map(jnp.copy, self.net_state)
        other.updater_state = jax.tree.map(jnp.copy, self.updater_state)
        other.iteration = self.iteration
        other._pretrain_done = self._pretrain_done
        return other
