"""Pretrained-model zoo path: VGG-16.

TPU-native equivalent of the reference's
``deeplearning4j-modelimport/.../trainedmodels/TrainedModels.java:18``
(VGG16 / VGG16NOTOP enum: downloads Keras-1 h5 weights and builds the
network) plus ``VGG16ImagePreProcessor`` (ImageNet mean subtraction,
referenced at ``TrainedModels.java:7``) and ``TrainedModelHelper``.

This environment has no egress, so the download step is split out: the
architecture builder and the weight loader are mandatory (BASELINE config
#5 is VGG-16 via import); fetching the ``.h5`` is the caller's job (pass a
local path).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..nn.conf import inputs as _inputs
from ..nn.conf.neural_net_configuration import (MultiLayerConfiguration,
                                                NeuralNetConfiguration)
from ..nn.layers.convolution import ConvolutionLayer, SubsamplingLayer
from ..nn.layers.core import DenseLayer, OutputLayer
from ..nn.multilayer import MultiLayerNetwork

# conv widths per block (reference VGG-16 topology)
_BLOCKS = ((64, 64), (128, 128), (256, 256, 256), (512, 512, 512),
           (512, 512, 512))


def vgg16(n_classes: int = 1000, include_top: bool = True,
          height: int = 224, width: int = 224, channels: int = 3,
          compute_dtype: Optional[str] = None) -> MultiLayerConfiguration:
    """VGG-16 configuration (reference ``TrainedModels.VGG16`` /
    ``VGG16NOTOP`` when ``include_top=False``)."""
    b = (NeuralNetConfiguration.builder()
         .seed(12).updater("nesterovs").learning_rate(1e-2)
         .weight_init("relu").activation("identity"))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    lb = b.list()
    for widths in _BLOCKS:
        for w in widths:
            lb.layer(ConvolutionLayer(n_out=w, kernel_size=(3, 3),
                                      stride=(1, 1),
                                      convolution_mode="same",
                                      activation="relu"))
        lb.layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                  stride=(2, 2)))
    if include_top:
        lb.layer(DenseLayer(n_out=4096, activation="relu"))
        lb.layer(DenseLayer(n_out=4096, activation="relu"))
        lb.layer(OutputLayer(n_out=n_classes, activation="softmax",
                             loss="mcxent"))
    lb.set_input_type(_inputs.convolutional(height, width, channels))
    return lb.build()


class VGG16ImagePreProcessor:
    """ImageNet mean subtraction (reference ``VGG16ImagePreProcessor``):
    per-channel RGB means, applied to (batch, H, W, 3) f32 images in
    0-255 range.  Usable as a DataSet preprocessor or called directly."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def transform(self, features: np.ndarray) -> np.ndarray:
        return np.asarray(features, np.float32) - self.MEANS

    def preprocess(self, dataset) -> None:
        dataset.features = self.transform(dataset.features)

    __call__ = transform


class ImageNetLabels:
    """Class-index -> label decoding (reference
    ``modelimport/.../Utils/ImageNetLabels.java``: decodePredictions).

    The reference downloads the 1000 ImageNet label strings; in a
    zero-egress build the labels come from a user-supplied file (one
    label per line, index order) and default to ``class_0000``-style
    placeholders.
    """

    def __init__(self, labels_path: Optional[str] = None,
                 labels: Optional[list] = None, n_classes: int = 1000):
        if labels is not None:
            self.labels = list(labels)
        elif labels_path is not None:
            with open(labels_path, "r", encoding="utf-8") as f:
                self.labels = [ln.strip() for ln in f if ln.strip()]
        else:
            self.labels = [f"class_{i:04d}" for i in range(n_classes)]

    def label(self, idx: int) -> str:
        return self.labels[idx]

    def decode_predictions(self, predictions, top: int = 5):
        """(batch, classes) probabilities -> per-example
        [(label, probability), ...] of the ``top`` most probable classes
        (reference ``decodePredictions``)."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None]
        if p.shape[-1] != len(self.labels):
            raise ValueError(f"{p.shape[-1]} classes vs "
                             f"{len(self.labels)} labels")
        order = np.argsort(-p, axis=-1)[:, :top]
        return [[(self.labels[int(c)], float(row_p[int(c)]))
                 for c in row] for row, row_p in zip(order, p)]


def load_vgg16(weights_path: Optional[str] = None,
               n_classes: int = 1000,
               include_top: bool = True) -> MultiLayerNetwork:
    """Build VGG-16 and (optionally) load Keras-1 h5 weights into it —
    the ``TrainedModelHelper.loadModel`` role.  The h5 must carry the
    standard Keras-1 VGG16 layer groups in file order (conv*/dense*)."""
    net = MultiLayerNetwork(vgg16(n_classes=n_classes,
                                  include_top=include_top)).init()
    if weights_path is None:
        return net
    import h5py
    import jax.numpy as jnp
    with h5py.File(weights_path, "r") as f:
        g = f["model_weights"] if "model_weights" in f else f
        # layers with params, in order
        param_layers = [i for i, l in enumerate(net.conf.layers)
                        if net.params[i]]
        # Keras-1 save_weights records file order in the `layer_names`
        # root attr; h5py group iteration is alphabetical (conv2d_10
        # sorts before conv2d_2), so the attr is authoritative.
        layer_names = [n.decode() if isinstance(n, bytes) else str(n)
                       for n in g.attrs.get("layer_names", [])]
        if not layer_names:
            layer_names = list(g)
        h5_layers = []
        for name in layer_names:
            grp = g[name]
            names = list(grp.attrs.get("weight_names", []))
            if names:
                h5_layers.append((name, grp, names))
        if len(h5_layers) != len(param_layers):
            raise ValueError(
                f"VGG16 weight file has {len(h5_layers)} param layers, "
                f"architecture expects {len(param_layers)}")
        th_detected = False
        last_conv_channels = None
        seen_dense_after_conv = False
        for (name, grp, names), i in zip(h5_layers, param_layers):
            arrays = [np.asarray(grp[n if isinstance(n, str)
                                     else n.decode()]) for n in names]
            W, bias = arrays[0], arrays[1]
            want = net.params[i]["W"].shape
            if W.ndim == 4:
                last_conv_channels = want[-1]
                if W.shape[0] not in (1, 3) and W.shape[-1] != want[-1]:
                    # th ordering; shared transform with the importer
                    from .keras_model_import import th_kernel_to_hwio
                    W = th_kernel_to_hwio(W)
                    th_detected = True
            elif (W.ndim == 2 and not seen_dense_after_conv
                  and last_conv_channels is not None):
                seen_dense_after_conv = True
                if th_detected:
                    # th flatten order is (C, H, W); this network flattens
                    # NHWC — permute the first dense layer's input rows
                    # (shared transform with the importer).
                    from .keras_model_import import th_dense_rows_to_nhwc
                    c = last_conv_channels
                    s = int(round((W.shape[0] / c) ** 0.5))
                    W = th_dense_rows_to_nhwc(W, (s, s, c))
            net.params[i]["W"] = jnp.asarray(
                W.reshape(want), net.params[i]["W"].dtype)
            net.params[i]["b"] = jnp.asarray(
                bias.reshape(net.params[i]["b"].shape),
                net.params[i]["b"].dtype)
    return net


class TrainedModels:
    """Reference enum-shaped namespace (``TrainedModels.java``)."""

    VGG16 = staticmethod(lambda weights_path=None: load_vgg16(
        weights_path, include_top=True))
    VGG16NOTOP = staticmethod(lambda weights_path=None: load_vgg16(
        weights_path, include_top=False))
