"""Keras 1.x HDF5 model import.

TPU-native equivalent of the reference's ``deeplearning4j-modelimport``:
``KerasModelImport.java:48-156`` (entry points),
``KerasSequentialModel`` (-> MultiLayerConfiguration),
``KerasModel.java:59`` (functional API -> ComputationGraph),
and the per-layer mappers in ``layers/Keras*.java``.

Where the reference walks the file with JavaCPP HDF5 C++ bindings
(``Hdf5Archive.java``), here h5py provides the (equally native C) HDF5
access.  Supported Keras 1.x layers: Dense, Activation, Dropout, Flatten,
Convolution2D, MaxPooling2D, AveragePooling2D, ZeroPadding2D,
BatchNormalization, LSTM, Embedding + functional-API Merge (concat/sum).

Weight-layout notes (mirroring the reference mappers):
- Dense: W (in, out), b — identical layout to ours.
- Convolution2D: Keras 'th' kernels are (nb_filter, stack, kh, kw); 'tf'
  kernels are (kh, kw, stack, nb_filter) = our HWIO (no transpose needed).
- LSTM: Keras per-gate arrays [W_i,W_f,W_c,W_o / U_* / b_*] concatenate in
  DL4J gate order [c|f|o|i] with 3 zeroed peephole columns appended to U
  (``KerasLstm.java:150-230``).
- BatchNormalization: gamma, beta, running_mean, running_std (Keras 1 stores
  std... actually variance; mode=0 feature axis).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf import inputs as _inputs
from ..nn.conf.computation_graph import MergeVertex, ElementWiseVertex
from ..nn.conf.neural_net_configuration import NeuralNetConfiguration
from ..nn.computation_graph import ComputationGraph
from ..nn.layers.convolution import (ConvolutionLayer, SubsamplingLayer,
                                     ZeroPaddingLayer)
from ..nn.layers.core import (ActivationLayer, DenseLayer, DropoutLayer,
                              EmbeddingLayer, OutputLayer)
from ..nn.layers.normalization import BatchNormalization
from ..nn.layers.recurrent import GravesLSTM, RnnOutputLayer
from ..nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh",
    "softmax": "softmax", "linear": "identity", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
}


def _map_activation(name: str) -> str:
    if name not in _ACTIVATIONS:
        raise ValueError(f"Unsupported Keras activation '{name}'")
    return _ACTIVATIONS[name]


def _layer_weights(wgroup, layer_name: str) -> Dict[str, np.ndarray]:
    """Read {short_param_name: array} for one layer (Keras 1.x layout:
    group per layer, attrs['weight_names'] ordering)."""
    if layer_name not in wgroup:
        return {}
    g = wgroup[layer_name]
    names = [n.decode() if isinstance(n, bytes) else str(n)
             for n in g.attrs.get("weight_names", [])]
    out = {}
    for full in names:
        short = full.split("/")[-1]
        # keras1 names like 'dense_1_W' -> 'W'; 'lstm_1_W_i' -> 'W_i'
        for prefix in (layer_name + "_", ):
            if short.startswith(prefix):
                short = short[len(prefix):]
        out[short] = np.asarray(g[full])
    return out


class _ImportedLayer:
    def __init__(self, conf_layer, params: Optional[Dict[str, np.ndarray]],
                 state: Optional[Dict[str, np.ndarray]] = None):
        self.conf_layer = conf_layer
        self.params = params
        self.state = state or {}


def _convert_layer(cls: str, cfg: dict, weights: Dict[str, np.ndarray],
                   dim_ordering: Optional[str]) -> Optional[_ImportedLayer]:
    """One Keras layer config -> our layer config + mapped params.
    Returns None for no-op layers (Flatten/Input — handled by preprocessors/
    shape inference)."""
    act = cfg.get("activation", "linear")
    if cls == "Dense":
        layer = DenseLayer(n_out=cfg["output_dim"],
                           activation=_map_activation(act))
        return _ImportedLayer(layer, {"W": weights["W"], "b": weights["b"]})
    if cls == "Activation":
        return _ImportedLayer(
            ActivationLayer(activation=_map_activation(act)), None)
    if cls == "Dropout":
        return _ImportedLayer(DropoutLayer(dropout=cfg.get("p", 0.0)), None)
    if cls in ("Flatten", "InputLayer"):
        return None
    if cls == "Convolution2D":
        ordering = cfg.get("dim_ordering", dim_ordering) or "tf"
        W = weights["W"]
        if ordering == "th":
            W = th_kernel_to_hwio(W)
        border = cfg.get("border_mode", "valid")
        mode = "same" if border == "same" else "truncate"
        layer = ConvolutionLayer(
            n_out=cfg["nb_filter"],
            kernel_size=(cfg["nb_row"], cfg["nb_col"]),
            stride=tuple(cfg.get("subsample", (1, 1))),
            convolution_mode=mode,
            activation=_map_activation(act))
        return _ImportedLayer(layer, {"W": W, "b": weights["b"]})
    if cls == "ZeroPadding2D":
        ph, pw = cfg.get("padding", (1, 1))
        return _ImportedLayer(
            ZeroPaddingLayer(padding=(ph, ph, pw, pw)), None)
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        border = cfg.get("border_mode", "valid")
        layer = SubsamplingLayer(
            pooling_type="max" if cls == "MaxPooling2D" else "avg",
            kernel_size=tuple(cfg.get("pool_size", (2, 2))),
            stride=tuple(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode="same" if border == "same" else "truncate")
        return _ImportedLayer(layer, None)
    if cls == "BatchNormalization":
        if cfg.get("mode", 0) != 0:
            raise ValueError("Only BatchNormalization mode=0 supported")
        layer = BatchNormalization(eps=cfg.get("epsilon", 1e-5))
        params = {"gamma": weights["gamma"], "beta": weights["beta"]}
        state = {"mean": weights.get("running_mean"),
                 "var": weights.get("running_std")}
        return _ImportedLayer(layer, params, state)
    if cls == "Embedding":
        layer = EmbeddingLayer(n_in=cfg["input_dim"],
                               n_out=cfg["output_dim"],
                               activation="identity")
        W = weights["W"]
        return _ImportedLayer(layer, {"W": W,
                                      "b": np.zeros(W.shape[1],
                                                    np.float32)})
    if cls == "LSTM":
        H = cfg["output_dim"]
        inner = _map_activation(cfg.get("inner_activation", "hard_sigmoid"))
        layer = GravesLSTM(n_out=H, activation=_map_activation(act),
                           gate_activation_fn=inner,
                           forget_gate_bias_init=0.0)
        # DL4J gate order [c|f|o|i] + zero peepholes (KerasLstm.java)
        W = np.concatenate([weights["W_c"], weights["W_f"], weights["W_o"],
                            weights["W_i"]], axis=1)
        U = np.concatenate([weights["U_c"], weights["U_f"], weights["U_o"],
                            weights["U_i"], np.zeros((H, 3), W.dtype)],
                           axis=1)
        b = np.concatenate([weights["b_c"], weights["b_f"], weights["b_o"],
                            weights["b_i"]])
        return _ImportedLayer(layer, {"W": W, "RW": U, "b": b})
    raise ValueError(f"Unsupported Keras layer class '{cls}'")


def _conv_out(size: int, k: int, s: int, border: str) -> int:
    if border == "same":
        return -(-size // s)          # ceil
    return (size - k) // s + 1        # valid


def _track_spatial(cls: str, cfg: dict, spatial):
    """Propagate (h, w, c) through conv/pool configs so a th-ordering
    Flatten->Dense can be layout-corrected (below)."""
    if spatial is None:
        return None
    h, w, c = spatial
    if cls == "Convolution2D":
        s = cfg.get("subsample", (1, 1))
        border = cfg.get("border_mode", "valid")
        return (_conv_out(h, cfg["nb_row"], s[0], border),
                _conv_out(w, cfg["nb_col"], s[1], border),
                cfg["nb_filter"])
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        k = cfg.get("pool_size", (2, 2))
        s = cfg.get("strides") or k
        border = cfg.get("border_mode", "valid")
        return (_conv_out(h, k[0], s[0], border),
                _conv_out(w, k[1], s[1], border), c)
    if cls == "ZeroPadding2D":
        ph, pw = cfg.get("padding", (1, 1))
        return (h + 2 * ph, w + 2 * pw, c)
    if cls in ("Activation", "Dropout", "BatchNormalization", "Flatten"):
        return spatial
    return None  # Dense etc. leave the spatial domain


def _input_spatial(cfg: dict, dim_ordering: Optional[str]):
    """(h, w, c) from a 4D ``batch_input_shape``, else None."""
    shape = cfg.get("batch_input_shape")
    if shape is None or len(shape) != 4:
        return None
    dims = shape[1:]
    return (tuple(dims[1:]) + (dims[0],) if dim_ordering == "th"
            else tuple(dims))


def th_kernel_to_hwio(W: np.ndarray) -> np.ndarray:
    """Keras-Theano conv kernel (nb_filter, stack, kh, kw), stored with
    Theano's 180°-rotated filters (true convolution, vs the
    cross-correlation XLA computes — reference
    ``KerasConvolution.java:127-139`` reverses each filter) -> HWIO.
    Shared by the model importer and the trained-models loader so the two
    can never disagree on Theano semantics."""
    return W[:, :, ::-1, ::-1].transpose(2, 3, 1, 0)


def _th_flatten_permutation(spatial) -> np.ndarray:
    """Row permutation taking a Keras-Theano flattened (C, H, W) dense
    kernel to this framework's NHWC (H, W, C) flatten order (reference
    role: ``TensorFlowCnnToFeedForwardPreProcessor`` exists because
    orderings genuinely differ — DL4J is NCHW so 'th' was free there;
    we are NHWC so 'th' needs the permutation and 'tf' is free)."""
    h, w, c = spatial
    return np.arange(c * h * w).reshape(c, h, w).transpose(1, 2, 0).ravel()


def th_dense_rows_to_nhwc(W: np.ndarray, spatial) -> np.ndarray:
    """Permute a post-Flatten dense kernel's input rows from Keras-th
    (C, H, W) flatten order to NHWC flatten order."""
    return np.asarray(W)[_th_flatten_permutation(spatial)]


def _keras_input_type(cfg: dict, dim_ordering: str):
    shape = cfg.get("batch_input_shape")
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return _inputs.feed_forward(dims[0])
    if len(dims) == 2:
        return _inputs.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return _inputs.convolutional(h, w, c)
    raise ValueError(f"Cannot map batch_input_shape {shape}")


def _open(path: str):
    import h5py
    return h5py.File(path, "r")


def _model_config(f) -> dict:
    raw = f.attrs["model_config"]
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8")
    return json.loads(raw)


def _weights_group(f):
    return f["model_weights"] if "model_weights" in f else f


def import_keras_sequential_model_and_weights(path: str,
                                              train_config: bool = False
                                              ) -> MultiLayerNetwork:
    """Reference ``KerasModelImport.importKerasSequentialModelAndWeights``:
    Keras 1.x Sequential .h5 -> MultiLayerNetwork with copied weights.

    The final Dense+softmax collapses into an OutputLayer (the reference
    requires a loss layer for training parity; inference is identical).
    """
    with _open(path) as f:
        conf = _model_config(f)
        if conf["class_name"] != "Sequential":
            raise ValueError("Not a Sequential model; use "
                             "import_keras_model_and_weights")
        layer_confs = conf["config"]
        wgroup = _weights_group(f)

        builder = (NeuralNetConfiguration.builder().updater("sgd")
                   .activation("identity").weight_init("xavier").list())
        imported: List[_ImportedLayer] = []
        input_type = None
        dim_ordering = None
        for lc in layer_confs:
            cfg = lc["config"]
            dim_ordering = cfg.get("dim_ordering", dim_ordering)
        spatial = None          # (h, w, c) while inside the conv domain
        flatten_perm = None     # pending th-order Flatten->Dense fixup
        for i, lc in enumerate(layer_confs):
            cls, cfg = lc["class_name"], lc["config"]
            name = cfg.get("name") or cfg.get("layer_name") or f"layer_{i}"
            if input_type is None:
                it = _keras_input_type(cfg, dim_ordering or "tf")
                if it is not None:
                    input_type = it
                    spatial = _input_spatial(cfg, dim_ordering)
            weights = _layer_weights(wgroup, name)
            if (cls == "Flatten" and dim_ordering == "th"
                    and spatial is not None):
                # Keras-th flattened (C,H,W); we flatten NHWC -> permute
                # the next Dense kernel's input rows
                flatten_perm = _th_flatten_permutation(spatial)
            if cls == "Dense" and flatten_perm is not None:
                weights = dict(weights)
                weights["W"] = np.asarray(weights["W"])[flatten_perm]
                flatten_perm = None
            spatial = _track_spatial(cls, cfg, spatial)
            conv = _convert_layer(cls, cfg, weights, dim_ordering)
            if conv is not None:
                imported.append(conv)

        # Keras commonly ends Dense(linear) + Activation(softmax): fold
        # the trailing Activation into the Dense before output-collapse
        if (len(imported) >= 2
                and isinstance(imported[-1].conf_layer, ActivationLayer)
                and isinstance(imported[-2].conf_layer, DenseLayer)):
            act_layer = imported.pop()
            d = imported[-1].conf_layer
            imported[-1] = _ImportedLayer(
                DenseLayer(n_out=d.n_out,
                           activation=act_layer.conf_layer.activation),
                imported[-1].params)

        # last Dense becomes OutputLayer (reference KerasLoss handling)
        last = imported[-1]
        if isinstance(last.conf_layer, DenseLayer):
            d = last.conf_layer
            imported[-1] = _ImportedLayer(
                OutputLayer(n_out=d.n_out, activation=d.activation or
                            "softmax",
                            loss="mcxent" if (d.activation == "softmax")
                            else "mse"),
                last.params)
        for il in imported:
            builder.layer(il.conf_layer)
        if input_type is not None:
            builder.set_input_type(input_type)
        net = MultiLayerNetwork(builder.build()).init()
        _copy_params_mln(net, imported)
        return net


def _copy_params_mln(net: MultiLayerNetwork, imported) -> None:
    import jax.numpy as jnp
    for i, il in enumerate(imported):
        if il.params:
            for k, v in il.params.items():
                net.params[i][k] = jnp.asarray(
                    np.asarray(v), net.params[i][k].dtype).reshape(
                        net.params[i][k].shape)
        for k, v in (il.state or {}).items():
            if v is not None and k in net.net_state[i]:
                net.net_state[i][k] = jnp.asarray(
                    np.asarray(v), net.net_state[i][k].dtype)


def import_keras_model_and_weights(path: str,
                                   train_config: bool = False
                                   ) -> ComputationGraph:
    """Reference ``KerasModelImport.importKerasModelAndWeights``: Keras 1.x
    functional-API .h5 -> ComputationGraph."""
    import jax.numpy as jnp
    with _open(path) as f:
        conf = _model_config(f)
        if conf["class_name"] not in ("Model", "Functional"):
            raise ValueError("Not a functional-API model")
        mc = conf["config"]
        layer_confs = mc["layers"]
        wgroup = _weights_group(f)

        dim_ordering = None
        for lc in layer_confs:
            dim_ordering = lc["config"].get("dim_ordering", dim_ordering)

        g = (NeuralNetConfiguration.builder().updater("sgd")
             .activation("identity").weight_init("xavier").graph_builder())
        input_names = [l[0] for l in mc["input_layers"]]
        output_names = [l[0] for l in mc["output_layers"]]
        input_types = []
        imported: Dict[str, _ImportedLayer] = {}
        passthrough: Dict[str, str] = {}  # flatten-like no-op mapping
        spatial_of: Dict[str, object] = {}   # name -> (h, w, c) or None
        perm_of: Dict[str, np.ndarray] = {}  # name -> pending th-flat perm
        records: List[tuple] = []  # ("layer"|"vertex", name, obj, in_names)

        def resolve(name: str) -> str:
            while name in passthrough:
                name = passthrough[name]
            return name

        # -- phase 1: parse every layer into records ------------------------
        for lc in layer_confs:
            cls, cfg = lc["class_name"], lc["config"]
            name = lc.get("name") or cfg.get("name")
            inbound = lc.get("inbound_nodes") or []
            # keras1 inbound_nodes: [[[name, node_idx, tensor_idx], ...]]
            raw_in = [x[0] for x in inbound[0]] if inbound else []
            in_names = [resolve(x) for x in raw_in]
            in_spatial = spatial_of.get(raw_in[0]) if raw_in else None
            inherited_perm = perm_of.get(raw_in[0]) if raw_in else None
            if cls == "InputLayer":
                input_types.append(
                    _keras_input_type(cfg, dim_ordering or "tf"))
                spatial_of[name] = _input_spatial(cfg, dim_ordering)
                continue
            if cls == "Flatten":
                passthrough[name] = in_names[0]
                if dim_ordering == "th" and in_spatial is not None:
                    perm_of[name] = _th_flatten_permutation(in_spatial)
                continue
            if cls == "Merge":
                mode = cfg.get("mode", "concat")
                if mode == "concat":
                    records.append(("vertex", name, MergeVertex(),
                                    in_names))
                elif mode == "sum":
                    records.append(("vertex", name,
                                    ElementWiseVertex(op="add"), in_names))
                else:
                    raise ValueError(f"Unsupported Merge mode '{mode}'")
                continue
            weights = _layer_weights(wgroup, name)
            if inherited_perm is not None:
                # a th Flatten upstream still awaits its Dense consumer
                if cls == "Dense":
                    weights = dict(weights)
                    weights["W"] = np.asarray(
                        weights["W"])[inherited_perm]
                elif cls in ("Activation", "Dropout"):
                    perm_of[name] = inherited_perm  # order-preserving
                else:
                    raise ValueError(
                        f"th Flatten feeding a '{cls}' layer is not "
                        "supported (the pending layout permutation "
                        "cannot flow through it)")
            spatial_of[name] = _track_spatial(cls, cfg, in_spatial)
            conv = _convert_layer(cls, cfg, weights, dim_ordering)
            if conv is None:
                passthrough[name] = in_names[0]
                if inherited_perm is not None:
                    perm_of[name] = inherited_perm
                continue
            imported[name] = conv
            records.append(("layer", name, conv, in_names))

        # -- phase 2: output folds ------------------------------------------
        by_name = {r[1]: i for i, r in enumerate(records)}

        def record_of(name):
            i = by_name.get(resolve(name))
            return records[i] if i is not None else None

        for out in output_names:
            rec = record_of(out)
            if rec is None or rec[0] != "layer":
                continue
            kind, name, il, in_names = rec
            # Dense(linear) -> Activation at an output folds into the
            # Dense before output-collapse (same as the sequential path)
            if (isinstance(il.conf_layer, ActivationLayer)
                    and len(in_names) == 1):
                prev = record_of(in_names[0])
                if (prev is not None and prev[0] == "layer"
                        and isinstance(prev[2].conf_layer, DenseLayer)):
                    d = prev[2].conf_layer
                    records[by_name[prev[1]]] = (
                        "layer", prev[1],
                        _ImportedLayer(
                            DenseLayer(n_out=d.n_out,
                                       activation=il.conf_layer.activation),
                            prev[2].params, prev[2].state),
                        prev[3])
                    imported.pop(name, None)
                    imported[prev[1]] = records[by_name[prev[1]]][2]
                    records[by_name[name]] = None
                    passthrough[name] = prev[1]
                    rec = records[by_name[prev[1]]]
                    kind, name, il, in_names = rec
            if isinstance(il.conf_layer, DenseLayer):
                d = il.conf_layer
                folded = _ImportedLayer(
                    OutputLayer(n_out=d.n_out,
                                activation=d.activation or "softmax",
                                loss="mcxent" if d.activation == "softmax"
                                else "mse"), il.params, il.state)
                records[by_name[name]] = ("layer", name, folded, in_names)
                imported[name] = folded

        # -- phase 3: build the graph ---------------------------------------
        for rec in records:
            if rec is None:
                continue
            kind, name, obj, in_names = rec
            if kind == "vertex":
                g.add_vertex(name, obj, *in_names)
            else:
                g.add_layer(name, obj.conf_layer, *in_names)

        g.add_inputs(*input_names)
        g.set_outputs(*[resolve(n) for n in output_names])
        if all(t is not None for t in input_types) and input_types:
            g.set_input_types(*input_types)
        cg = ComputationGraph(g.build()).init()
        for name, il in imported.items():
            if il.params:
                for k, v in il.params.items():
                    cg.params[name][k] = jnp.asarray(
                        np.asarray(v),
                        cg.params[name][k].dtype).reshape(
                            cg.params[name][k].shape)
            for k, v in (il.state or {}).items():
                if v is not None and k in cg.net_state[name]:
                    cg.net_state[name][k] = jnp.asarray(
                        np.asarray(v), cg.net_state[name][k].dtype)
        return cg


class KerasModelImport:
    """Namespace mirroring the reference entry points
    (``KerasModelImport.java:48-156``)."""

    import_keras_sequential_model_and_weights = staticmethod(
        import_keras_sequential_model_and_weights)
    import_keras_model_and_weights = staticmethod(
        import_keras_model_and_weights)
