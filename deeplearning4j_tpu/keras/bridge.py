"""Keras RPC training-backend bridge.

TPU-native equivalent of the reference's ``deeplearning4j-keras`` module
(459 LoC): a py4j RPC server (``Server.java``) exposing
``DeepLearning4jEntryPoint.java`` so an external Keras process can use
this framework as its training backend, with
``HDF5MiniBatchDataSetIterator.java`` reading per-minibatch HDF5 files
from a directory.

py4j isn't in this image, and the contract is transport-agnostic anyway:
the bridge speaks newline-delimited JSON over TCP
(``{"id", "method", "params"}`` -> ``{"id", "result" | "error"}``), which
any Keras-side caller can produce with the stdlib.  Methods mirror the
reference entry point:

- ``sequential_fit(model_file_path, train_dir, nb_epoch, batch_size)`` —
  import a Keras h5, train on an HDF5 minibatch directory, return the
  final score (the reference's ``fit`` call from the Keras callback).
- ``import_model(path)`` / ``predict`` / ``evaluate`` / ``save`` — model
  handle lifecycle around the importer.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from typing import Dict, List, Optional

import numpy as np

from ..datasets.dataset import DataSet
from ..datasets.iterators import DataSetIterator
from .keras_model_import import (import_keras_model_and_weights,
                                 import_keras_sequential_model_and_weights)


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """Directory of per-minibatch ``.h5`` files, each holding ``features``
    and (optionally) ``labels`` datasets, iterated in sorted filename
    order (reference ``HDF5MiniBatchDataSetIterator.java``)."""

    def __init__(self, directory: str):
        import h5py                      # baked into the image
        self._h5py = h5py
        self.directory = directory
        self.paths: List[str] = sorted(
            os.path.join(directory, f) for f in os.listdir(directory)
            if f.endswith((".h5", ".hdf5")))
        if not self.paths:
            raise ValueError(f"no .h5 minibatch files in {directory}")
        self._pos = 0

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        with self._h5py.File(self.paths[0], "r") as f:
            return int(f["features"].shape[0])

    def __next__(self) -> DataSet:
        if self._pos >= len(self.paths):
            raise StopIteration
        path = self.paths[self._pos]
        self._pos += 1
        with self._h5py.File(path, "r") as f:
            feats = np.asarray(f["features"], np.float32)
            labels = (np.asarray(f["labels"], np.float32)
                      if "labels" in f else None)
        ds = DataSet(feats, labels)
        return self._pre(ds)


class KerasBridgeEntryPoint:
    """The RPC-callable surface (reference
    ``DeepLearning4jEntryPoint.java``)."""

    def __init__(self):
        self._models: Dict[str, object] = {}
        self._next_id = 0
        self._lock = threading.Lock()   # ThreadingTCPServer: one thread
        #                                 per connection shares this entry

    # -- the reference's one-shot fit call ---------------------------------
    def sequential_fit(self, model_file_path: str, train_dir: str,
                       nb_epoch: int = 1) -> dict:
        net = import_keras_sequential_model_and_weights(
            model_file_path, train_config=True)
        it = HDF5MiniBatchDataSetIterator(train_dir)
        net.fit(it, epochs=int(nb_epoch))
        handle = self._register(net)
        return {"model_id": handle, "score": float(net.score())}

    # -- handle lifecycle --------------------------------------------------
    def _register(self, net) -> str:
        with self._lock:
            handle = f"model_{self._next_id}"
            self._next_id += 1
            self._models[handle] = net
        return handle

    def _get(self, model_id: str):
        if model_id not in self._models:
            raise KeyError(f"unknown model_id {model_id!r}")
        return self._models[model_id]

    def import_model(self, path: str, model_type: str = "sequential") -> dict:
        if model_type == "sequential":
            net = import_keras_sequential_model_and_weights(path)
        elif model_type == "functional":
            net = import_keras_model_and_weights(path)
        else:
            raise ValueError(f"unknown model_type {model_type!r}")
        return {"model_id": self._register(net)}

    def fit(self, model_id: str, train_dir: str, nb_epoch: int = 1) -> dict:
        net = self._get(model_id)
        net.fit(HDF5MiniBatchDataSetIterator(train_dir),
                epochs=int(nb_epoch))
        return {"score": float(net.score())}

    def predict(self, model_id: str, features: list) -> dict:
        net = self._get(model_id)
        out = net.output(np.asarray(features, np.float32))
        return {"output": np.asarray(out).tolist()}

    def evaluate(self, model_id: str, data_dir: str) -> dict:
        net = self._get(model_id)
        ev = net.evaluate(HDF5MiniBatchDataSetIterator(data_dir))
        return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    def save(self, model_id: str, path: str) -> dict:
        from ..utils import model_serializer
        model_serializer.write_model(self._get(model_id), path)
        return {"path": path}


class _BridgeHandler(socketserver.StreamRequestHandler):
    def handle(self):
        entry: KerasBridgeEntryPoint = self.server.entry  # type: ignore
        for raw in self.rfile:
            req = None                  # fresh per line: no stale ids
            try:
                req = json.loads(raw.decode("utf-8"))
                method = req.get("method", "")
                if method.startswith("_") or not hasattr(entry, method):
                    raise AttributeError(f"unknown method {method!r}")
                result = getattr(entry, method)(**req.get("params", {}))
                resp = {"id": req.get("id"), "result": result}
            except Exception as e:
                resp = {"id": (req.get("id")
                               if isinstance(req, dict) else None),
                        "error": repr(e)}
            self.wfile.write((json.dumps(resp) + "\n").encode("utf-8"))
            self.wfile.flush()


class KerasBridgeServer:
    """The RPC server (reference ``Server.java``): ``port=0`` binds an
    ephemeral port exposed as ``.port``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.entry = KerasBridgeEntryPoint()
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _BridgeHandler)
        self._server.daemon_threads = True
        self._server.entry = self.entry           # type: ignore
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "KerasBridgeServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "KerasBridgeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class KerasBridgeClient:
    """Minimal client for the JSON-over-TCP protocol (what the Keras-side
    shim uses; also exercises the wire format in tests)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._fh = self._sock.makefile("rwb")
        self._next_id = 0

    def call(self, method: str, **params):
        req_id = self._next_id
        self._next_id += 1
        self._fh.write((json.dumps(
            {"id": req_id, "method": method, "params": params}) + "\n")
            .encode("utf-8"))
        self._fh.flush()
        resp = json.loads(self._fh.readline().decode("utf-8"))
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["result"]

    def close(self) -> None:
        self._fh.close()
        self._sock.close()
