"""Vocabulary: VocabWord, VocabCache, VocabConstructor, Huffman coding.

TPU-native equivalents of the reference's
``models/word2vec/wordstore/inmemory/AbstractCache.java`` (446 LoC),
``models/word2vec/wordstore/VocabConstructor.java`` (572 LoC — corpus scan,
min-word-frequency prune, special-token retention) and
``models/word2vec/Huffman.java`` (hierarchical-softmax tree: binary codes +
inner-node point paths per word).

Host-side data structures; the device kernels consume the integer
codes/points arrays built here.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence


@dataclasses.dataclass
class VocabWord:
    """Reference ``models/word2vec/VocabWord.java``: frequency-weighted
    vocab element with Huffman code assignment."""

    word: str
    element_frequency: float = 1.0
    index: int = -1
    # Huffman assignment (reference VocabWord.codes / .points)
    codes: List[int] = dataclasses.field(default_factory=list)
    points: List[int] = dataclasses.field(default_factory=list)
    # ParagraphVectors: label elements are excluded from subsampling
    is_label: bool = False

    def increment(self, by: float = 1.0) -> None:
        self.element_frequency += by


class VocabCache:
    """Reference ``wordstore/inmemory/AbstractCache.java``: word <-> index
    <-> frequency store."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_count = 0.0

    # -- population --------------------------------------------------------
    def add_token(self, word: VocabWord) -> None:
        existing = self._words.get(word.word)
        if existing is not None:
            existing.increment(word.element_frequency)
        else:
            self._words[word.word] = word

    def update_words_occurrences(self) -> None:
        self.total_word_count = sum(w.element_frequency
                                    for w in self._words.values())

    def finalize_vocab(self) -> None:
        """Assign indices by descending frequency (ties: lexicographic),
        like the reference's sorted vocab."""
        self._by_index = sorted(self._words.values(),
                                key=lambda w: (-w.element_frequency, w.word))
        for i, w in enumerate(self._by_index):
            w.index = i
        self.update_words_occurrences()

    def remove(self, word: str) -> None:
        self._words.pop(word, None)

    # -- lookups (reference AbstractCache API) -----------------------------
    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_frequency(self, word: str) -> float:
        w = self._words.get(word)
        return w.element_frequency if w else 0.0

    def index_of(self, word: str) -> int:
        w = self._words.get(word)
        return w.index if w else -1

    def word_at_index(self, index: int) -> Optional[str]:
        if 0 <= index < len(self._by_index):
            return self._by_index[index].word
        return None

    def element_at_index(self, index: int) -> Optional[VocabWord]:
        if 0 <= index < len(self._by_index):
            return self._by_index[index]
        return None

    def num_words(self) -> int:
        return len(self._words)

    def words(self) -> List[str]:
        return [w.word for w in self._by_index] if self._by_index \
            else list(self._words)

    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index) if self._by_index \
            else list(self._words.values())

    def __len__(self) -> int:
        return len(self._words)


class VocabConstructor:
    """Corpus scan -> pruned, index-assigned VocabCache (reference
    ``VocabConstructor.java``: ``buildJointVocabulary``, min-word-frequency
    prune at the end of the scan)."""

    def __init__(self, min_word_frequency: int = 1,
                 special_tokens: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.special_tokens = set(special_tokens)

    def build_vocab(self, sequences: Iterable[Sequence[str]],
                    cache: Optional[VocabCache] = None) -> VocabCache:
        cache = cache or VocabCache()
        counts: Counter = Counter()
        n_sequences = 0
        for seq in sequences:
            n_sequences += 1
            counts.update(seq)
        for word, count in counts.items():
            if count >= self.min_word_frequency or word in \
                    self.special_tokens:
                cache.add_token(VocabWord(word, float(count)))
        cache.finalize_vocab()
        cache.sequence_count = n_sequences
        return cache


def huffman_codes(frequencies: Sequence[int], max_code_length: int = 40):
    """Huffman codes + syn1 point paths for arbitrary frequencies
    (reference ``models/word2vec/Huffman.java``; also backs the graph
    tier's degree tree, ``deepwalk/GraphHuffman.java``).

    Standard word2vec construction: two frequency-sorted arrays merged
    bottom-up; each element's ``codes`` are its binary branch decisions
    from root to leaf, ``points`` the inner-node indices along that path
    (offsets into syn1: root = n-2, then top-down, leaf excluded).

    Input order is arbitrary — a stable descending-frequency permutation is
    applied internally and inverted on output.  Returns a list of
    ``(codes, points)`` pairs, one per input index.
    """
    freqs = [int(f) for f in frequencies]
    n = len(freqs)
    if n == 0:
        return []
    if n == 1:
        return [([], [])]
    # classic word2vec layout expects leaves sorted descending by freq
    perm = sorted(range(n), key=lambda i: -freqs[i])
    inv = [0] * n
    for sorted_pos, orig in enumerate(perm):
        inv[orig] = sorted_pos
    # count array: leaves then inner nodes
    count = [freqs[perm[i]] for i in range(n)] + [int(1e15)] * (n - 1)
    binary = [0] * (2 * n - 1)
    parent = [0] * (2 * n - 1)
    pos1, pos2 = n - 1, n
    for i in range(n - 1):
        # pick two smallest
        if pos1 >= 0 and count[pos1] < count[pos2]:
            min1, pos1 = pos1, pos1 - 1
        else:
            min1, pos2 = pos2, pos2 + 1
        if pos1 >= 0 and (pos2 >= 2 * n - 1 or count[pos1] < count[pos2]):
            min2, pos1 = pos1, pos1 - 1
        else:
            min2, pos2 = pos2, pos2 + 1
        count[n + i] = count[min1] + count[min2]
        parent[min1] = n + i
        parent[min2] = n + i
        binary[min2] = 1
    out = []
    for orig in range(n):
        codes: List[int] = []
        points: List[int] = []
        node = inv[orig]
        while node != 2 * n - 2:
            codes.append(binary[node])
            points.append(node)
            node = parent[node]
        codes.reverse()
        points.reverse()
        # After reversal ``points`` is [childOfRoot, ..., parentOfLeaf,
        # leaf].  The syn1 rows visited during training (word2vec layout,
        # reference Huffman.java) are the root (inner-node id n-2) followed
        # by the path inner nodes top-down, excluding the leaf; inner-node
        # ids shift down by n (the leaf count).
        codes = codes[:max_code_length]
        out.append((codes,
                    ([n - 2] + [p - n for p in points[:-1]])[:len(codes)]))
    return out


def build_huffman_tree(cache: VocabCache, max_code_length: int = 40) -> None:
    """Assign Huffman codes/points to every vocab word (reference
    ``models/word2vec/Huffman.java``) via :func:`huffman_codes`."""
    words = cache.vocab_words()
    if not words:
        return
    assigned = huffman_codes([int(w.element_frequency) for w in words],
                             max_code_length)
    for w, (codes, points) in zip(words, assigned):
        w.codes = codes
        w.points = points
    cache.huffman_built = True
