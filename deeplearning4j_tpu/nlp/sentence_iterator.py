"""Sentence / document iterators.

TPU-native equivalents of the reference's
``text/sentenceiterator/`` (``SentenceIterator`` SPI, ``BasicLineIterator``,
``CollectionSentenceIterator``, ``FileSentenceIterator``,
``LineSentenceIterator``) and ``text/documentiterator/`` (LabelAware
variants, ``LabelsSource``).  Host-side IO only.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    """Reference ``sentenceiterator/SentenceIterator.java``."""

    def __init__(self):
        self._preprocessor: Optional[SentencePreProcessor] = None

    def set_pre_processor(self, pre: SentencePreProcessor) -> None:
        self._preprocessor = pre

    def _apply(self, sentence: str) -> str:
        return (self._preprocessor.pre_process(sentence)
                if self._preprocessor else sentence)

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    """Reference ``CollectionSentenceIterator.java``."""

    def __init__(self, sentences: Sequence[str]):
        super().__init__()
        self._sentences = list(sentences)
        self._pos = 0

    def next_sentence(self) -> str:
        s = self._apply(self._sentences[self._pos])
        self._pos += 1
        return s

    def has_next(self) -> bool:
        return self._pos < len(self._sentences)

    def reset(self) -> None:
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference
    ``BasicLineIterator.java``)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._fh = None
        self._next_line: Optional[str] = None
        self.reset()

    def _advance(self) -> None:
        line = self._fh.readline()
        self._next_line = line.rstrip("\n") if line else None

    def next_sentence(self) -> str:
        s = self._apply(self._next_line)
        self._advance()
        return s

    def has_next(self) -> bool:
        return self._next_line is not None

    def reset(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._fh = open(self.path, "r", encoding="utf-8")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """All lines of every file under a directory (reference
    ``FileSentenceIterator.java``)."""

    def __init__(self, path: str):
        super().__init__()
        self.files = ([os.path.join(path, f) for f in sorted(os.listdir(path))]
                      if os.path.isdir(path) else [path])
        self.reset()

    def _load(self) -> None:
        self._lines: List[str] = []
        for f in self.files:
            with open(f, "r", encoding="utf-8") as fh:
                self._lines.extend(line.rstrip("\n") for line in fh)
        self._pos = 0

    def next_sentence(self) -> str:
        s = self._apply(self._lines[self._pos])
        self._pos += 1
        return s

    def has_next(self) -> bool:
        return self._pos < len(self._lines)

    def reset(self) -> None:
        self._load()


class LabelsSource:
    """Reference ``documentiterator/LabelsSource.java``: generates or stores
    document labels."""

    def __init__(self, template: str = "DOC_",
                 labels: Optional[Sequence[str]] = None):
        self.template = template
        self._labels = list(labels) if labels is not None else []
        self._counter = 0
        self._generated = labels is None

    def next_label(self) -> str:
        if self._generated:
            label = f"{self.template}{self._counter}"
            self._counter += 1
            self._labels.append(label)
            return label
        label = self._labels[self._counter]
        self._counter += 1
        return label

    def get_labels(self) -> List[str]:
        return list(self._labels)

    def reset(self) -> None:
        self._counter = 0


class LabelledDocument:
    """Reference ``documentiterator/LabelledDocument.java``."""

    def __init__(self, content: str, label: Optional[str] = None):
        self.content = content
        self.label = label


class LabelAwareIterator:
    """Reference ``documentiterator/LabelAwareIterator.java``."""

    def has_next_document(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def get_labels_source(self) -> LabelsSource:
        raise NotImplementedError

    def __iter__(self) -> Iterator[LabelledDocument]:
        self.reset()
        while self.has_next_document():
            yield self.next_document()


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wraps (content, label) pairs or plain sentences with generated
    labels (reference ``BasicLabelAwareIterator``)."""

    def __init__(self, documents: Sequence, labels_source:
                 Optional[LabelsSource] = None):
        self._docs = list(documents)
        self._labels_source = labels_source or LabelsSource()
        self._pos = 0
        self._resolved: List[LabelledDocument] = []
        for doc in self._docs:
            if isinstance(doc, LabelledDocument):
                self._resolved.append(doc)
            elif isinstance(doc, tuple):
                self._resolved.append(LabelledDocument(doc[0], doc[1]))
            else:
                self._resolved.append(
                    LabelledDocument(doc, self._labels_source.next_label()))

    def has_next_document(self) -> bool:
        return self._pos < len(self._resolved)

    def next_document(self) -> LabelledDocument:
        d = self._resolved[self._pos]
        self._pos += 1
        return d

    def reset(self) -> None:
        self._pos = 0

    def get_labels_source(self) -> LabelsSource:
        return self._labels_source
