"""Language packs: Japanese / Korean tokenizers + UIMA-style pipeline.

TPU-native equivalents of the reference's NLP language modules:

- ``deeplearning4j-nlp-japanese`` vendors the Kuromoji morphological
  analyzer (55 files incl. its dictionary).  Shipping a vendored
  dictionary is out of scope here; :class:`JapaneseTokenizerFactory` is
  an honest rule-based segmenter: script-run segmentation (kanji /
  hiragana / katakana / latin / digit runs — the backbone of Japanese
  tokenization) refined by a longest-match split of common function
  words (particles, copulas) inside hiragana runs.  Same SPI, swap in a
  dictionary tokenizer for production morphology.
- ``deeplearning4j-nlp-korean`` wraps twitter-korean-text;
  :class:`KoreanTokenizerFactory` does whitespace segmentation with
  optional josa (particle-suffix) stripping — the normalization that
  wrapper is used for in embedding pipelines.
- ``deeplearning4j-nlp-uima`` drives UIMA ``AnalysisEngine``s
  (tokenizer + sentence segmenter annotators over a CAS).  The
  :class:`AnalysisEngine` here is the same shape: annotators mutate a
  :class:`CAS` (text + typed annotation spans) in pipeline order;
  :class:`UimaTokenizerFactory` and :class:`UimaSentenceIterator`
  expose the standard tokenizer/sentence SPIs on top.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from .sentence_iterator import SentenceIterator
from .tokenization import Tokenizer, TokenizerFactory


# --------------------------------------------------------------- japanese
_HIRAGANA = ("぀", "ゟ")
_KATAKANA = ("゠", "ヿ")
_CJK = ("一", "鿿")

# Common function words (particles, copulas, auxiliaries) for the
# longest-match split inside hiragana runs; ordered scan tries longer
# entries first.
_JA_FUNCTION_WORDS = sorted(
    ["から", "まで", "です", "ます", "でした", "ました", "だった",
     "では", "には", "とは", "は", "が", "を", "に", "で", "と",
     "の", "も", "へ", "や", "ね", "よ", "か", "だ", "な"],
    key=len, reverse=True)


def _script(ch: str) -> str:
    if _HIRAGANA[0] <= ch <= _HIRAGANA[1]:
        return "hiragana"
    if _KATAKANA[0] <= ch <= _KATAKANA[1]:
        return "katakana"
    if _CJK[0] <= ch <= _CJK[1]:
        return "kanji"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def _split_hiragana_run(run: str) -> List[str]:
    """Longest-match function-word segmentation of a hiragana run: peel
    known particles off the front; unknown prefixes accumulate until a
    match starts."""
    out: List[str] = []
    buf = ""
    i = 0
    while i < len(run):
        for w in _JA_FUNCTION_WORDS:
            if run.startswith(w, i):
                if buf:
                    out.append(buf)
                    buf = ""
                out.append(w)
                i += len(w)
                break
        else:
            buf += run[i]
            i += 1
    if buf:
        out.append(buf)
    return out


def japanese_tokenize(text: str) -> List[str]:
    """Script-run segmentation + hiragana function-word splitting."""
    runs: List[Tuple[str, str]] = []
    for ch in text:
        s = _script(ch)
        if runs and runs[-1][0] == s:
            runs[-1] = (s, runs[-1][1] + ch)
        else:
            runs.append((s, ch))
    tokens: List[str] = []
    for s, run in runs:
        if s in ("space", "punct"):
            continue
        if s == "hiragana":
            tokens.extend(_split_hiragana_run(run))
        else:
            tokens.append(run)
    return tokens


class JapaneseTokenizerFactory(TokenizerFactory):
    """Reference ``JapaneseTokenizerFactory`` (Kuromoji role).

    ``mode="lattice"`` (default) runs the dictionary lattice + Viterbi
    tokenizer (``nlp/lattice.py`` — the actual Kuromoji algorithm over a
    bundled dictionary); ``mode="heuristic"`` keeps the script-run
    segmenter for dictionary-free use."""

    def __init__(self, mode: str = "lattice", dictionary=None):
        super().__init__()
        if mode not in ("lattice", "heuristic"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self._lattice = None
        if mode == "lattice":
            from .lattice import LatticeTokenizer
            self._lattice = LatticeTokenizer(entries=dictionary)

    def create(self, text: str) -> Tokenizer:
        tokens = (self._lattice.tokenize(text) if self._lattice is not None
                  else japanese_tokenize(text))
        return Tokenizer(tokens, self._preprocessor)


# ----------------------------------------------------------------- korean
_KO_JOSA = sorted(
    ["은", "는", "이", "가", "을", "를", "의", "에", "에서", "에게",
     "으로", "로", "와", "과", "도", "만", "부터", "까지", "처럼",
     "보다", "한테"],
    key=len, reverse=True)


def _strip_josa(token: str) -> str:
    """Strip one trailing particle if the stem stays non-empty Hangul."""
    for josa in _KO_JOSA:
        if token.endswith(josa) and len(token) > len(josa):
            return token[:-len(josa)]
    return token


def korean_tokenize(text: str, strip_josa: bool = True) -> List[str]:
    tokens = []
    for raw in re.findall(r"[가-힣]+|[A-Za-z0-9]+", text):
        tokens.append(_strip_josa(raw) if strip_josa
                      and "가" <= raw[0] <= "힣" else raw)
    return tokens


class KoreanTokenizerFactory(TokenizerFactory):
    """Reference ``KoreanTokenizerFactory`` (twitter-korean-text role):
    Hangul/alnum segmentation with josa stripping."""

    def __init__(self, strip_josa: bool = True):
        super().__init__()
        self.strip_josa = strip_josa

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(korean_tokenize(text, self.strip_josa),
                         self._preprocessor)


# ------------------------------------------------------------------- uima
class CAS:
    """Common Analysis Structure: document text + typed annotation spans
    (reference UIMA ``CAS``/``JCas`` role, minimally)."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: Dict[str, List[Tuple[int, int]]] = {}

    def add(self, type_name: str, begin: int, end: int) -> None:
        self.annotations.setdefault(type_name, []).append((begin, end))

    def covered(self, type_name: str) -> List[str]:
        return [self.text[b:e]
                for b, e in self.annotations.get(type_name, [])]


class Annotator:
    """One analysis step (reference UIMA ``AnalysisComponent``)."""

    def process(self, cas: CAS) -> None:
        raise NotImplementedError


class SentenceAnnotator(Annotator):
    """Sentence spans by terminator punctuation (the SentenceDetector
    role)."""

    _BOUNDARY = re.compile(r"[.!?。！？]+\s*")

    def process(self, cas: CAS) -> None:
        start = 0
        for m in self._BOUNDARY.finditer(cas.text):
            if m.end() > start:
                span = cas.text[start:m.start()].strip()
                if span:
                    b = cas.text.index(span, start)
                    cas.add("sentence", b, b + len(span))
            start = m.end()
        tail = cas.text[start:].strip()
        if tail:
            b = cas.text.index(tail, start)
            cas.add("sentence", b, b + len(tail))


class TokenAnnotator(Annotator):
    """Token spans (the WhitespaceTokenizer annotator role)."""

    _TOKEN = re.compile(r"\S+")

    def process(self, cas: CAS) -> None:
        for m in self._TOKEN.finditer(cas.text):
            cas.add("token", m.start(), m.end())


class AnalysisEngine:
    """Annotator pipeline (reference UIMA ``AnalysisEngine`` /
    ``AggregateAnalysisEngine``)."""

    def __init__(self, annotators: Sequence[Annotator]):
        self.annotators = list(annotators)

    def process(self, text: str) -> CAS:
        cas = CAS(text)
        for a in self.annotators:
            a.process(cas)
        return cas


class UimaTokenizerFactory(TokenizerFactory):
    """Reference ``UimaTokenizerFactory``: tokens come from the engine's
    ``token`` annotations."""

    def __init__(self, engine: Optional[AnalysisEngine] = None):
        super().__init__()
        self.engine = engine or AnalysisEngine([TokenAnnotator()])

    def create(self, text: str) -> Tokenizer:
        cas = self.engine.process(text)
        return Tokenizer(cas.covered("token"), self._preprocessor)


class UimaSentenceIterator(SentenceIterator):
    """Reference ``UimaSentenceIterator``: documents -> sentence spans via
    the engine's ``sentence`` annotations."""

    def __init__(self, documents: Sequence[str],
                 engine: Optional[AnalysisEngine] = None):
        super().__init__()
        self.documents = list(documents)
        self.engine = engine or AnalysisEngine([SentenceAnnotator()])
        self._sentences: List[str] = []
        self._build()
        self._pos = 0

    def _build(self) -> None:
        self._sentences = []
        for doc in self.documents:
            self._sentences.extend(self.engine.process(doc)
                                   .covered("sentence"))

    def has_next(self) -> bool:
        return self._pos < len(self._sentences)

    def next_sentence(self) -> str:
        s = self._sentences[self._pos]
        self._pos += 1
        return self._apply(s)

    def reset(self) -> None:
        self._pos = 0
