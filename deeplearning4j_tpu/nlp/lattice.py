"""Dictionary-based lattice tokenizer for Japanese (the Kuromoji role).

The reference vendors the full Kuromoji morphological analyzer
(``deeplearning4j-nlp-japanese``, 55 files: trie-backed dictionary,
lattice construction, Viterbi with word + connection costs, script-based
unknown-word handling).  This module implements the same algorithm at a
bundled-dictionary scale:

- :data:`DICTIONARY` — a few hundred high-frequency entries
  (surface, POS, cost); enough to prove the algorithm end to end.
  Production use loads a bigger dictionary through the same
  :class:`LatticeTokenizer` constructor.
- :class:`Trie` — common-prefix search over surfaces (Kuromoji's
  DoubleArrayTrie role).
- :class:`LatticeTokenizer` — per-position dictionary + unknown-word
  node generation, then Viterbi over (position, POS) states with word
  costs and a coarse POS-pair connection matrix.  Unknown words get
  script-dependent costs (katakana runs cheap as single tokens — they
  are usually loanword nouns; hiragana unknowns prefer short — real
  hiragana content words are in the dictionary; kanji runs moderate —
  compounds are fine as single tokens).

Segmentation quality goal (tested): all-hiragana sentences that the
script-run heuristic in ``lang.py`` cannot split
(すもももももももものうち, わたしはにほんごをべんきょうします, ...).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

# (surface, pos, cost); lower cost = preferred.  POS classes: noun,
# pron, verb (conjugated surface forms included — this dictionary stores
# surfaces, not lemmas, like Kuromoji's conjugated entries), adj,
# particle, aux, adv, prefix, suffix, conj (conjunction), num.
_D: List[Tuple[str, str, int]] = [
    # --- particles (case/topic/binding; compounds as own entries) ---
    ("は", "particle", 1700), ("が", "particle", 1600),
    ("を", "particle", 1600), ("に", "particle", 1700),
    ("で", "particle", 1800), ("と", "particle", 1800),
    ("の", "particle", 1600), ("も", "particle", 1800),
    ("へ", "particle", 1800), ("や", "particle", 2000),
    ("か", "particle", 2100), ("ね", "particle", 2200),
    ("よ", "particle", 2200), ("な", "particle", 2300),
    ("から", "particle", 1900), ("まで", "particle", 1900),
    ("より", "particle", 2100), ("では", "particle", 2100),
    ("には", "particle", 2000), ("とは", "particle", 2100),
    ("への", "particle", 2100), ("でも", "particle", 2100),
    ("だけ", "particle", 2000), ("しか", "particle", 2100),
    ("ばかり", "particle", 2200), ("ながら", "particle", 2200),
    ("ので", "particle", 2000), ("のに", "particle", 2100),
    ("けど", "particle", 2100), ("けれど", "particle", 2200),
    # --- auxiliaries / polite endings / copulas ---
    ("です", "aux", 1800), ("でした", "aux", 1900),
    ("ます", "aux", 1700), ("ました", "aux", 1800),
    ("ません", "aux", 1900), ("ましょう", "aux", 2000),
    ("だ", "aux", 2000), ("だった", "aux", 2000),
    ("である", "aux", 2100), ("じゃない", "aux", 2100),
    ("ない", "aux", 1900), ("なかった", "aux", 2000),
    ("たい", "aux", 2000), ("たかった", "aux", 2100),
    ("られる", "aux", 2100), ("れる", "aux", 2200),
    ("させる", "aux", 2200), ("せる", "aux", 2300),
    ("ている", "aux", 1900), ("ています", "aux", 1900),
    ("ていた", "aux", 2000), ("てある", "aux", 2200),
    ("ておく", "aux", 2200), ("てしまう", "aux", 2200),
    ("ください", "aux", 1900),
    ("なさい", "aux", 2100), ("でしょう", "aux", 2000),
    ("だろう", "aux", 2100), ("かもしれない", "aux", 2200),
    ("はず", "aux", 2200), ("べき", "aux", 2300),
    # --- pronouns / demonstratives ---
    ("わたし", "pron", 2200), ("私", "pron", 2000),
    ("あなた", "pron", 2300), ("かれ", "pron", 2500),
    ("彼", "pron", 2100), ("彼女", "pron", 2100),
    ("これ", "pron", 2100), ("それ", "pron", 2100),
    ("あれ", "pron", 2200), ("どれ", "pron", 2300),
    ("ここ", "pron", 2100), ("そこ", "pron", 2200),
    ("あそこ", "pron", 2300), ("どこ", "pron", 2200),
    ("この", "adn", 2000), ("その", "adn", 2000),
    ("あの", "adn", 2100), ("どの", "adn", 2200),
    ("なに", "pron", 2200), ("何", "pron", 2100),
    ("だれ", "pron", 2300), ("誰", "pron", 2200),
    ("いつ", "pron", 2300), ("みんな", "pron", 2400),
    # --- common nouns (hiragana + kanji surfaces) ---
    ("うち", "noun", 2500), ("ひと", "noun", 2600), ("人", "noun", 2200),
    ("こと", "noun", 2300), ("もの", "noun", 2400), ("物", "noun", 2400),
    ("とき", "noun", 2400), ("時", "noun", 2300), ("ところ", "noun", 2500),
    ("所", "noun", 2500), ("日", "noun", 2300), ("年", "noun", 2300),
    ("月", "noun", 2400), ("今日", "noun", 2200), ("明日", "noun", 2300),
    ("昨日", "noun", 2300), ("今", "noun", 2300), ("いま", "noun", 2600),
    ("すもも", "noun", 2600), ("もも", "noun", 2600), ("桃", "noun", 2400),
    ("にほんご", "noun", 2400), ("日本語", "noun", 2100),
    ("にほん", "noun", 2500), ("日本", "noun", 2100),
    ("東京", "noun", 2200), ("大学", "noun", 2200),
    ("学生", "noun", 2200), ("がくせい", "noun", 2600),
    ("先生", "noun", 2200), ("せんせい", "noun", 2600),
    ("学校", "noun", 2200), ("がっこう", "noun", 2600),
    ("会社", "noun", 2200), ("かいしゃ", "noun", 2600),
    ("仕事", "noun", 2200), ("しごと", "noun", 2600),
    ("電車", "noun", 2300), ("でんしゃ", "noun", 2700),
    ("車", "noun", 2400), ("くるま", "noun", 2700),
    ("家", "noun", 2300), ("いえ", "noun", 2700),
    ("水", "noun", 2400), ("みず", "noun", 2700),
    ("お金", "noun", 2300), ("おかね", "noun", 2700),
    ("ご飯", "noun", 2300), ("ごはん", "noun", 2600),
    ("きもの", "noun", 2700), ("着物", "noun", 2300),
    ("はきもの", "noun", 2750), ("履物", "noun", 2400),
    ("ほん", "noun", 2700), ("本", "noun", 2300),
    ("映画", "noun", 2300), ("えいが", "noun", 2700),
    ("音楽", "noun", 2300), ("おんがく", "noun", 2700),
    ("友達", "noun", 2300), ("ともだち", "noun", 2600),
    ("家族", "noun", 2300), ("かぞく", "noun", 2700),
    ("天気", "noun", 2300), ("てんき", "noun", 2700),
    ("雨", "noun", 2400), ("あめ", "noun", 2700),
    ("朝", "noun", 2400), ("あさ", "noun", 2700),
    ("夜", "noun", 2400), ("よる", "noun", 2700),
    ("部屋", "noun", 2300), ("へや", "noun", 2700),
    ("写真", "noun", 2300), ("しゃしん", "noun", 2700),
    ("問題", "noun", 2300), ("もんだい", "noun", 2700),
    ("質問", "noun", 2300), ("しつもん", "noun", 2700),
    ("答え", "noun", 2400), ("こたえ", "noun", 2700),
    ("言葉", "noun", 2300), ("ことば", "noun", 2600),
    ("名前", "noun", 2300), ("なまえ", "noun", 2600),
    ("世界", "noun", 2300), ("せかい", "noun", 2700),
    ("国", "noun", 2400), ("くに", "noun", 2700),
    ("町", "noun", 2400), ("まち", "noun", 2700),
    ("駅", "noun", 2300), ("えき", "noun", 2700),
    ("店", "noun", 2400), ("みせ", "noun", 2700),
    ("道", "noun", 2400), ("みち", "noun", 2700),
    ("海", "noun", 2400), ("うみ", "noun", 2700),
    ("山", "noun", 2400), ("やま", "noun", 2700),
    ("空", "noun", 2400), ("そら", "noun", 2700),
    ("花", "noun", 2400), ("はな", "noun", 2700),
    ("犬", "noun", 2400), ("いぬ", "noun", 2700),
    ("猫", "noun", 2400), ("ねこ", "noun", 2700),
    ("魚", "noun", 2400), ("さかな", "noun", 2700),
    ("肉", "noun", 2400), ("にく", "noun", 2700),
    ("野菜", "noun", 2400), ("やさい", "noun", 2700),
    ("果物", "noun", 2400), ("くだもの", "noun", 2700),
    ("お茶", "noun", 2400), ("おちゃ", "noun", 2700),
    ("子供", "noun", 2300), ("こども", "noun", 2600),
    ("男", "noun", 2400), ("おとこ", "noun", 2700),
    ("女", "noun", 2400), ("おんな", "noun", 2700),
    ("目", "noun", 2500), ("手", "noun", 2500), ("足", "noun", 2500),
    ("頭", "noun", 2500), ("心", "noun", 2500), ("気", "noun", 2500),
    ("話", "noun", 2400), ("はなし", "noun", 2700),
    ("勉強", "noun", 2300), ("べんきょう", "noun", 2600),
    ("旅行", "noun", 2300), ("りょこう", "noun", 2700),
    ("料理", "noun", 2300), ("りょうり", "noun", 2700),
    ("買い物", "noun", 2300), ("かいもの", "noun", 2700),
    ("電話", "noun", 2300), ("でんわ", "noun", 2700),
    ("時間", "noun", 2300), ("じかん", "noun", 2700),
    ("時計", "noun", 2400), ("とけい", "noun", 2700),
    ("今年", "noun", 2400), ("ことし", "noun", 2700),
    ("去年", "noun", 2400), ("きょねん", "noun", 2700),
    ("来年", "noun", 2400), ("らいねん", "noun", 2700),
    # --- verbs (common surfaces incl. conjugations) ---
    ("する", "verb", 2000), ("し", "verb", 2400), ("します", "verb", 2100),
    ("して", "verb", 2200), ("した", "verb", 2200),
    ("いる", "verb", 2200), ("い", "verb", 2800), ("いた", "verb", 2500),
    ("ある", "verb", 2200), ("あった", "verb", 2400),
    ("あります", "verb", 2200), ("いく", "verb", 2400),
    ("行く", "verb", 2200), ("行った", "verb", 2300),
    ("行きます", "verb", 2300), ("いきます", "verb", 2500),
    ("くる", "verb", 2400), ("来る", "verb", 2300),
    ("きた", "verb", 2600), ("来た", "verb", 2400),
    ("きます", "verb", 2600), ("来ます", "verb", 2400),
    ("みる", "verb", 2400), ("見る", "verb", 2300),
    ("みた", "verb", 2600), ("見た", "verb", 2400),
    ("みます", "verb", 2600), ("見ます", "verb", 2400),
    ("きく", "verb", 2500), ("聞く", "verb", 2300),
    ("きいて", "verb", 2600), ("聞いて", "verb", 2400),
    ("いう", "verb", 2400), ("言う", "verb", 2300),
    ("いって", "verb", 2500), ("言って", "verb", 2400),
    ("おもう", "verb", 2500), ("思う", "verb", 2300),
    ("おもった", "verb", 2600), ("思った", "verb", 2400),
    ("たべる", "verb", 2400), ("食べる", "verb", 2300),
    ("たべた", "verb", 2500), ("食べた", "verb", 2400),
    ("たべます", "verb", 2500), ("食べます", "verb", 2400),
    ("のむ", "verb", 2500), ("飲む", "verb", 2300),
    ("のんで", "verb", 2600), ("飲んで", "verb", 2400),
    ("よむ", "verb", 2500), ("読む", "verb", 2300),
    ("よんで", "verb", 2600), ("読んで", "verb", 2400),
    ("かく", "verb", 2500), ("書く", "verb", 2300),
    ("かいて", "verb", 2600), ("書いて", "verb", 2400),
    ("はなす", "verb", 2500), ("話す", "verb", 2300),
    ("はなして", "verb", 2600), ("話して", "verb", 2400),
    ("わかる", "verb", 2400), ("分かる", "verb", 2300),
    ("わかった", "verb", 2500), ("分かった", "verb", 2400),
    ("しる", "verb", 2600), ("知る", "verb", 2300),
    ("しって", "verb", 2600), ("知って", "verb", 2400),
    ("かう", "verb", 2500), ("買う", "verb", 2300),
    ("かって", "verb", 2600), ("買って", "verb", 2400),
    ("つかう", "verb", 2500), ("使う", "verb", 2300),
    ("つかって", "verb", 2600), ("使って", "verb", 2400),
    ("つくる", "verb", 2500), ("作る", "verb", 2300),
    ("はたらく", "verb", 2500), ("働く", "verb", 2300),
    ("あるく", "verb", 2500), ("歩く", "verb", 2300),
    ("はしる", "verb", 2500), ("走る", "verb", 2300),
    ("およぐ", "verb", 2500), ("泳ぐ", "verb", 2300),
    ("ねる", "verb", 2500), ("寝る", "verb", 2300),
    ("おきる", "verb", 2500), ("起きる", "verb", 2300),
    ("すむ", "verb", 2500), ("住む", "verb", 2300),
    ("すんで", "verb", 2600), ("住んで", "verb", 2400),
    ("まつ", "verb", 2500), ("待つ", "verb", 2300),
    ("もつ", "verb", 2500), ("持つ", "verb", 2300),
    ("ぬぐ", "verb", 2600), ("脱ぐ", "verb", 2300),
    ("ぬいで", "verb", 2600), ("脱いで", "verb", 2400),
    ("わらう", "verb", 2500), ("笑う", "verb", 2300),
    ("なく", "verb", 2600), ("泣く", "verb", 2400),
    ("あそぶ", "verb", 2500), ("遊ぶ", "verb", 2300),
    ("おしえる", "verb", 2500), ("教える", "verb", 2300),
    ("ならう", "verb", 2500), ("習う", "verb", 2300),
    ("おぼえる", "verb", 2500), ("覚える", "verb", 2300),
    ("わすれる", "verb", 2500), ("忘れる", "verb", 2300),
    ("あう", "verb", 2500), ("会う", "verb", 2300),
    ("あって", "verb", 2700), ("会って", "verb", 2400),
    ("なる", "verb", 2300), ("なった", "verb", 2400),
    ("なります", "verb", 2400),
    # --- adjectives ---
    ("いい", "adj", 2200), ("よい", "adj", 2300), ("よかった", "adj", 2300),
    ("わるい", "adj", 2400), ("悪い", "adj", 2300),
    ("おおきい", "adj", 2400), ("大きい", "adj", 2300),
    ("ちいさい", "adj", 2400), ("小さい", "adj", 2300),
    ("あたらしい", "adj", 2400), ("新しい", "adj", 2300),
    ("ふるい", "adj", 2400), ("古い", "adj", 2300),
    ("たかい", "adj", 2400), ("高い", "adj", 2300),
    ("やすい", "adj", 2400), ("安い", "adj", 2300),
    ("ながい", "adj", 2400), ("長い", "adj", 2300),
    ("みじかい", "adj", 2400), ("短い", "adj", 2300),
    ("はやい", "adj", 2400), ("早い", "adj", 2300), ("速い", "adj", 2300),
    ("おそい", "adj", 2400), ("遅い", "adj", 2300),
    ("あつい", "adj", 2400), ("暑い", "adj", 2300), ("熱い", "adj", 2300),
    ("さむい", "adj", 2400), ("寒い", "adj", 2300),
    ("おいしい", "adj", 2300), ("まずい", "adj", 2500),
    ("たのしい", "adj", 2300), ("楽しい", "adj", 2300),
    ("うれしい", "adj", 2300), ("嬉しい", "adj", 2300),
    ("かなしい", "adj", 2400), ("悲しい", "adj", 2300),
    ("むずかしい", "adj", 2300), ("難しい", "adj", 2300),
    ("やさしい", "adj", 2300), ("易しい", "adj", 2400),
    ("すばらしい", "adj", 2300), ("素晴らしい", "adj", 2300),
    ("きれい", "adj", 2400), ("げんき", "adj", 2500),
    ("元気", "adj", 2300), ("しずか", "adj", 2500), ("静か", "adj", 2300),
    # --- adverbs / conjunctions ---
    ("とても", "adv", 2200), ("すこし", "adv", 2300),
    ("少し", "adv", 2300), ("ちょっと", "adv", 2300),
    ("たくさん", "adv", 2300), ("もう", "adv", 2300),
    ("まだ", "adv", 2300), ("また", "adv", 2300),
    ("いつも", "adv", 2300), ("ときどき", "adv", 2400),
    ("あまり", "adv", 2400), ("ぜんぜん", "adv", 2400),
    ("きっと", "adv", 2400), ("やっぱり", "adv", 2400),
    ("そして", "conj", 2200), ("しかし", "conj", 2300),
    ("でも", "conj", 2400), ("だから", "conj", 2300),
    ("それから", "conj", 2400), ("それで", "conj", 2400),
    # --- prefixes / suffixes / counters ---
    ("お", "prefix", 2900), ("ご", "prefix", 2900),
    ("さん", "suffix", 2200), ("ちゃん", "suffix", 2400),
    ("くん", "suffix", 2400), ("さま", "suffix", 2500),
    ("たち", "suffix", 2400), ("ら", "suffix", 2800),
    ("人", "suffix", 2600), ("円", "suffix", 2300),
    ("時", "suffix", 2600), ("分", "suffix", 2600),
]

DICTIONARY: List[Tuple[str, str, int]] = list(_D)


# Coarse POS-pair connection costs (Kuromoji's connection matrix role);
# absent pairs cost 0.  BOS/EOS are virtual.
CONNECTION_COSTS: Dict[Tuple[str, str], int] = {
    ("BOS", "particle"): 3000,   # sentences rarely open with a particle
    ("BOS", "aux"): 3000,
    ("BOS", "suffix"): 3500,
    ("particle", "particle"): 1200,  # compound particles are own entries
    ("particle", "aux"): 800,
    ("aux", "noun"): 600,
    ("noun", "noun"): 700,       # prefer one long noun over two short
    ("pron", "noun"): 900,
    ("noun", "verb"): 200,
    ("verb", "aux"): -400,       # verbs attract their auxiliaries
    ("adj", "noun"): 100,
    ("prefix", "noun"): -200,
    ("noun", "suffix"): -200,
    ("num", "suffix"): -400,
    ("unk", "unk"): 1500,        # discourage chains of unknown scraps
    ("particle", "EOS"): 900,
    ("prefix", "EOS"): 2500,
}


def load_dictionary(path: str) -> List[Tuple[str, str, int]]:
    """Load dictionary entries from a CSV/TSV file (the loadable
    counterpart of the reference's vendored Kuromoji dictionaries).

    Two line formats are accepted (auto-detected per line, ``#``
    comments and blank lines skipped; separator is TAB if present,
    else comma):

    - **simple**: ``surface,pos,cost`` — this module's native triple.
    - **MeCab-style** (``surface,left_id,right_id,word_cost,POS,...``,
      the format Kuromoji's dictionary compiler consumes): detected by
      numeric columns 2-4; the POS tag is taken from column 5 and
      mapped onto this module's coarse classes via
      :data:`MECAB_POS_MAP` (unknown tags pass through lowercased).
    """
    out: List[Tuple[str, str, int]] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            sep = "\t" if "\t" in line else ","
            cols = line.split(sep)
            if len(cols) >= 5 and cols[1].lstrip("-").isdigit() \
                    and cols[2].lstrip("-").isdigit() \
                    and cols[3].lstrip("-").isdigit():
                surface = cols[0]
                cost = int(cols[3])
                pos = MECAB_POS_MAP.get(cols[4], cols[4].lower())
            elif len(cols) == 3:
                surface, pos = cols[0], cols[1]
                try:
                    cost = int(cols[2])
                except ValueError:
                    raise ValueError(
                        f"{path}:{lineno}: cost column is not an int: "
                        f"{cols[2]!r}")
            else:
                raise ValueError(
                    f"{path}:{lineno}: expected 'surface,pos,cost' or "
                    f"MeCab-style 'surface,l,r,cost,POS,...'; got "
                    f"{len(cols)} columns")
            if not surface:
                raise ValueError(f"{path}:{lineno}: empty surface")
            out.append((surface, pos, cost))
    return out


#: MeCab/IPADIC top-level POS tags -> this module's coarse classes.
MECAB_POS_MAP: Dict[str, str] = {
    "名詞": "noun", "動詞": "verb", "形容詞": "adj", "副詞": "adv",
    "助詞": "particle", "助動詞": "aux", "連体詞": "adn",
    "接続詞": "conj", "感動詞": "interj", "接頭詞": "prefix",
    "接頭辞": "prefix", "接尾辞": "suffix", "代名詞": "pron",
    "記号": "punct",
}


def load_connection_matrix(path: str) -> Dict[Tuple[str, str], int]:
    """Load POS-pair connection costs (the role of Kuromoji's learned
    ``matrix.def``): one ``left_pos right_pos cost`` triple per line
    (whitespace- or comma-separated; ``#`` comments skipped).  The
    virtual classes ``BOS``/``EOS`` are valid on the left/right."""
    out: Dict[Tuple[str, str], int] = {}
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            cols = line.replace(",", " ").split()
            if len(cols) != 3:
                raise ValueError(
                    f"{path}:{lineno}: expected 'left right cost', got "
                    f"{line!r}")
            out[(cols[0], cols[1])] = int(cols[2])
    return out


def save_dictionary(entries: Sequence[Tuple[str, str, int]],
                    path: str, sep: str = ",") -> None:
    """Write entries in the simple ``surface,pos,cost`` format
    :func:`load_dictionary` reads (round-trip tested)."""
    with open(path, "w", encoding="utf-8") as fh:
        for surface, pos, cost in entries:
            fh.write(f"{surface}{sep}{pos}{sep}{cost}\n")


class Trie:
    """Character trie with common-prefix search (DoubleArrayTrie role)."""

    __slots__ = ("_root",)

    def __init__(self, entries: Sequence[Tuple[str, str, int]]):
        self._root: dict = {}
        for surface, pos, cost in entries:
            node = self._root
            for ch in surface:
                node = node.setdefault(ch, {})
            node.setdefault(None, []).append((surface, pos, cost))

    def prefixes(self, text: str, start: int) -> List[Tuple[str, str, int]]:
        """All dictionary entries whose surface starts at ``start``."""
        out: List[Tuple[str, str, int]] = []
        node = self._root
        for i in range(start, len(text)):
            node = node.get(text[i])
            if node is None:
                break
            out.extend(node.get(None, ()))
        return out


def _script(ch: str) -> str:
    if "぀" <= ch <= "ゟ":
        return "hiragana"
    if "゠" <= ch <= "ヿ" or ch == "ー":
        return "katakana"
    if "一" <= ch <= "鿿" or "豈" <= ch <= "﫿":
        return "kanji"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


# script-dependent unknown-word costs: base + per-char (Kuromoji's
# unk.def char-class costs, coarsely)
_UNK_COSTS = {
    "katakana": (4500, 150),   # loanword nouns: whole run cheap
    "latin": (4000, 100),
    "digit": (3800, 80),
    "kanji": (5200, 700),
    "hiragana": (6000, 1700),  # real hiragana words live in the dict
}


class LatticeTokenizer:
    """Viterbi segmentation over a dictionary lattice (Kuromoji
    ``ViterbiBuilder``/``ViterbiSearcher`` role).

    >>> LatticeTokenizer().tokenize("すもももももももものうち")
    ['すもも', 'も', 'もも', 'も', 'もも', 'の', 'うち']
    """

    def __init__(self, entries: Optional[Sequence[Tuple[str, str, int]]]
                 = None,
                 connection_costs: Optional[Dict] = None):
        self.entries = list(entries) if entries is not None \
            else list(DICTIONARY)
        self.trie = Trie(self.entries)
        self.conn = dict(CONNECTION_COSTS if connection_costs is None
                         else connection_costs)

    @classmethod
    def from_files(cls, dictionary_path: str,
                   connection_path: Optional[str] = None,
                   include_bundled: bool = True) -> "LatticeTokenizer":
        """Build a tokenizer from on-disk dictionary assets — the
        loadable-dictionary tier (the reference vendors Kuromoji's
        compiled dictionaries + learned connection matrix,
        ``deeplearning4j-nlp-japanese``; here the assets are plain
        text, see :func:`load_dictionary` /
        :func:`load_connection_matrix` for the formats).

        ``include_bundled=True`` layers the file's entries OVER the
        bundled 440-entry dictionary (user-dictionary semantics —
        Kuromoji's ``UserDictionary`` augments the system dictionary);
        ``False`` uses the file alone."""
        entries = list(DICTIONARY) if include_bundled else []
        entries.extend(load_dictionary(dictionary_path))
        conn = dict(CONNECTION_COSTS) if include_bundled else {}
        if connection_path is not None:
            conn.update(load_connection_matrix(connection_path))
        return cls(entries, conn)

    # ---------------------------------------------------------------- core
    def _conn(self, left: str, right: str) -> int:
        return self.conn.get((left, right), 0)

    def _unknown_nodes(self, chunk: str, i: int
                       ) -> List[Tuple[str, str, int]]:
        s = _script(chunk[i])
        j = i
        while j < len(chunk) and _script(chunk[j]) == s and j - i < 24:
            j += 1
        run = j - i
        base, per = _UNK_COSTS.get(s, (6000, 1500))
        out = []
        # the full same-script run ...
        out.append((chunk[i:j], "unk", base + per * run))
        # ... and, for hiragana/kanji, short prefixes so the search can
        # re-synchronize with the dictionary mid-run
        if s in ("hiragana", "kanji"):
            for ln in range(1, min(run, 3)):
                out.append((chunk[i:i + ln], "unk", base + per * ln))
        return out

    def _segment_chunk(self, chunk: str) -> List[Tuple[str, str]]:
        """Viterbi over (position, POS) states; returns
        [(surface, pos), ...]."""
        n = len(chunk)
        # best[i][pos] = (cost, back) — back = (prev_i, prev_pos, surface)
        best: List[Dict[str, Tuple[int, Optional[tuple]]]] = \
            [dict() for _ in range(n + 1)]
        best[0]["BOS"] = (0, None)
        for i in range(n):
            if not best[i]:
                continue
            nodes = self.trie.prefixes(chunk, i)
            nodes += self._unknown_nodes(chunk, i)
            for surface, pos, wcost in nodes:
                j = i + len(surface)
                if j > n:
                    continue
                for left_pos, (lcost, _) in best[i].items():
                    c = lcost + wcost + self._conn(left_pos, pos)
                    cur = best[j].get(pos)
                    if cur is None or c < cur[0]:
                        best[j][pos] = (c, (i, left_pos, surface))
        # close with EOS connection
        end_pos, end_cost = None, None
        for pos, (c, _) in best[n].items():
            c2 = c + self._conn(pos, "EOS")
            if end_cost is None or c2 < end_cost:
                end_pos, end_cost = pos, c2
        if end_pos is None:
            return [(chunk, "unk")]
        # backtrack
        out: List[Tuple[str, str]] = []
        i, pos = n, end_pos
        while i > 0:
            _, back = best[i][pos]
            prev_i, prev_pos, surface = back
            out.append((surface, pos))
            i, pos = prev_i, prev_pos
        out.reverse()
        return out

    # ----------------------------------------------------------------- api
    def tokenize_with_pos(self, text: str) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        chunk = ""
        for ch in text:
            if _script(ch) in ("space", "punct"):
                if chunk:
                    out.extend(self._segment_chunk(chunk))
                    chunk = ""
            else:
                chunk += ch
        if chunk:
            out.extend(self._segment_chunk(chunk))
        return out

    def tokenize(self, text: str) -> List[str]:
        return [s for s, _ in self.tokenize_with_pos(text)]
