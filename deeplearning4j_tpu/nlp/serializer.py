"""Word-vector serialization.

TPU-native equivalent of the reference's
``models/embeddings/loader/WordVectorSerializer.java`` (2710 LoC): the
Google word2vec text and binary formats plus a DL4J-style zip container
(vocab json + vectors) that round-trips the full model (frequencies,
Huffman state, training config).
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Optional

import numpy as np

from .lookup_table import InMemoryLookupTable
from .vocab import VocabCache, VocabWord, build_huffman_tree


# ------------------------------------------------------- Google text format

def write_word_vectors(model, path: str) -> None:
    """Google word2vec *text* format: header "V D", then one line per word:
    ``word v1 v2 ... vD`` (reference ``writeWordVectors``)."""
    vocab, table = model.vocab, model.lookup_table
    m = table.weights()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(f"{vocab.num_words()} {table.vector_length}\n")
        for w in vocab.vocab_words():
            vec = " ".join(f"{x:.6f}" for x in m[w.index])
            fh.write(f"{w.word} {vec}\n")


def load_txt_vectors(path: str):
    """Load Google text vectors -> (VocabCache, InMemoryLookupTable)
    (reference ``loadTxtVectors``).  Handles both headered and headerless
    files."""
    with open(path, "r", encoding="utf-8") as fh:
        lines = [line.rstrip("\n") for line in fh if line.strip()]
    start = 0
    first = lines[0].split()
    if len(first) == 2 and all(tok.isdigit() for tok in first):
        start = 1
    vocab = VocabCache()
    vectors = []
    for line in lines[start:]:
        parts = line.split(" ")
        word = parts[0]
        vec = np.array([float(x) for x in parts[1:] if x], np.float32)
        vocab.add_token(VocabWord(word, 1.0))
        vectors.append((word, vec))
    vocab.finalize_vocab()
    dim = vectors[0][1].size if vectors else 0
    table = InMemoryLookupTable(vocab, dim, use_hs=False, negative=1.0)
    table.reset_weights()
    m = np.zeros((vocab.num_words(), dim), np.float32)
    for word, vec in vectors:
        m[vocab.index_of(word)] = vec
    import jax.numpy as jnp
    table.syn0 = jnp.asarray(m)
    return vocab, table


# ----------------------------------------------------- Google binary format

def write_binary_word_vectors(model, path: str) -> None:
    """Google word2vec *binary* format: "V D\\n" header then per word:
    ``word`` + space + D little-endian float32s + newline (reference binary
    branch of ``writeWordVectors``/original word2vec layout)."""
    vocab, table = model.vocab, model.lookup_table
    m = table.weights().astype("<f4")
    with open(path, "wb") as fh:
        fh.write(f"{vocab.num_words()} {table.vector_length}\n"
                 .encode("utf-8"))
        for w in vocab.vocab_words():
            fh.write(w.word.encode("utf-8") + b" ")
            fh.write(m[w.index].tobytes())
            fh.write(b"\n")


def load_binary_word_vectors(path: str):
    """Reference ``loadGoogleModel(file, binary=true)``."""
    with open(path, "rb") as fh:
        header = fh.readline().decode("utf-8").split()
        v, d = int(header[0]), int(header[1])
        vocab = VocabCache()
        m = np.zeros((v, d), np.float32)
        entries = []
        for _ in range(v):
            word_bytes = bytearray()
            while True:
                ch = fh.read(1)
                if ch in (b" ", b""):
                    break
                if ch != b"\n":
                    word_bytes.extend(ch)
            word = word_bytes.decode("utf-8")
            vec = np.frombuffer(fh.read(4 * d), dtype="<f4").copy()
            entries.append((word, vec))
            nxt = fh.peek(1)[:1] if hasattr(fh, "peek") else b""
            if nxt == b"\n":
                fh.read(1)
    for word, _ in entries:
        vocab.add_token(VocabWord(word, 1.0))
    vocab.finalize_vocab()
    for word, vec in entries:
        m[vocab.index_of(word)] = vec
    table = InMemoryLookupTable(vocab, d, use_hs=False, negative=1.0)
    import jax.numpy as jnp
    table.syn0 = jnp.asarray(m)
    return vocab, table


# ----------------------------------------------------------- DL4J zip format

def write_full_model(model, path: str) -> None:
    """Full-model zip (reference ``writeFullModel``/``writeWord2VecModel``):
    config.json + vocab.json (words, frequencies, Huffman codes) +
    syn0/syn1/syn1neg .npy entries."""
    vocab, table = model.vocab, model.lookup_table
    config = {
        "layer_size": model.layer_size,
        "window_size": model.window_size,
        "min_word_frequency": model.min_word_frequency,
        "learning_rate": model.learning_rate,
        "min_learning_rate": model.min_learning_rate,
        "negative": model.negative,
        "use_hierarchic_softmax": model.use_hs,
        "sampling": model.sampling,
        "seed": model.seed,
        "algorithm": model.algorithm,
    }
    vocab_entries = [{
        "word": w.word, "frequency": w.element_frequency, "index": w.index,
        "codes": w.codes, "points": w.points, "is_label": w.is_label,
    } for w in vocab.vocab_words()]

    def npy_bytes(arr) -> bytes:
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr))
        return buf.getvalue()

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("config.json", json.dumps(config))
        zf.writestr("vocab.json", json.dumps(vocab_entries))
        zf.writestr("syn0.npy", npy_bytes(table.syn0))
        if table.syn1 is not None:
            zf.writestr("syn1.npy", npy_bytes(table.syn1))
        if table.syn1neg is not None:
            zf.writestr("syn1neg.npy", npy_bytes(table.syn1neg))


def read_full_model(path: str):
    """Restore a :class:`~..word2vec.Word2Vec`-compatible model (reference
    ``readWord2VecModel``) — training can resume: syn1/syn1neg and Huffman
    state round-trip."""
    import jax.numpy as jnp

    from .word2vec import Word2Vec

    with zipfile.ZipFile(path, "r") as zf:
        config = json.loads(zf.read("config.json"))
        vocab_entries = json.loads(zf.read("vocab.json"))
        syn0 = np.load(io.BytesIO(zf.read("syn0.npy")))
        syn1 = (np.load(io.BytesIO(zf.read("syn1.npy")))
                if "syn1.npy" in zf.namelist() else None)
        syn1neg = (np.load(io.BytesIO(zf.read("syn1neg.npy")))
                   if "syn1neg.npy" in zf.namelist() else None)

    model = Word2Vec(
        layer_size=config["layer_size"], window_size=config["window_size"],
        min_word_frequency=config["min_word_frequency"],
        learning_rate=config["learning_rate"],
        min_learning_rate=config["min_learning_rate"],
        negative=config["negative"],
        use_hierarchic_softmax=config["use_hierarchic_softmax"],
        sampling=config["sampling"], seed=config["seed"],
        elements_learning_algorithm=config["algorithm"])
    vocab = VocabCache()
    for e in vocab_entries:
        w = VocabWord(e["word"], e["frequency"])
        w.codes = list(e["codes"])
        w.points = list(e["points"])
        w.is_label = e.get("is_label", False)
        vocab.add_token(w)
    vocab.finalize_vocab()
    # finalize re-assigns indices by frequency; trust the stored ones
    for e in vocab_entries:
        vocab.word_for(e["word"]).index = e["index"]
    vocab._by_index = sorted(vocab.vocab_words(), key=lambda w: w.index)
    model.vocab = vocab
    table = InMemoryLookupTable(vocab, config["layer_size"], config["seed"],
                                config["use_hierarchic_softmax"],
                                config["negative"])
    table.syn0 = jnp.asarray(syn0)
    if syn1 is not None:
        table.syn1 = jnp.asarray(syn1)
    if syn1neg is not None:
        table.syn1neg = jnp.asarray(syn1neg)
    model.lookup_table = table
    model._prepare_code_arrays()
    return model
