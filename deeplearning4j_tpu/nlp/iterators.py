"""NN-bridge iterators: sentences -> DataSet tensors.

TPU-native equivalents of the reference's
``deeplearning4j-nlp/.../iterator/CnnSentenceDataSetIterator.java``
(sentences -> padded word-vector tensors + masks for CNN text
classification), ``LabeledSentenceProvider`` SPI, and
``models/word2vec/iterator/Word2VecDataSetIterator.java`` (per-timestep
word vectors for RNNs).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..datasets.dataset import DataSet
from .tokenization import DefaultTokenizerFactory, TokenizerFactory


class LabeledSentenceProvider:
    """Reference ``iterator/LabeledSentenceProvider.java``."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sentence(self) -> Tuple[str, str]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def all_labels(self) -> List[str]:
        raise NotImplementedError


class CollectionLabeledSentenceProvider(LabeledSentenceProvider):
    """Reference ``CollectionLabeledSentenceProvider.java``."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str]):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        self.sentences = list(sentences)
        self.labels = list(labels)
        self._order = sorted(set(labels))
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.sentences)

    def next_sentence(self) -> Tuple[str, str]:
        pair = (self.sentences[self._pos], self.labels[self._pos])
        self._pos += 1
        return pair

    def reset(self) -> None:
        self._pos = 0

    def all_labels(self) -> List[str]:
        return list(self._order)


class CnnSentenceDataSetIterator:
    """Sentences -> (batch, maxLen, vecSize, 1) NHWC tensors + per-timestep
    masks (reference ``CnnSentenceDataSetIterator.java``; that emits NCHW
    (b, 1, maxLen, vec) — NHWC is the TPU-preferred layout used by this
    framework's conv stack).  ``format="rnn"`` emits (batch, time, vec) for
    recurrent heads (the ``Word2VecDataSetIterator`` role)."""

    def __init__(self, word_vectors, provider: LabeledSentenceProvider,
                 batch_size: int = 32, max_length: int = 64,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 format: str = "cnn"):
        self.word_vectors = word_vectors
        self.provider = provider
        self.batch_size = batch_size
        self.max_length = max_length
        self.tokenizer_factory = tokenizer_factory \
            or DefaultTokenizerFactory()
        if format not in ("cnn", "rnn"):
            raise ValueError("format must be cnn|rnn")
        self.format = format
        self.labels = provider.all_labels()
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self.vec_size = self._infer_vec_size()

    def _infer_vec_size(self) -> int:
        table = getattr(self.word_vectors, "lookup_table", None)
        if table is not None:
            return table.vector_length
        return self.word_vectors.vector_length

    def _vector(self, token: str) -> Optional[np.ndarray]:
        return self.word_vectors.word_vector(token) \
            if hasattr(self.word_vectors, "word_vector") \
            else self.word_vectors.vector(token)

    def reset(self) -> None:
        self.provider.reset()

    def __iter__(self):
        self.reset()
        while self.provider.has_next():
            batch = []
            while self.provider.has_next() \
                    and len(batch) < self.batch_size:
                batch.append(self.provider.next_sentence())
            ds = self._to_dataset(batch)
            if ds is not None:
                yield ds

    def _to_dataset(self, batch) -> Optional[DataSet]:
        seqs: List[np.ndarray] = []
        labels: List[int] = []
        for sentence, label in batch:
            tokens = self.tokenizer_factory.create(sentence).get_tokens()
            vecs = [self._vector(t) for t in tokens]
            vecs = [v for v in vecs if v is not None][:self.max_length]
            if not vecs:
                continue
            seqs.append(np.stack(vecs))
            labels.append(self._label_idx[label])
        if not seqs:
            return None
        b = len(seqs)
        T = max(s.shape[0] for s in seqs)
        feats = np.zeros((b, T, self.vec_size), np.float32)
        mask = np.zeros((b, T), np.float32)
        for i, s in enumerate(seqs):
            feats[i, :s.shape[0]] = s
            mask[i, :s.shape[0]] = 1.0
        y = np.eye(len(self.labels), dtype=np.float32)[labels]
        if self.format == "cnn":
            return DataSet(feats[..., None], y, features_mask=None)
        return DataSet(feats, y, features_mask=mask)
