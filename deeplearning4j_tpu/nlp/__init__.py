"""NLP / embeddings tier.

TPU-native equivalent of the reference's ``deeplearning4j-nlp-parent``
(SURVEY.md §2.7): tokenization SPI, sentence/document iterators, vocabulary
construction + Huffman coding, in-memory lookup tables, SequenceVectors /
Word2Vec (XLA skip-gram/CBOW kernels), ParagraphVectors, GloVe, TF-IDF /
bag-of-words, and word-vector serde.
"""

from .tokenization import (CommonPreprocessor, DefaultTokenizerFactory,
                           EndingPreProcessor, LowCasePreProcessor,
                           NGramTokenizerFactory, Tokenizer,
                           TokenizerFactory)
from .sentence_iterator import (BasicLineIterator,
                                CollectionSentenceIterator,
                                FileSentenceIterator, LabelAwareIterator,
                                LabelledDocument, LabelsSource,
                                SentenceIterator, SimpleLabelAwareIterator)
from .vocab import (VocabCache, VocabConstructor, VocabWord,
                    build_huffman_tree)
from .lookup_table import InMemoryLookupTable
from .word2vec import SequenceVectors, Word2Vec
from .paragraph_vectors import ParagraphVectors
from .glove import Glove
from .vectorizer import BagOfWordsVectorizer, TfidfVectorizer
from .iterators import (CnnSentenceDataSetIterator,
                        CollectionLabeledSentenceProvider,
                        LabeledSentenceProvider)

__all__ = [
    "BagOfWordsVectorizer", "BasicLineIterator",
    "CnnSentenceDataSetIterator", "CollectionLabeledSentenceProvider",
    "CollectionSentenceIterator", "CommonPreprocessor",
    "DefaultTokenizerFactory", "EndingPreProcessor", "FileSentenceIterator",
    "Glove", "InMemoryLookupTable", "LabelAwareIterator",
    "LabeledSentenceProvider", "LabelledDocument", "LabelsSource",
    "LowCasePreProcessor", "NGramTokenizerFactory", "ParagraphVectors",
    "SentenceIterator", "SequenceVectors", "SimpleLabelAwareIterator",
    "TfidfVectorizer", "Tokenizer", "TokenizerFactory", "VocabCache",
    "VocabConstructor", "VocabWord", "Word2Vec", "build_huffman_tree",
]
