"""ParagraphVectors (doc2vec).

TPU-native equivalent of the reference's
``models/paragraphvectors/ParagraphVectors.java`` with the sequence learning
algorithms ``models/embeddings/learning/impl/sequence/DBOW.java`` and
``DM.java``.

Labels (document ids) are vocabulary elements with their own syn0 rows
(reference: label elements added to the vocab with ``isLabel`` markers):

- **DBOW** (distributed bag of words): the label vector is trained to
  predict each word of its document — skip-gram with input = label.
- **DM** (distributed memory): the label vector joins the context window
  average predicting the center word — CBOW with the label appended to
  every window.

Both reuse the Word2Vec XLA kernels unchanged.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .sentence_iterator import (LabelAwareIterator, LabelledDocument,
                                LabelsSource, SimpleLabelAwareIterator)
from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache, VocabWord, build_huffman_tree
from .lookup_table import InMemoryLookupTable
from .word2vec import SequenceVectors


class ParagraphVectors(SequenceVectors):
    """doc2vec trainer (reference ``ParagraphVectors.java``)."""

    def __init__(self, sequence_learning_algorithm: str = "dbow",
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 train_word_vectors: bool = True, **kwargs):
        kwargs.setdefault("min_word_frequency", 1)
        super().__init__(**kwargs)
        self.sequence_algorithm = sequence_learning_algorithm.lower()
        if self.sequence_algorithm not in ("dbow", "dm"):
            raise ValueError("sequence_learning_algorithm must be dbow|dm")
        self.tokenizer_factory = tokenizer_factory \
            or DefaultTokenizerFactory()
        self.train_word_vectors = train_word_vectors
        self.labels_source = LabelsSource()
        self._docs: List[Tuple[List[str], str]] = []

    # ------------------------------------------------------------ ingestion
    def _resolve_documents(self, documents) -> List[Tuple[List[str], str]]:
        if isinstance(documents, LabelAwareIterator):
            docs = list(documents)
        else:
            docs = list(SimpleLabelAwareIterator(documents,
                                                 self.labels_source))
        out = []
        for d in docs:
            tokens = (self.tokenizer_factory.create(d.content).get_tokens()
                      if isinstance(d.content, str) else list(d.content))
            out.append((tokens, d.label))
        return out

    def build_vocab_from_documents(self, docs) -> None:
        from .vocab import VocabConstructor
        constructor = VocabConstructor(
            min_word_frequency=self.min_word_frequency)
        self.vocab = constructor.build_vocab([t for t, _ in docs])
        # Label elements join the vocab with frequency 1 and is_label=True
        # (excluded from subsampling and from being prediction targets).
        for _, label in docs:
            if not self.vocab.contains_word(label):
                w = VocabWord(label, 1.0)
                w.is_label = True
                self.vocab.add_token(w)
        self.vocab.finalize_vocab()
        if self.use_hs:
            build_huffman_tree(self.vocab,
                               max_code_length=self.max_code_length)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, self.seed, self.use_hs,
            self.negative)
        self.lookup_table.reset_weights()
        self._prepare_code_arrays()

    # ------------------------------------------------------------- training
    def _device_eligible_pv(self, docs) -> bool:
        """Route PV onto the device pipelines: the word-vector side
        reuses the skip-gram/CBOW corpus scan; DBOW's label side runs
        the label-pair scan, DM's the CBOW grid with the label joined
        as an always-live context column.  Same gating posture as
        ``SequenceVectors._device_eligible``; subclasses overriding
        ``_train_document`` keep their loop."""
        if self.sequence_algorithm not in ("dbow", "dm"):
            return False
        # DM "auto" stays on the host loop: the device DM pass converges
        # slower on small corpora (its word/label segment alternation is
        # coarser than the host's per-document interleaving) — explicit
        # pair_generation="device" opts in.
        if (self.sequence_algorithm == "dm"
                and self.pair_generation != "device"):
            return False
        if type(self)._train_document is not ParagraphVectors._train_document:
            return False
        return self._device_eligible([t for t, _ in docs])

    #: interleave granularity: each pass of each pipeline splits into
    #: this many alternating scan dispatches (approximating the host
    #: loop's per-document alternation)
    INTERLEAVE_SEGMENTS = 16

    def _run_interleaved(self, word_pipe, label_pipe):
        """Alternate word-side and label-side SEGMENTS within each pass
        (the host loop interleaves per document): running all word
        passes first leaves the predictive tables already fit to the
        contexts, so the label gradients arrive saturated — measured on
        DM: labels stayed at noise (same-topic 0.10 vs host 0.68)
        under sequential ordering, and whole-pass alternation was still
        too coarse for the fast-converging NS tables; ~16-way segment
        alternation restores host-level label quality."""
        passes = self.epochs * self.iterations
        nseg = self.INTERLEAVE_SEGMENTS if word_pipe is not None else 1
        stats = {}
        prev = {}
        for name, pipe in (("word", word_pipe), ("label", label_pipe)):
            if pipe is not None:
                prev[name] = (pipe.pairs_trained, pipe.loss_sum)
        for p in range(passes):
            for seg in range(nseg):
                if word_pipe is not None:
                    word_pipe.run_segment(p, word_pipe.n_words * passes,
                                          seg, nseg)
                label_pipe.run_segment(p, label_pipe.n_words * passes,
                                       seg, nseg)
        for name, pipe in (("word", word_pipe), ("label", label_pipe)):
            if pipe is not None:
                pipe.finish()
                p0, l0 = prev[name]
                stats[name] = {"pairs_trained": pipe.pairs_trained - p0,
                               "loss_sum": pipe.loss_sum - l0,
                               "passes": passes}
        return stats

    def _fit_device_dbow(self, docs, source=None) -> "ParagraphVectors":
        """Both device pipelines (word side + label side), with the
        corpus indexed ONCE and both built pipelines cached across
        fit() calls keyed on the caller's ``documents`` object, the
        vocab, and the baked config (the SequenceVectors cache posture:
        re-fitting for more epochs skips re-indexing/re-upload and
        draws fresh RNG via the pipelines' lifetime pass counters)."""
        from .device_corpus import DeviceDbowLabels
        conf_key = self._device_conf_key() + (self.train_word_vectors,)
        cached = getattr(self, "_device_dbow_cache", None)
        if not (cached is not None and source is not None
                and cached[0] is source and cached[1] is self.vocab
                and cached[2] == conf_key):
            cached = None
        seqs = None
        if cached is None:
            seqs = [self._sequence_to_indices(t) for t, _ in docs]
        word_pipe = (self._device_word_pipe([t for t, _ in docs],
                                            source=source, seqs_idx=seqs)
                     if self.train_word_vectors else None)
        if cached is not None:
            label_pipe = cached[3]
        else:
            labels = [self.vocab.index_of(lab) for _, lab in docs]
            keep = [(s, l) for s, l in zip(seqs, labels)
                    if s.size >= 1 and l >= 0]
            if not keep:
                # no resolvable labels: the WORD side still trains
                # (baseline behavior); zeroed label stats so a prior
                # fit's numbers can't read as this fit's
                if word_pipe is not None:
                    self._device_pipeline_stats = \
                        self._run_device_passes(word_pipe)
                self._device_dbow_stats = {"pairs_trained": 0.0,
                                           "loss_sum": 0.0, "passes": 0}
                return self
            label_pipe = DeviceDbowLabels(self, [s for s, _ in keep],
                                          [l for _, l in keep])
            if source is not None:
                self._device_dbow_cache = (source, self.vocab, conf_key,
                                           label_pipe)
        stats = self._run_interleaved(word_pipe, label_pipe)
        if "word" in stats:
            self._device_pipeline_stats = stats["word"]
        self._device_dbow_stats = stats["label"]
        return self

    def _fit_device_dm(self, docs, source=None) -> "ParagraphVectors":
        """PV-DM on the device pipelines: optional word-vector training
        (the element algorithm's corpus scan), then the DM pass — the
        CBOW grid with each document's label appended as an always-live
        window column (reference ``DM.java`` semantics; a center with an
        otherwise-empty window trains from the label alone).  Long
        documents concentrate label-row scatter duplicates within a
        span exactly as the host path's batching does — shared, 
        documented exposure; quality-tested at moderate lengths."""
        from .device_corpus import DeviceSkipGram
        conf_key = self._device_conf_key() + ("dm",
                                              self.train_word_vectors)
        cached = getattr(self, "_device_dm_cache", None)
        if not (cached is not None and source is not None
                and cached[0] is source and cached[1] is self.vocab
                and cached[2] == conf_key):
            cached = None
        seqs = None
        if cached is None:
            seqs = [self._sequence_to_indices(t) for t, _ in docs]
        word_pipe = (self._device_word_pipe([t for t, _ in docs],
                                            source=source, seqs_idx=seqs)
                     if self.train_word_vectors else None)
        if cached is not None:
            dm_pipe = cached[3]
        else:
            labels = [self.vocab.index_of(lab) for _, lab in docs]
            keep = [(s, l) for s, l in zip(seqs, labels)
                    if s.size >= 1 and l >= 0]
            if not keep:
                if word_pipe is not None:
                    self._device_pipeline_stats = \
                        self._run_device_passes(word_pipe)
                self._device_dm_stats = {"pairs_trained": 0.0,
                                         "loss_sum": 0.0, "passes": 0}
                return self
            dm_pipe = DeviceSkipGram(self, [s for s, _ in keep],
                                     label_rows=[l for _, l in keep],
                                     algorithm="cbow")
            if source is not None:
                self._device_dm_cache = (source, self.vocab, conf_key,
                                         dm_pipe)
        stats = self._run_interleaved(word_pipe, dm_pipe)
        if "word" in stats:
            self._device_pipeline_stats = stats["word"]
        self._device_dm_stats = stats["label"]
        return self

    def fit(self, documents=None) -> "ParagraphVectors":
        docs = self._resolve_documents(documents)
        self._docs = docs
        if self.vocab is None:
            self.build_vocab_from_documents(docs)
        self._reset_queues()  # drop stale pairs from an aborted prior fit
        if self._device_eligible_pv(docs):
            if self.sequence_algorithm == "dm":
                return self._fit_device_dm(docs, source=documents)
            return self._fit_device_dbow(docs, source=documents)
        total = sum(len(t) for t, _ in docs) * self.epochs * self.iterations
        seen = 0
        for _ in range(self.epochs):
            for tokens, label in docs:
                for _ in range(self.iterations):
                    seen += len(tokens)
                    alpha = max(self.min_learning_rate,
                                self.learning_rate
                                * (1.0 - seen / max(total + 1, 1)))
                    self._train_document(tokens, label, alpha)
        self._flush_queues()
        return self

    def _train_document(self, tokens: Sequence[str], label: str,
                        alpha: float) -> None:
        """Queue one document's training pairs.  Pairs accumulate across
        documents into full ``batch_size`` XLA dispatches (a short document
        no longer costs its own device round-trip — the host-dispatch-bound
        anti-pattern the word2vec kernel design note warns about)."""
        word_idx = self._subsample_keep(self._sequence_to_indices(tokens))
        label_idx = self.vocab.index_of(label)
        if word_idx.size == 0 or label_idx < 0:
            return
        if self.train_word_vectors:
            self._train_sequence(tokens, alpha)
        if self.sequence_algorithm == "dbow":
            # label -> each word (skip-gram, input = label row)
            inputs = np.full(word_idx.size, label_idx, np.int64)
            self._queue_skipgram(inputs, word_idx, alpha)
        else:
            # DM: CBOW windows with the label appended to every context
            ctx, cmask, centers = self._generate_cbow(word_idx)
            if centers.size == 0:
                # single-word docs: label alone predicts the word
                ctx = np.zeros((word_idx.size, 1), np.int64)
                cmask = np.zeros((word_idx.size, 1), np.float32)
                centers = word_idx
            label_col = np.full((ctx.shape[0], 1), label_idx, np.int64)
            ctx = np.concatenate([ctx, label_col], axis=1)
            cmask = np.concatenate(
                [cmask, np.ones((cmask.shape[0], 1), np.float32)], axis=1)
            self._queue_cbow(ctx, cmask, centers, alpha)

    # ------------------------------------------------------------ inference
    def infer_vector(self, text, steps: int = 20,
                     alpha: float = 0.025) -> np.ndarray:
        """Infer a vector for an unseen document (reference
        ``inferVector``): gradient steps on a fresh row with all other
        params frozen.  Host-side loop over a tiny problem — cheap."""
        tokens = (self.tokenizer_factory.create(text).get_tokens()
                  if isinstance(text, str) else list(text))
        word_idx = self._sequence_to_indices(tokens)
        rng = np.random.RandomState(abs(hash(tuple(tokens))) % (2 ** 31))
        vec = ((rng.rand(self.layer_size) - 0.5)
               / self.layer_size).astype(np.float32)
        if word_idx.size == 0:
            return vec
        syn0 = self.lookup_table.weights()
        if self.use_hs:
            points, codes, cmask = [np.asarray(a)
                                    for a in self._code_arrays]
        for _ in range(steps):
            if self.use_hs:
                for w in word_idx:
                    p, c, m = points[w], codes[w], cmask[w]
                    w1 = np.asarray(self.lookup_table.syn1)[p]
                    logits = w1 @ vec
                    g = (1.0 - c - 1.0 / (1.0 + np.exp(-logits))) * m
                    vec = vec + alpha * (g @ w1)
            else:
                table = self.lookup_table.negative_table()
                syn1neg = np.asarray(self.lookup_table.syn1neg)
                for w in word_idx:
                    negs = table[rng.randint(0, table.size,
                                             int(self.negative))]
                    tgt = np.concatenate([[w], negs])
                    lbl = np.concatenate([[1.0],
                                          np.zeros(int(self.negative))])
                    w1 = syn1neg[tgt]
                    logits = w1 @ vec
                    g = lbl - 1.0 / (1.0 + np.exp(-logits))
                    vec = vec + alpha * (g @ w1)
        return vec

    def predict(self, text) -> Optional[str]:
        """Nearest label for a document (reference ``predict``)."""
        vec = self.infer_vector(text)
        labels = [w for w in self.vocab.vocab_words() if w.is_label]
        if not labels:
            return None
        m = self.lookup_table.weights()
        best, best_sim = None, -np.inf
        for w in labels:
            lv = m[w.index]
            denom = max(np.linalg.norm(lv) * np.linalg.norm(vec), 1e-12)
            sim = float(lv @ vec / denom)
            if sim > best_sim:
                best, best_sim = w.word, sim
        return best

    def label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.word_vector(label)
