"""GloVe: co-occurrence-matrix embeddings.

TPU-native equivalent of the reference's ``models/glove/Glove.java`` +
``models/glove/AbstractCoOccurrences.java``: a host-side weighted
co-occurrence scan (weight 1/distance within the window), then AdaGrad
regression on ``f(X_ij) (w_i·w̃_j + b_i + b̃_j − log X_ij)²`` executed as
jitted XLA batches (the reference runs per-pair AdaGrad in Java threads).
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .lookup_table import InMemoryLookupTable
from .vocab import VocabCache, VocabConstructor
from .word2vec import SequenceVectors

Array = jax.Array


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(W: Array, Wc: Array, b: Array, bc: Array, hW: Array,
                hWc: Array, hb: Array, hbc: Array, rows: Array, cols: Array,
                logx: Array, fx: Array, mask: Array, lr: Array):
    """One AdaGrad batch over co-occurrence triples.

    W/Wc: word and context embeddings; b/bc biases; h*: AdaGrad
    accumulators.  Standard GloVe gradients with scatter-add updates.
    """
    wi = W[rows]
    wj = Wc[cols]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols] - logx)
    g = fx * diff * mask                               # (B,)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask)
    gw = g[:, None] * wj
    gwc = g[:, None] * wi
    # AdaGrad: accumulate squared grads, scale updates
    hW = hW.at[rows].add(gw * gw)
    hWc = hWc.at[cols].add(gwc * gwc)
    hb = hb.at[rows].add(g * g)
    hbc = hbc.at[cols].add(g * g)
    W = W.at[rows].add(-lr * gw / jnp.sqrt(hW[rows] + 1e-8))
    Wc = Wc.at[cols].add(-lr * gwc / jnp.sqrt(hWc[cols] + 1e-8))
    b = b.at[rows].add(-lr * g / jnp.sqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * g / jnp.sqrt(hbc[cols] + 1e-8))
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


class Glove(SequenceVectors):
    """GloVe trainer (reference ``Glove.java`` builder: xMax, alpha,
    learningRate, epochs, symmetric window)."""

    def __init__(self, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, **kwargs):
        kwargs.setdefault("learning_rate", 0.05)
        kwargs.setdefault("use_hierarchic_softmax", True)  # unused; appease
        super().__init__(**kwargs)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self._context: Optional[Array] = None

    # ------------------------------------------------------- co-occurrences
    def _count_cooccurrences(self, seqs: List[List[str]]
                             ) -> Dict[Tuple[int, int], float]:
        counts: Dict[Tuple[int, int], float] = defaultdict(float)
        for seq in seqs:
            idx = self._sequence_to_indices(seq)
            n = idx.size
            for i in range(n):
                for j in range(max(0, i - self.window_size), i):
                    w = 1.0 / (i - j)
                    counts[(int(idx[i]), int(idx[j]))] += w
                    if self.symmetric:
                        counts[(int(idx[j]), int(idx[i]))] += w
        return counts

    # ------------------------------------------------------------- training
    def fit(self, sequences) -> "Glove":
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        counts = self._count_cooccurrences(seq_list)
        if not counts:
            return self
        pairs = np.array(list(counts.keys()), np.int32)
        xs = np.array(list(counts.values()), np.float32)
        logx = np.log(xs)
        fx = np.minimum(1.0, (xs / self.x_max) ** self.alpha).astype(
            np.float32)

        V, D = self.vocab.num_words(), self.layer_size
        key = jax.random.PRNGKey(self.seed)
        k1, k2 = jax.random.split(key)
        W = ((jax.random.uniform(k1, (V, D), jnp.float32) - 0.5)
             / D).astype(jnp.float32)
        Wc = ((jax.random.uniform(k2, (V, D), jnp.float32) - 0.5)
              / D).astype(jnp.float32)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        hW = jnp.zeros((V, D), jnp.float32)
        hWc = jnp.zeros((V, D), jnp.float32)
        hb = jnp.zeros((V,), jnp.float32)
        hbc = jnp.zeros((V,), jnp.float32)
        lr = jnp.float32(self.learning_rate)

        B = self.batch_size
        n = pairs.shape[0]
        order = np.arange(n)
        for _ in range(self.epochs):
            self._rng.shuffle(order)
            for s in range(0, n, B):
                sel = order[s:s + B]
                pad = B - sel.size
                mask = np.concatenate([np.ones(sel.size, np.float32),
                                       np.zeros(pad, np.float32)])
                sel_p = np.concatenate([sel, np.zeros(pad, np.int64)])
                (W, Wc, b, bc, hW, hWc, hb, hbc, _) = _glove_step(
                    W, Wc, b, bc, hW, hWc, hb, hbc,
                    jnp.asarray(pairs[sel_p, 0]),
                    jnp.asarray(pairs[sel_p, 1]),
                    jnp.asarray(logx[sel_p]), jnp.asarray(fx[sel_p]),
                    jnp.asarray(mask), lr)

        # Final embedding: W + Wc (standard GloVe practice; the reference
        # exposes syn0)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, D, self.seed, use_hs=False, negative=1.0)
        self.lookup_table.syn0 = W + Wc
        self._context = Wc
        return self
