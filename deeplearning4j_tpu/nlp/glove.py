"""GloVe: co-occurrence-matrix embeddings.

TPU-native equivalent of the reference's ``models/glove/Glove.java`` +
``models/glove/AbstractCoOccurrences.java``: a host-side weighted
co-occurrence scan (weight 1/distance within the window), then AdaGrad
regression on ``f(X_ij) (w_i·w̃_j + b_i + b̃_j − log X_ij)²`` executed as
jitted XLA batches (the reference runs per-pair AdaGrad in Java threads).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.scatter import aggregation_enabled, fused_adagrad_dual
from .lookup_table import InMemoryLookupTable
from .vocab import VocabCache, VocabConstructor
from .word2vec import SequenceVectors

Array = jax.Array


def _glove_update(W: Array, Wc: Array, b: Array, bc: Array, hW: Array,
                  hWc: Array, hb: Array, hbc: Array, rows: Array,
                  cols: Array, logx: Array, fx: Array, mask: Array,
                  lr: Array):
    """One AdaGrad batch over co-occurrence triples — the NAIVE
    eight-scatter reference path (four accumulator bumps, four scaled
    weight deltas).  The production path is ``_glove_update_fused``
    below, parity-tested against this; this form is kept as the
    documented semantics contract, the parity oracle, and the
    ``Glove.use_fused_scatter = False`` escape hatch.

    W/Wc: word and context embeddings; b/bc biases; h*: AdaGrad
    accumulators.  Standard GloVe gradients with scatter-add updates.
    """
    wi = W[rows]
    wj = Wc[cols]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols] - logx)
    g = fx * diff * mask                               # (B,)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask)
    gw = g[:, None] * wj
    gwc = g[:, None] * wi
    # AdaGrad: accumulate squared grads, scale updates
    hW = hW.at[rows].add(gw * gw)
    hWc = hWc.at[cols].add(gwc * gwc)
    hb = hb.at[rows].add(g * g)
    hbc = hbc.at[cols].add(g * g)
    W = W.at[rows].add(-lr * gw / jnp.sqrt(hW[rows] + 1e-8))
    Wc = Wc.at[cols].add(-lr * gwc / jnp.sqrt(hWc[cols] + 1e-8))
    b = b.at[rows].add(-lr * g / jnp.sqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * g / jnp.sqrt(hbc[cols] + 1e-8))
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


_glove_step = jax.jit(_glove_update, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))


def _glove_update_fused(Sr: Array, Sc: Array, rows: Array, cols: Array,
                        logx: Array, fx: Array, mask: Array, lr: Array):
    """The production AdaGrad batch: TWO fused table updates instead of
    eight scatters (``ops/scatter.py``, the scatter-row economics the
    word2vec tier profiled at ~7M scatter rows/s — scatter rows, not
    FLOPs, bound this kernel).

    State is the packed dual-buffer layout: ``Sr`` (V, 2D+2) =
    ``[W | b | hW | hb]`` for the word side, ``Sc`` likewise for the
    context side (``[Wc | bc | hWc | hbc]``).  Each side's batch
    collapses duplicate destination rows (hot words repeat heavily in
    co-occurrence batches) with a sort + segment-sum, then lands weight
    deltas AND accumulator bumps in ONE sorted-unique scatter
    (:func:`~deeplearning4j_tpu.ops.scatter.fused_adagrad_dual`).
    Gradient math, the read-after-batch-accumulator AdaGrad semantics,
    and the loss are identical to ``_glove_update`` (parity-tested,
    incl. duplicate-heavy batches)."""
    D = Sr.shape[1] // 2 - 1
    ri, ci = Sr[rows], Sc[cols]                        # (B, 2D+2)
    wi, bi = ri[:, :D], ri[:, D]
    wj, bj = ci[:, :D], ci[:, D]
    diff = jnp.einsum("bd,bd->b", wi, wj) + bi + bj - logx
    g = fx * diff * mask                               # (B,)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask)
    grad_r = jnp.concatenate([g[:, None] * wj, g[:, None]], axis=1)
    grad_c = jnp.concatenate([g[:, None] * wi, g[:, None]], axis=1)
    Sr = fused_adagrad_dual(Sr, rows, grad_r, lr)
    Sc = fused_adagrad_dual(Sc, cols, grad_c, lr)
    return Sr, Sc, loss


def _glove_epoch_fused(Sr, Sc, rows_all, cols_all, logx_all, fx_all,
                       order, lr):
    """Fused twin of ``_glove_epoch``: same one-dispatch-per-epoch scan
    over device-resident triples, with the packed dual-buffer state and
    the two-scatter update body."""
    def body(carry, idx):
        Sr, Sc, loss_sum = carry
        mask = (idx >= 0).astype(jnp.float32)
        sel = jnp.maximum(idx, 0)
        Sr, Sc, loss = _glove_update_fused(
            Sr, Sc, rows_all[sel], cols_all[sel], logx_all[sel],
            fx_all[sel], mask, lr)
        return (Sr, Sc, loss_sum + loss), None
    (Sr, Sc, loss), _ = jax.lax.scan(
        body, (Sr, Sc, jnp.float32(0.0)), order)
    return Sr, Sc, loss


_glove_epoch_fused = jax.jit(_glove_epoch_fused, donate_argnums=(0, 1))


def _glove_epoch(W, Wc, b, bc, hW, hWc, hb, hbc, rows_all, cols_all,
                 logx_all, fx_all, order, lr):
    """One EPOCH of AdaGrad as a single scan dispatch: the co-occurrence
    triples live on device (uploaded once per fit), and each epoch ships
    only the shuffled (n_chunks, B) permutation — the same
    device-residency move as ``nn/ingest.py``'s epoch cache and
    ``nlp/device_corpus.py``.  The update math, chunk boundaries, mask
    padding, and shuffle stream are IDENTICAL to the per-batch path
    (parity-tested), so this is purely a dispatch-structure change.
    (jit specializes per (n_chunks, B) shape; no factory needed.)"""
    def body(carry, idx):
        W, Wc, b, bc, hW, hWc, hb, hbc, loss_sum = carry
        mask = (idx >= 0).astype(jnp.float32)
        sel = jnp.maximum(idx, 0)
        (W, Wc, b, bc, hW, hWc, hb, hbc, loss) = _glove_update(
            W, Wc, b, bc, hW, hWc, hb, hbc, rows_all[sel],
            cols_all[sel], logx_all[sel], fx_all[sel], mask, lr)
        return (W, Wc, b, bc, hW, hWc, hb, hbc, loss_sum + loss), None
    init = (W, Wc, b, bc, hW, hWc, hb, hbc, jnp.float32(0.0))
    (W, Wc, b, bc, hW, hWc, hb, hbc, loss), _ = jax.lax.scan(
        body, init, order)
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


_glove_epoch = jax.jit(_glove_epoch, donate_argnums=tuple(range(8)))


class Glove(SequenceVectors):
    """GloVe trainer (reference ``Glove.java`` builder: xMax, alpha,
    learningRate, epochs, symmetric window)."""

    #: co-occurrence keys buffered between dedup flushes (bounds the
    #: counting pass's transient memory on huge corpora)
    COOC_CHUNK_KEYS = 4_000_000

    #: final-epoch weighted-least-squares loss of the last fit (None
    #: until a fit trains at least one epoch on a non-empty cooc set)
    last_epoch_loss: Optional[float] = None

    #: route AdaGrad batches through the two-scatter fused dual-buffer
    #: path (``ops/scatter.py``); False falls back to the naive
    #: eight-scatter reference kernel (same math — parity-tested).
    #: None = auto: fused where scatter rows are the cost (TPU), naive
    #: where the aggregation pass costs more than CPU's cheap scatters
    #: save (``aggregation_enabled()`` — same gate, same env override)
    use_fused_scatter: Optional[bool] = None

    def __init__(self, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, **kwargs):
        kwargs.setdefault("learning_rate", 0.05)
        kwargs.setdefault("use_hierarchic_softmax", True)  # unused; appease
        super().__init__(**kwargs)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self._context: Optional[Array] = None

    # ------------------------------------------------------- co-occurrences
    def _count_cooccurrences(self, seqs: List[List[str]]
                             ) -> Dict[Tuple[int, int], float]:
        """Windowed 1/distance co-occurrence counts (reference
        ``AbstractCoOccurrences``).  Vectorized: for each distance d,
        the (i, i-d) pairs of a sequence are two shifted slices, keyed
        as i*V + j and merge-summed with unique/bincount — the Python
        per-position double loop this replaces was the fit bottleneck
        past ~100k words (O(corpus x window) dict ops)."""
        V = max(self.vocab.num_words(), 1)
        uk, sums = self._cooc_arrays(seqs)
        return {(int(k // V), int(k % V)): float(s)
                for k, s in zip(uk, sums)}

    def _cooc_arrays(self, seqs: List[List[str]]
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """(keys, sums) arrays of the windowed co-occurrence counts —
        keys are i*V + j.  ``fit`` consumes these directly (the dict
        form above exists for the reference-shaped API and tests; a
        4.5M-triple corpus spent more time building/flattening the dict
        than counting)."""
        V = max(self.vocab.num_words(), 1)
        deduped: List[Tuple[np.ndarray, np.ndarray]] = []
        keys_parts: List[np.ndarray] = []
        wt_parts: List[np.ndarray] = []
        pending = 0

        def flush() -> None:
            nonlocal pending
            if not keys_parts:
                return
            keys = np.concatenate(keys_parts)
            uk, inv = np.unique(keys, return_inverse=True)
            deduped.append(
                (uk, np.bincount(inv, weights=np.concatenate(wt_parts))))
            keys_parts.clear()
            wt_parts.clear()
            pending = 0

        for seq in seqs:
            idx = self._sequence_to_indices(seq).astype(np.int64)
            n = idx.size
            for d in range(1, min(self.window_size, n - 1) + 1):
                a, bwd = idx[d:], idx[:-d]
                keys_parts.append(a * V + bwd)
                wt_parts.append(np.full(a.size, 1.0 / d))
                pending += a.size
                if self.symmetric:
                    keys_parts.append(bwd * V + a)
                    wt_parts.append(np.full(a.size, 1.0 / d))
                    pending += a.size
            # Dedup in bounded chunks: transient memory scales with the
            # chunk plus the UNIQUE pairs seen so far, not with
            # corpus x window (the regime this vectorization targets).
            if pending >= self.COOC_CHUNK_KEYS:
                flush()
        flush()
        if not deduped:
            return (np.zeros(0, np.int64), np.zeros(0, np.float64))
        keys = np.concatenate([k for k, _ in deduped])
        uk, inv = np.unique(keys, return_inverse=True)
        sums = np.bincount(
            inv, weights=np.concatenate([s for _, s in deduped]))
        return uk, sums

    # ------------------------------------------------------------- training
    def fit(self, sequences) -> "Glove":
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        V = max(self.vocab.num_words(), 1)
        keys, sums = self._cooc_arrays(seq_list)
        if keys.size == 0:
            return self
        pairs = np.stack([(keys // V).astype(np.int32),
                          (keys % V).astype(np.int32)], axis=1)
        xs = sums.astype(np.float32)
        logx = np.log(xs)
        fx = np.minimum(1.0, (xs / self.x_max) ** self.alpha).astype(
            np.float32)

        V, D = self.vocab.num_words(), self.layer_size
        key = jax.random.PRNGKey(self.seed)
        k1, k2 = jax.random.split(key)
        W = ((jax.random.uniform(k1, (V, D), jnp.float32) - 0.5)
             / D).astype(jnp.float32)
        Wc = ((jax.random.uniform(k2, (V, D), jnp.float32) - 0.5)
              / D).astype(jnp.float32)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        hW = jnp.zeros((V, D), jnp.float32)
        hWc = jnp.zeros((V, D), jnp.float32)
        hb = jnp.zeros((V,), jnp.float32)
        hbc = jnp.zeros((V,), jnp.float32)
        lr = jnp.float32(self.learning_rate)

        B = self.batch_size
        n = pairs.shape[0]
        n_chunks = -(-n // B)
        del keys, sums
        # triples device-resident for the whole fit; each epoch ships one
        # shuffled permutation and runs as ONE scan dispatch
        rows_d = jnp.asarray(pairs[:, 0])
        cols_d = jnp.asarray(pairs[:, 1])
        logx_d = jnp.asarray(logx)
        fx_d = jnp.asarray(fx)
        order = np.arange(n)
        fused = (self.use_fused_scatter if self.use_fused_scatter
                 is not None else aggregation_enabled())
        if fused:
            # packed dual-buffer state: [W | b | hW | hb] per side, so
            # each side's AdaGrad batch is ONE sorted-unique scatter
            Sr = jnp.concatenate([W, b[:, None], hW, hb[:, None]], axis=1)
            Sc = jnp.concatenate([Wc, bc[:, None], hWc, hbc[:, None]],
                                 axis=1)
            for _ in range(self.epochs):
                self._rng.shuffle(order)
                padded = np.full(n_chunks * B, -1, np.int32)
                padded[:n] = order
                Sr, Sc, ep_loss = _glove_epoch_fused(
                    Sr, Sc, rows_d, cols_d, logx_d, fx_d,
                    jnp.asarray(padded.reshape(n_chunks, B)), lr)
            W, b = Sr[:, :D], Sr[:, D]
            Wc, bc = Sc[:, :D], Sc[:, D]
        else:
            for _ in range(self.epochs):
                self._rng.shuffle(order)
                padded = np.full(n_chunks * B, -1, np.int32)
                padded[:n] = order
                (W, Wc, b, bc, hW, hWc, hb, hbc, ep_loss) = _glove_epoch(
                    W, Wc, b, bc, hW, hWc, hb, hbc, rows_d, cols_d,
                    logx_d, fx_d,
                    jnp.asarray(padded.reshape(n_chunks, B)), lr)
        #: monitored loss: the FINAL epoch's weighted-least-squares sum
        #: (the reference logs per-epoch GloVe loss); fetching it is also
        #: the fit's device completion barrier
        # dl4j-lint: disable=R7 one fetch per fit: logged loss doubles as the completion barrier
        self.last_epoch_loss = (float(np.asarray(ep_loss))
                                if self.epochs else None)

        # Final embedding: W + Wc (standard GloVe practice; the reference
        # exposes syn0)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, D, self.seed, use_hs=False, negative=1.0)
        self.lookup_table.syn0 = W + Wc
        self._context = Wc
        return self
