"""Bag-of-words / TF-IDF vectorizers.

TPU-native equivalent of the reference's ``bagofwords/vectorizer/``
(``BagOfWordsVectorizer.java``, ``TfidfVectorizer.java``): corpus scan ->
vocab, then text -> fixed-width count / tf-idf vectors, optionally paired
with labels as a classification :class:`DataSet`.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..datasets.dataset import DataSet
from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache, VocabConstructor


class BagOfWordsVectorizer:
    """Count vectors over a fixed vocab (reference
    ``BagOfWordsVectorizer``)."""

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Sequence[str] = ()):
        self.tokenizer_factory = tokenizer_factory \
            or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = set(stop_words)
        self.vocab: Optional[VocabCache] = None

    def _tokenize(self, text: str) -> List[str]:
        tokens = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in tokens if t not in self.stop_words]

    def fit(self, texts: Iterable[str]) -> "BagOfWordsVectorizer":
        seqs = [self._tokenize(t) for t in texts]
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency).build_vocab(seqs)
        self._post_fit(seqs)
        return self

    def _post_fit(self, seqs: List[List[str]]) -> None:
        pass

    def transform(self, text: str) -> np.ndarray:
        counts = Counter(self._tokenize(text))
        vec = np.zeros(self.vocab.num_words(), np.float32)
        for tok, c in counts.items():
            idx = self.vocab.index_of(tok)
            if idx >= 0:
                vec[idx] = self._weight(tok, c)
        return vec

    def _weight(self, token: str, count: int) -> float:
        return float(count)

    def fit_transform(self, texts: Sequence[str]) -> np.ndarray:
        self.fit(texts)
        return np.stack([self.transform(t) for t in texts])

    def vectorize(self, texts: Sequence[str],
                  labels: Sequence[int], n_classes: int) -> DataSet:
        """texts+labels -> classification DataSet (reference
        ``vectorize``)."""
        features = np.stack([self.transform(t) for t in texts])
        y = np.eye(n_classes, dtype=np.float32)[np.asarray(labels)]
        return DataSet(features, y)


class TfidfVectorizer(BagOfWordsVectorizer):
    """TF-IDF weighting (reference ``TfidfVectorizer.java``:
    idf = log(N / df), tf = raw count)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._idf: Optional[np.ndarray] = None

    def _post_fit(self, seqs: List[List[str]]) -> None:
        n_docs = max(len(seqs), 1)
        df = np.zeros(self.vocab.num_words(), np.float64)
        for seq in seqs:
            for tok in set(seq):
                idx = self.vocab.index_of(tok)
                if idx >= 0:
                    df[idx] += 1
        self._idf = np.log(n_docs / np.maximum(df, 1.0)).astype(np.float32)

    def _weight(self, token: str, count: int) -> float:
        return float(count) * float(self._idf[self.vocab.index_of(token)])
