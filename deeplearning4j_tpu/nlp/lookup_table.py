"""In-memory embedding lookup table.

TPU-native equivalent of the reference's
``models/embeddings/inmemory/InMemoryLookupTable.java`` (734 LoC): the
``syn0`` (input embeddings), ``syn1`` (hierarchical-softmax inner nodes) and
``syn1Neg`` (negative-sampling output embeddings) matrices plus the unigram
negative-sampling table.  The exp table is unnecessary — XLA computes real
sigmoids on the device.

Arrays are ``jax.Array``s living in device memory; the training kernels
(``word2vec.py``) update them functionally with scatter-adds inside one
jitted step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import VocabCache


class InMemoryLookupTable:
    """syn0/syn1/syn1neg store + derived sampling tables."""

    def __init__(self, vocab: VocabCache, vector_length: int = 100,
                 seed: int = 42, use_hs: bool = True, negative: float = 0.0,
                 dtype=jnp.float32):
        self.vocab = vocab
        self.vector_length = vector_length
        self.seed = seed
        self.use_hs = use_hs
        self.negative = negative
        self.dtype = dtype
        self.syn0: Optional[jax.Array] = None
        self.syn1: Optional[jax.Array] = None
        self.syn1neg: Optional[jax.Array] = None
        self._neg_table: Optional[np.ndarray] = None

    def reset_weights(self) -> None:
        """word2vec init: syn0 ~ U(-0.5, 0.5)/dim; syn1* zero (reference
        ``InMemoryLookupTable.resetWeights``)."""
        v = max(self.vocab.num_words(), 1)
        d = self.vector_length
        key = jax.random.PRNGKey(self.seed)
        self.syn0 = ((jax.random.uniform(key, (v, d), jnp.float32) - 0.5)
                     / d).astype(self.dtype)
        if self.use_hs:
            self.syn1 = jnp.zeros((max(v - 1, 1), d), self.dtype)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((v, d), self.dtype)

    # ---------------------------------------------------- negative sampling
    def negative_table(self, size: int = 1_000_000,
                       power: float = 0.75) -> np.ndarray:
        """Unigram^0.75 sampling table (reference ``makeTable``) — host-side
        numpy; negatives are drawn on host per batch and shipped with it."""
        if self._neg_table is None or self._neg_table.size != size:
            words = self.vocab.vocab_words()
            freqs = np.array([w.element_frequency for w in words],
                             np.float64)
            probs = freqs ** power
            probs /= probs.sum()
            counts = np.maximum(1, np.round(probs * size)).astype(np.int64)
            self._neg_table = np.repeat(np.arange(len(words)), counts)
        return self._neg_table

    # ------------------------------------------------------------- lookups
    def vector(self, word: str) -> Optional[np.ndarray]:
        idx = self.vocab.index_of(word)
        if idx < 0 or self.syn0 is None:
            return None
        return np.asarray(self.syn0[idx])

    def set_vector(self, word: str, vec) -> None:
        idx = self.vocab.index_of(word)
        if idx < 0:
            raise KeyError(word)
        self.syn0 = self.syn0.at[idx].set(jnp.asarray(vec, self.syn0.dtype))

    def weights(self) -> np.ndarray:
        return np.asarray(self.syn0)
