"""SequenceVectors / Word2Vec: embedding training with XLA kernels.

TPU-native equivalent of the reference's
``models/sequencevectors/SequenceVectors.java:179`` (the fit pipeline:
vocab build -> Huffman -> windowed training), ``models/word2vec/
Word2Vec.java`` (598 LoC builder API) and the learning algorithms
``models/embeddings/learning/impl/elements/SkipGram.java`` /
``CBOW.java``.

The hot loop: the reference dispatches every (center, context) pair to the
native ND4J ``AggregateSkipGram`` C++ op (``SkipGram.java:258``).  The
TPU-native redesign batches thousands of pairs and executes ONE jitted XLA
step per batch: embedding gathers, a (B, L, D) dot-product block on the MXU,
and scatter-adds back into syn0/syn1/syn1neg — duplicates accumulate
correctly because XLA scatter-add is atomic per index.  Pair generation
(window sampling, frequent-word subsampling, negative drawing from the
unigram table) stays on host, exactly the role of the reference's per-thread
Java loop that feeds the native op.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.scatter import scatter_add_agg
from .lookup_table import InMemoryLookupTable
from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache, VocabConstructor, build_huffman_tree

Array = jax.Array


# --------------------------------------------------------------------------
# XLA kernels
# --------------------------------------------------------------------------


def _hs_update(syn0: Array, syn1: Array, inputs: Array, points: Array,
               codes: Array, code_mask: Array, pair_mask: Array,
               lr: Array):
    """Hierarchical-softmax batch update math (shared by the jitted
    ``_hs_step`` and the on-device corpus pipeline's scan body).

    inputs (B,): syn0 rows (the context word in skip-gram; the averaged
    window is handled by the CBOW kernel).  points/codes/code_mask (B, L):
    the target word's Huffman path.  word2vec update: for each inner node,
    g = (1 - code - sigmoid(h.w)) * lr; syn1 += g h; h += sum g w.
    """
    h = syn0[inputs]                                   # (B, D)
    w = syn1[points]                                   # (B, L, D)
    logits = jnp.einsum("bd,bld->bl", h, w)
    mask = code_mask * pair_mask[:, None]
    g = (1.0 - codes - jax.nn.sigmoid(logits)) * mask * lr
    dh = jnp.einsum("bl,bld->bd", g, w)
    # unique-row aggregated scatters (ops/scatter.py): Huffman paths
    # share inner nodes heavily (every pair hits the root), so the
    # duplicate-row sums collapse before ONE sorted-unique scatter per
    # table; g is already masked, so dead rows carry zero payload
    syn1 = scatter_add_agg(syn1, points, g[:, :, None] * h[:, None, :])
    syn0 = scatter_add_agg(syn0, inputs, dh)
    # Monitored loss: BCE over the path, sign-folded logits.
    loss = -jnp.sum(jax.nn.log_sigmoid((1.0 - 2.0 * codes) * logits) * mask)
    return syn0, syn1, loss


_hs_step = jax.jit(_hs_update, donate_argnums=(0, 1))


def _ns_update(syn0: Array, syn1neg: Array, inputs: Array, targets: Array,
               labels: Array, target_mask: Array, pair_mask: Array,
               lr: Array):
    """Negative-sampling batch update math (the ``AggregateSkipGram``
    role; shared by the jitted ``_ns_step`` and the device pipeline).

    targets (B, 1+K): positive word then K negatives; labels (1+K,) is
    [1, 0, ..., 0].  target_mask (B, 1+K) zeroes residual negative-sample
    collisions with the positive (word2vec skips target==positive draws).
    """
    h = syn0[inputs]                                   # (B, D)
    w = syn1neg[targets]                               # (B, 1+K, D)
    logits = jnp.einsum("bd,bkd->bk", h, w)
    mask = target_mask * pair_mask[:, None]
    g = (labels[None, :] - jax.nn.sigmoid(logits)) * mask * lr
    dh = jnp.einsum("bk,bkd->bd", g, w)
    # aggregated scatters: negative draws repeat hot unigram rows, and
    # inputs repeat within a window's pair block (ops/scatter.py)
    syn1neg = scatter_add_agg(syn1neg, targets,
                              g[:, :, None] * h[:, None, :])
    syn0 = scatter_add_agg(syn0, inputs, dh)
    loss = -jnp.sum(jax.nn.log_sigmoid(
        jnp.where(labels[None, :] > 0, logits, -logits)) * mask)
    return syn0, syn1neg, loss


_ns_step = jax.jit(_ns_update, donate_argnums=(0, 1))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cbow_hs_step(syn0: Array, syn1: Array, contexts: Array,
                  context_mask: Array, points: Array, codes: Array,
                  code_mask: Array, pair_mask: Array, lr: Array):
    """CBOW + HS: input is the mean of the window's vectors; the input-side
    gradient is distributed to every context word (reference CBOW.java)."""
    cvecs = syn0[contexts]                             # (B, C, D)
    counts = jnp.maximum(jnp.sum(context_mask, axis=1, keepdims=True), 1.0)
    h = jnp.einsum("bcd,bc->bd", cvecs, context_mask) / counts
    w = syn1[points]
    logits = jnp.einsum("bd,bld->bl", h, w)
    mask = code_mask * pair_mask[:, None]
    g = (1.0 - codes - jax.nn.sigmoid(logits)) * mask * lr
    # Only the forward hidden is averaged; the full neu1e is added to every
    # context word (word2vec.c / reference AggregateCBOW semantics).
    dh = jnp.einsum("bl,bld->bd", g, w)                # (B, D)
    syn1 = syn1.at[points].add(g[:, :, None] * h[:, None, :])
    syn0 = syn0.at[contexts].add(dh[:, None, :] * context_mask[:, :, None])
    loss = -jnp.sum(jax.nn.log_sigmoid((1.0 - 2.0 * codes) * logits) * mask)
    return syn0, syn1, loss


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cbow_ns_step(syn0: Array, syn1neg: Array, contexts: Array,
                  context_mask: Array, targets: Array, labels: Array,
                  target_mask: Array, pair_mask: Array, lr: Array):
    cvecs = syn0[contexts]
    counts = jnp.maximum(jnp.sum(context_mask, axis=1, keepdims=True), 1.0)
    h = jnp.einsum("bcd,bc->bd", cvecs, context_mask) / counts
    w = syn1neg[targets]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    mask = target_mask * pair_mask[:, None]
    g = (labels[None, :] - jax.nn.sigmoid(logits)) * mask * lr
    dh = jnp.einsum("bk,bkd->bd", g, w)
    syn1neg = syn1neg.at[targets].add(g[:, :, None] * h[:, None, :])
    syn0 = syn0.at[contexts].add(dh[:, None, :] * context_mask[:, :, None])
    loss = -jnp.sum(jax.nn.log_sigmoid(
        jnp.where(labels[None, :] > 0, logits, -logits)) * mask)
    return syn0, syn1neg, loss


# --------------------------------------------------------------------------
# SequenceVectors
# --------------------------------------------------------------------------


class SequenceVectors:
    """Generic embedding trainer over sequences of tokens (reference
    ``SequenceVectors.java``; Word2Vec/ParagraphVectors/DeepWalk build on
    it).

    Builder-style kwargs mirror the reference
    ``SequenceVectors.Builder`` / ``Word2Vec.Builder``:
    ``layer_size`` (layerSize), ``window_size`` (windowSize),
    ``min_word_frequency``, ``iterations``, ``epochs``, ``learning_rate``
    (0.025), ``min_learning_rate`` (1e-4), ``negative`` (negative samples; 0
    = off), ``use_hierarchic_softmax``, ``sampling`` (frequent-word
    subsampling threshold; 0 = off), ``batch_size`` (pairs per XLA step),
    ``elements_learning_algorithm`` ("skipgram" | "cbow"), ``seed``.
    """

    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, iterations: int = 1,
                 epochs: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: float = 0.0,
                 use_hierarchic_softmax: bool = True, sampling: float = 0.0,
                 batch_size: int = 2048, seed: int = 42,
                 elements_learning_algorithm: str = "skipgram",
                 max_code_length: int = 40,
                 pair_generation: str = "auto"):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = elements_learning_algorithm.lower()
        self.max_code_length = max_code_length
        if pair_generation not in ("auto", "host", "device"):
            raise ValueError(
                f"unknown pair_generation {pair_generation!r}; expected "
                "'auto', 'host', or 'device'")
        self.pair_generation = pair_generation
        if not self.use_hs and self.negative <= 0:
            raise ValueError(
                "Enable hierarchical softmax and/or negative sampling")

        self.vocab: Optional[VocabCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._rng = np.random.RandomState(seed)
        self._code_arrays = None
        self._reset_queues()  # cross-sequence pair accumulators

    # ----------------------------------------------------------- vocab prep
    def build_vocab(self, sequences: Iterable[Sequence[str]]) -> None:
        constructor = VocabConstructor(
            min_word_frequency=self.min_word_frequency)
        self.vocab = constructor.build_vocab(sequences)
        if self.use_hs:
            build_huffman_tree(self.vocab,
                               max_code_length=self.max_code_length)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, self.seed, self.use_hs,
            self.negative)
        self.lookup_table.reset_weights()
        self._prepare_code_arrays()

    def _prepare_code_arrays(self) -> None:
        """Pack per-word Huffman codes/points into dense (V, L) arrays for
        device gathers."""
        if not self.use_hs:
            self._code_arrays = None
            return
        words = self.vocab.vocab_words()
        L = max((len(w.codes) for w in words), default=1)
        L = max(L, 1)
        V = len(words)
        points = np.zeros((V, L), np.int32)
        codes = np.zeros((V, L), np.float32)
        mask = np.zeros((V, L), np.float32)
        for w in words:
            n = len(w.codes)
            points[w.index, :n] = w.points
            codes[w.index, :n] = w.codes
            mask[w.index, :n] = 1.0
        self._code_arrays = (jnp.asarray(points), jnp.asarray(codes),
                             jnp.asarray(mask))

    # ------------------------------------------------------- pair generation
    def _subsample_keep(self, indices: np.ndarray) -> np.ndarray:
        """Frequent-word subsampling filter (word2vec: keep prob
        (sqrt(f/(sample*total)) + 1) * sample*total/f)."""
        if self.sampling <= 0:
            return indices
        words = self.vocab.vocab_words()
        freqs = np.array([words[i].element_frequency for i in indices])
        total = self.vocab.total_word_count
        ratio = self.sampling * total / np.maximum(freqs, 1.0)
        keep_prob = np.minimum(1.0, np.sqrt(ratio) + ratio)
        return indices[self._rng.rand(indices.size) < keep_prob]

    def _sequence_to_indices(self, seq: Sequence[str]) -> np.ndarray:
        idx = [self.vocab.index_of(t) for t in seq]
        return np.array([i for i in idx if i >= 0], np.int64)

    def _generate_pairs(self, indices: np.ndarray):
        """(input_word, target_word) skip-gram pairs with per-center dynamic
        window shrink b ~ U[0, window) (word2vec semantics: input = context
        word, target = center word whose codes are trained)."""
        n = indices.size
        if n < 2:
            return np.empty((0, 2), np.int64)
        bs = self._rng.randint(0, self.window_size, n)
        pairs = []
        for pos in range(n):
            center = indices[pos]
            start = max(0, pos - self.window_size + bs[pos])
            end = min(n, pos + self.window_size - bs[pos] + 1)
            for j in range(start, end):
                if j != pos:
                    pairs.append((indices[j], center))
        return np.array(pairs, np.int64)

    def _generate_cbow(self, indices: np.ndarray):
        """(context window, center) examples for CBOW."""
        n = indices.size
        C = 2 * self.window_size
        if n < 2:
            return (np.empty((0, C), np.int64), np.empty((0, C), np.float32),
                    np.empty((0,), np.int64))
        bs = self._rng.randint(0, self.window_size, n)
        ctx = np.zeros((n, C), np.int64)
        cmask = np.zeros((n, C), np.float32)
        keep = []
        for pos in range(n):
            start = max(0, pos - self.window_size + bs[pos])
            end = min(n, pos + self.window_size - bs[pos] + 1)
            k = 0
            for j in range(start, end):
                if j != pos:
                    ctx[pos, k] = indices[j]
                    cmask[pos, k] = 1.0
                    k += 1
            if k:
                keep.append(pos)
        keep = np.array(keep, np.int64)
        return ctx[keep], cmask[keep], indices[keep]

    # ------------------------------------------------------------- training
    #: "auto" routes to the on-device pipeline above this many corpus
    #: words (compile cost amortizes; tiny corpora keep the host path's
    #: sequential-update fidelity).
    DEVICE_PIPELINE_MIN_WORDS = 100_000

    def _device_eligible(self, seq_list) -> bool:
        if self.algorithm not in ("skipgram", "cbow"):
            return False
        if self.pair_generation == "host":
            return False
        # Subclasses that customize ANY hook of the feeding loop keep
        # their loop — the device scan would silently bypass overrides.
        for hook in ("_train_sequence", "_generate_pairs",
                     "_subsample_keep", "_sequence_to_indices",
                     "_draw_negatives", "_skipgram_batch",
                     "_generate_cbow", "_cbow_batch"):
            if getattr(type(self), hook) is not getattr(SequenceVectors,
                                                        hook):
                return False
        if self.pair_generation == "device":
            return True
        n = sum(len(s) for s in seq_list)
        return n >= self.DEVICE_PIPELINE_MIN_WORDS

    def _device_conf_key(self):
        """Everything the device pipeline bakes in at construction plus
        the routing knobs: a change to any of these must invalidate the
        pipeline cache (learning_rate/epochs/iterations are re-read per
        pass and may change freely)."""
        return (self.window_size, self.negative, self.use_hs,
                self.sampling, self.batch_size, self.seed,
                self.pair_generation, self.algorithm)

    def _run_device_passes(self, pipe) -> Dict:
        """Run epochs x iterations passes on a device pipeline and
        return THIS fit's stats (deltas — the pipeline's counters span
        its cached lifetime)."""
        passes = self.epochs * self.iterations
        total_words = pipe.n_words * passes
        prev_pairs, prev_loss = pipe.pairs_trained, pipe.loss_sum
        for p in range(passes):
            pipe.run_pass(p, total_words)
        pipe.finish()
        return {"pairs_trained": pipe.pairs_trained - prev_pairs,
                "loss_sum": pipe.loss_sum - prev_loss, "passes": passes}

    def _device_word_pipe(self, seq_list, source=None, seqs_idx=None):
        """The (possibly cached) word-side device pipeline, WITHOUT
        running passes — callers that interleave several pipelines
        (ParagraphVectors) drive run_pass themselves.  Cache keyed on
        the caller's ``sequences`` object identity + vocab + baked
        config (mutating the same sequence object in place between fits
        is not detected — the ingest-cache posture: data is immutable
        while training on it)."""
        from .device_corpus import DeviceSkipGram
        conf_key = self._device_conf_key()
        cached = getattr(self, "_device_fit_cache", None)
        if (cached is not None and source is not None
                and cached[0] is source and cached[1] is self.vocab
                and cached[2] == conf_key):
            return cached[3]
        seqs = (seqs_idx if seqs_idx is not None else
                [self._sequence_to_indices(s) for s in seq_list])
        seqs = [s for s in seqs if s.size >= 2]
        if not seqs:
            return None
        pipe = DeviceSkipGram(self, seqs)
        if source is not None:
            self._device_fit_cache = (source, self.vocab, conf_key, pipe)
        return pipe

    def _fit_device(self, seq_list, source=None,
                    seqs_idx=None) -> "SequenceVectors":
        """On-device corpus pipeline: one scan dispatch per corpus pass
        (see ``nlp/device_corpus.py``); the built pipeline caches
        across fit() calls — re-fitting the same corpus skips the
        ~0.3 s/M-words host re-indexing and the corpus re-upload."""
        pipe = self._device_word_pipe(seq_list, source, seqs_idx)
        if pipe is None:
            return self
        stats = self._run_device_passes(pipe)
        stats.update(span=pipe.span, n_spans=pipe.n_spans)
        self._device_pipeline_stats = stats
        return self

    def fit(self, sequences) -> "SequenceVectors":
        """The reference fit pipeline (``SequenceVectors.java:179``):
        build vocab -> Huffman -> train ``epochs`` passes.

        Skip-gram corpora route through the on-device pair-generation
        pipeline (``pair_generation="auto"|"device"``; window sampling,
        subsampling and negative draws all on-chip — the reference's
        feeding loop around ``SkipGram.java:258`` moved onto the
        device, for both skip-gram and CBOW element algorithms); small
        corpora and subclassed feeding loops use the host loop."""
        cached = getattr(self, "_device_fit_cache", None)
        if (cached is not None and cached[0] is sequences
                and cached[1] is self.vocab
                and cached[2] == self._device_conf_key()):
            return self._fit_device(None, source=sequences)
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        self._reset_queues()  # drop stale pairs from an aborted prior fit
        if self._device_eligible(seq_list):
            return self._fit_device(seq_list, source=sequences)
        total_words = sum(len(s) for s in seq_list) * self.epochs \
            * self.iterations
        words_seen = 0
        for _ in range(self.epochs):
            for seq in seq_list:
                for _ in range(self.iterations):
                    words_seen += len(seq)
                    alpha = max(
                        self.min_learning_rate,
                        self.learning_rate
                        * (1.0 - words_seen / max(total_words + 1, 1)))
                    self._train_sequence(seq, alpha)
        self._flush_queues()
        return self

    def _train_sequence(self, seq: Sequence[str], alpha: float) -> None:
        indices = self._sequence_to_indices(seq)
        indices = self._subsample_keep(indices)
        if indices.size < 2:
            return
        if self.algorithm == "cbow":
            ctx, cmask, centers = self._generate_cbow(indices)
            if centers.size:
                self._queue_cbow(ctx, cmask, centers, alpha)
            return
        pairs = self._generate_pairs(indices)
        if pairs.size:
            self._queue_skipgram(pairs[:, 0], pairs[:, 1], alpha)

    # -------------------------------------------- cross-sequence batching
    # A short sentence/document must not cost a whole device dispatch:
    # pairs accumulate across sequences and dispatch in full
    # ``batch_size`` chunks (the per-dispatch lr is the mean alpha of the
    # chunk's pairs — alpha decays slowly, so this matches the reference's
    # per-pair schedule to within one batch).  The leftover partial chunk
    # flushes at the end of fit().

    def _queue_skipgram(self, inputs: np.ndarray, targets: np.ndarray,
                        alpha: float) -> None:
        self._sg_queue.append((inputs.astype(np.int64),
                               targets.astype(np.int64),
                               np.full(inputs.size, alpha, np.float64)))
        self._sg_count += inputs.size
        if self._sg_count >= self.batch_size:
            self._drain_skipgram(force=False)

    def _queue_cbow(self, ctx: np.ndarray, cmask: np.ndarray,
                    centers: np.ndarray, alpha: float) -> None:
        self._cb_queue.append((ctx.astype(np.int64),
                               cmask.astype(np.float32),
                               centers.astype(np.int64),
                               np.full(centers.size, alpha, np.float64)))
        self._cb_count += centers.size
        if self._cb_count >= self.batch_size:
            self._drain_cbow(force=False)

    def _effective_batch(self) -> int:
        """Pairs per XLA step, clamped so duplicate-row scatter-adds stay
        bounded: word2vec.c applies updates SEQUENTIALLY, so a word hit k
        times sees k small self-correcting steps; one batched scatter-add
        applies k gradients computed at the same stale point — an
        effective k×lr that diverges once batch ≫ vocabulary (a 13-word
        toy corpus at batch 2048 reached norm 1e18).  Clamping the chunk
        to ~2×vocab keeps expected duplicates per row at ~2 (measured
        stable AND quality-preserving on small corpora) while leaving
        realistic vocabularies (vocab ≥ batch/2) at full batch size."""
        v = self.vocab.num_words() if hasattr(self, "vocab") and \
            self.vocab is not None else 0
        if v <= 0:
            return self.batch_size
        return int(min(self.batch_size, max(64, 2 * v)))

    def _drain_skipgram(self, force: bool) -> None:
        if not self._sg_count:
            return
        ins = np.concatenate([q[0] for q in self._sg_queue])
        tgts = np.concatenate([q[1] for q in self._sg_queue])
        alphas = np.concatenate([q[2] for q in self._sg_queue])
        B = self._effective_batch()
        s = 0
        while ins.size - s >= B or (force and s < ins.size):
            sl = slice(s, s + B)
            self._skipgram_batch(ins[sl], tgts[sl],
                                 float(alphas[sl].mean()))
            s += B
        self._sg_queue = ([] if s >= ins.size
                          else [(ins[s:], tgts[s:], alphas[s:])])
        self._sg_count = max(0, ins.size - s)

    def _drain_cbow(self, force: bool) -> None:
        if not self._cb_count:
            return
        width = max(q[0].shape[1] for q in self._cb_queue)

        def _w(a, fill):
            pad = width - a.shape[1]
            if not pad:
                return a
            return np.concatenate(
                [a, np.full((a.shape[0], pad), fill, a.dtype)], axis=1)

        ctx = np.concatenate([_w(q[0], 0) for q in self._cb_queue])
        cmask = np.concatenate([_w(q[1], 0.0) for q in self._cb_queue])
        ctrs = np.concatenate([q[2] for q in self._cb_queue])
        alphas = np.concatenate([q[3] for q in self._cb_queue])
        B = self._effective_batch()
        s = 0
        while ctrs.size - s >= B or (force and s < ctrs.size):
            sl = slice(s, s + B)
            self._cbow_batch(ctx[sl], cmask[sl], ctrs[sl],
                             float(alphas[sl].mean()))
            s += B
        self._cb_queue = ([] if s >= ctrs.size
                          else [(ctx[s:], cmask[s:], ctrs[s:], alphas[s:])])
        self._cb_count = max(0, ctrs.size - s)

    def _flush_queues(self) -> None:
        self._drain_skipgram(force=True)
        self._drain_cbow(force=True)

    def _reset_queues(self) -> None:
        self._sg_queue = []
        self._sg_count = 0
        self._cb_queue = []
        self._cb_count = 0

    def _pad(self, arr: np.ndarray, size: int):
        """Pad the leading axis to ``size`` (static XLA shapes) and return
        (padded, pair_mask)."""
        n = arr.shape[0]
        mask = np.zeros(size, np.float32)
        mask[:n] = 1.0
        if n == size:
            return arr, mask
        pad = np.zeros((size - n,) + arr.shape[1:], arr.dtype)
        return np.concatenate([arr, pad]), mask

    def _draw_negatives(self, positives: np.ndarray, B: int):
        """Draw K negatives per row from the unigram table; collisions with
        the positive are resampled once, residual collisions are masked out
        entirely (word2vec skips target==positive draws)."""
        table = self.lookup_table.negative_table()
        K = int(self.negative)
        negs = table[self._rng.randint(0, table.size, (B, K))]
        collide = negs == positives[:, None]
        if collide.any():
            negs[collide] = table[self._rng.randint(
                0, table.size, int(collide.sum()))]
        tgt = np.concatenate([positives[:, None], negs], axis=1)
        tmask = np.ones((B, 1 + K), np.float32)
        tmask[:, 1:] = (negs != positives[:, None]).astype(np.float32)
        labels = jnp.asarray(
            np.concatenate([[1.0], np.zeros(K)]).astype(np.float32))
        return (jnp.asarray(tgt.astype(np.int32)), labels,
                jnp.asarray(tmask))

    def _skipgram_batch(self, inputs: np.ndarray, targets: np.ndarray,
                        alpha: float) -> None:
        lt = self.lookup_table
        B = self._effective_batch()
        inputs_p, pair_mask = self._pad(inputs.astype(np.int32), B)
        targets_p, _ = self._pad(targets.astype(np.int32), B)
        lr = jnp.float32(alpha)
        if self.use_hs:
            points, codes, cmask = self._code_arrays
            lt.syn0, lt.syn1, _ = _hs_step(
                lt.syn0, lt.syn1, jnp.asarray(inputs_p),
                points[targets_p], codes[targets_p], cmask[targets_p],
                jnp.asarray(pair_mask), lr)
        if self.negative > 0:
            tgt, labels, tmask = self._draw_negatives(targets_p, B)
            lt.syn0, lt.syn1neg, _ = _ns_step(
                lt.syn0, lt.syn1neg, jnp.asarray(inputs_p), tgt, labels,
                tmask, jnp.asarray(pair_mask), lr)

    def _cbow_batch(self, ctx: np.ndarray, cmask: np.ndarray,
                    centers: np.ndarray, alpha: float) -> None:
        lt = self.lookup_table
        B = self._effective_batch()
        ctx_p, pair_mask = self._pad(ctx.astype(np.int32), B)
        cmask_p, _ = self._pad(cmask, B)
        centers_p, _ = self._pad(centers.astype(np.int32), B)
        lr = jnp.float32(alpha)
        if self.use_hs:
            points, codes, hmask = self._code_arrays
            lt.syn0, lt.syn1, _ = _cbow_hs_step(
                lt.syn0, lt.syn1, jnp.asarray(ctx_p), jnp.asarray(cmask_p),
                points[centers_p], codes[centers_p], hmask[centers_p],
                jnp.asarray(pair_mask), lr)
        if self.negative > 0:
            tgt, labels, tmask = self._draw_negatives(centers_p, B)
            lt.syn0, lt.syn1neg, _ = _cbow_ns_step(
                lt.syn0, lt.syn1neg, jnp.asarray(ctx_p),
                jnp.asarray(cmask_p), tgt, labels, tmask,
                jnp.asarray(pair_mask), lr)

    # --------------------------------------------------- WordVectors API
    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        return (self.lookup_table.vector(word)
                if self.lookup_table else None)

    getWordVectorMatrix = word_vector  # reference-name alias

    def similarity(self, w1: str, w2: str) -> float:
        """Cosine similarity (reference ``similarity``); NaN if missing."""
        a, b = self.word_vector(w1), self.word_vector(w2)
        if a is None or b is None:
            return float("nan")
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 0.0
        return float(np.dot(a, b) / (na * nb))

    def words_nearest(self, word_or_vec, negative=None,
                      top_n: int = 10) -> List[str]:
        """Nearest neighbors by cosine (reference ``wordsNearest``).

        Also accepts the analogy form (reference
        ``wordsNearest(positive, negative, top)`` /
        ``wordsNearestSum``): a list of positive words plus an optional
        list of negatives — e.g. ``words_nearest(["king", "woman"],
        ["man"])`` — queried as sum(positive) - sum(negative), with the
        query words excluded from the result."""
        if isinstance(negative, int):       # words_nearest(word, 5) form
            top_n, negative = negative, None
        if isinstance(negative, str):       # single negative word
            negative = [negative]
        if negative:
            if isinstance(word_or_vec, str):
                word_or_vec = [word_or_vec]
            elif not isinstance(word_or_vec, (list, tuple)):
                raise ValueError(
                    "negative words require word-name positives, not a "
                    "raw vector")
        if isinstance(word_or_vec, str):
            vec = self.word_vector(word_or_vec)
            exclude = {word_or_vec}
            if vec is None:
                return []
        elif isinstance(word_or_vec, (list, tuple)) \
                and word_or_vec and isinstance(word_or_vec[0], str):
            pos = [self.word_vector(w) for w in word_or_vec]
            neg = [self.word_vector(w) for w in (negative or [])]
            if any(v is None for v in pos + neg):
                return []
            vec = np.sum(pos, axis=0)
            if neg:
                vec = vec - np.sum(neg, axis=0)
            exclude = set(word_or_vec) | set(negative or [])
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        m = self.lookup_table.weights()
        norms = np.linalg.norm(m, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = m @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for idx in order:
            w = self.vocab.word_at_index(int(idx))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out

    # reference wordsNearestSum: same additive-combination query
    words_nearest_sum = words_nearest

    def similar_words_in_vocab_to(self, word: str,
                                  accuracy: float) -> List[str]:
        """Vocab words whose string similarity to ``word`` is >=
        ``accuracy`` (reference ``similarWordsInVocabTo`` /
        ``MathUtils.stringSimilarity``)."""
        import difflib
        if self.vocab is None:
            return []
        # one matcher, query cached as seq2 (the side difflib indexes);
        # quick-ratio upper bounds prune before the quadratic ratio()
        sm = difflib.SequenceMatcher(None)
        sm.set_seq2(word)
        out = []
        for w in self.vocab.words():
            sm.set_seq1(w)
            if sm.real_quick_ratio() >= accuracy \
                    and sm.quick_ratio() >= accuracy \
                    and sm.ratio() >= accuracy:
                out.append(w)
        return out

    def word_vectors(self, words) -> np.ndarray:
        """(n, layer_size) matrix of the vectors for the given words,
        skipping out-of-vocab entries (reference ``getWordVectors``)."""
        vecs = [self.word_vector(w) for w in words]
        vecs = [v for v in vecs if v is not None]
        if not vecs:
            return np.zeros((0, self.layer_size), np.float32)
        return np.stack(vecs)

    def word_vectors_mean(self, words) -> np.ndarray:
        """Mean vector over in-vocab words (reference
        ``getWordVectorsMean``)."""
        m = self.word_vectors(words)
        if m.shape[0] == 0:
            return np.zeros((self.layer_size,), np.float32)
        return m.mean(axis=0)


class Word2Vec(SequenceVectors):
    """Word2Vec over text corpora (reference ``models/word2vec/
    Word2Vec.java``): a SequenceVectors whose sequences come from a sentence
    iterator + tokenizer factory."""

    def __init__(self, iterate=None,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Sequence[str] = (), **kwargs):
        kwargs.setdefault("min_word_frequency", 5)
        super().__init__(**kwargs)
        self.sentence_iterator = iterate
        self.tokenizer_factory = tokenizer_factory \
            or DefaultTokenizerFactory()
        self.stop_words = set(stop_words)

    # Builder parity (reference Word2Vec.Builder().iterate(...).build())
    class Builder:
        def __init__(self):
            self._kw: Dict = {}
            self._iterate = None
            self._tokenizer = None

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self
            return setter

        def iterate(self, it):
            self._iterate = it
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(iterate=self._iterate,
                            tokenizer_factory=self._tokenizer, **self._kw)

    def _sentences_to_sequences(self, sentences: Iterable[str]):
        for sentence in sentences:
            tokens = self.tokenizer_factory.create(sentence).get_tokens()
            if self.stop_words:
                tokens = [t for t in tokens if t not in self.stop_words]
            if tokens:
                yield tokens

    def fit(self, sentences=None) -> "Word2Vec":
        source = sentences if sentences is not None \
            else self.sentence_iterator
        if source is None:
            raise ValueError("No sentence source; pass `iterate` or call "
                             "fit(sentences)")
        if isinstance(source, (list, tuple)) and source \
                and not isinstance(source[0], str):
            sequences = source  # pre-tokenized
        else:
            sequences = list(self._sentences_to_sequences(source))
        return super().fit(sequences)
