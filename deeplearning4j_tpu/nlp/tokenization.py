"""Tokenizer SPI.

TPU-native equivalent of the reference's
``deeplearning4j-nlp/.../text/tokenization/`` tier: ``Tokenizer`` /
``TokenizerFactory`` interfaces (``tokenizerfactory/DefaultTokenizerFactory
.java``, ``NGramTokenizerFactory.java``) and token preprocessors
(``tokenizer/preprocessor/CommonPreprocessor.java``,
``EndingPreProcessor.java``).

Pure host-side text processing — tokenization feeds the vocab build and the
device-side training kernels; it never enters the XLA graph.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional, Sequence


class TokenPreProcess:
    """Reference ``tokenization/tokenizer/TokenPreProcess.java``."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference
    ``CommonPreprocessor.java``: removes ``[\\d.:,"'()\\[\\]|/?!;]``)."""

    _PATTERN = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PATTERN.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer (reference ``EndingPreProcessor.java``: strips s/ed/
    ing/ly endings)."""

    def pre_process(self, token: str) -> str:
        for ending in ("ing", "ed", "ly", "s"):
            if token.endswith(ending) and len(token) > len(ending) + 2:
                return token[: -len(ending)]
        return token


class StemmingPreprocessor(CommonPreprocessor):
    """Common cleanup + ending strip (reference chains porter stemming; the
    vendored snowball stemmer is out of scope)."""

    def pre_process(self, token: str) -> str:
        return EndingPreProcessor().pre_process(super().pre_process(token))


class Tokenizer:
    """Reference ``tokenization/tokenizer/Tokenizer.java`` — an iterator of
    tokens over one string."""

    def __init__(self, tokens: Sequence[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = list(tokens)
        self._preprocessor = preprocessor
        self._pos = 0

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._preprocessor = pre

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        tok = self._tokens[self._pos]
        self._pos += 1
        return self._preprocessor.pre_process(tok) if self._preprocessor \
            else tok

    def get_tokens(self) -> List[str]:
        out = []
        while self.has_more_tokens():
            tok = self.next_token()
            if tok:
                out.append(tok)
        return out

    def __iter__(self):
        while self.has_more_tokens():
            tok = self.next_token()
            if tok:
                yield tok


class TokenizerFactory:
    """Reference ``tokenizerfactory/TokenizerFactory.java``."""

    def __init__(self):
        self._preprocessor: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._preprocessor = pre

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference ``DefaultTokenizerFactory.java`` wraps
    Java's StringTokenizer on whitespace)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._preprocessor)


class NGramTokenizerFactory(TokenizerFactory):
    """Reference ``NGramTokenizerFactory.java``: emits n-grams (joined by
    space? the reference joins with a space) from min_n to max_n over the
    base tokenizer's tokens."""

    def __init__(self, base: Optional[TokenizerFactory] = None,
                 min_n: int = 1, max_n: int = 2):
        super().__init__()
        self.base = base or DefaultTokenizerFactory()
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        tokens = self.base.create(text).get_tokens()
        grams: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(tokens) - n + 1):
                grams.append(" ".join(tokens[i:i + n]))
        return Tokenizer(grams, self._preprocessor)


# Reference ``text/stopwords/StopWords.java`` ships a canned English list;
# this is the standard minimal set.
DEFAULT_STOP_WORDS = frozenset("""
a an and are as at be but by for if in into is it no not of on or such that
the their then there these they this to was will with
""".split())


def filter_stop_words(tokens: Iterable[str],
                      stop_words=DEFAULT_STOP_WORDS) -> List[str]:
    return [t for t in tokens if t not in stop_words]
