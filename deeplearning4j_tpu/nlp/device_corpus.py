"""On-device skip-gram pair generation: the whole word2vec inner loop
as one ``lax.scan`` dispatch per corpus pass.

The round-4 end-to-end ``Word2Vec.fit()`` was bound by HOST pair
generation — ``SequenceVectors._generate_pairs`` is a Python
per-position loop, and negative draws were host numpy — so the real fit
ran orders of magnitude below the 11.8M pairs/s staged kernel rate.
This module moves the reference's feeding loop (the per-thread Java
loop around ``models/embeddings/learning/impl/elements/SkipGram.java:258``
that feeds the native ``AggregateSkipGram`` op) onto the chip:

- the tokenized corpus is uploaded ONCE as a flat int32 index array plus
  a sentence-id array (windows never cross sentence boundaries);
- frequent-word subsampling happens on device per pass (uniform draw
  against a per-word keep probability, then a cumsum/scatter compaction
  so windows close up over removed words — word2vec.c semantics);
- each scan step takes a chunk of positions, draws the per-center
  window shrink b ~ U[0, W) on device, forms the (B, 2W) context grid
  with offset/boundary/sentence masks, draws negatives from the
  device-resident unigram table, and applies the same HS/NS update math
  as the host path (shared ``_hs_update`` / ``_ns_update``);
- per-chunk learning rates follow the linear word-count decay schedule
  and are precomputed host-side as scan inputs.

Per-pass host traffic: one scalar fetch (the pair/loss counters used as
the completion barrier).  Semantics vs the host path: identical update
math and masking; the RNG stream differs (device threefry vs host
MT19937), per-chunk lr replaces per-sequence lr (the same compromise
the host path's cross-sequence batching already makes), and
``iterations`` repeats the whole corpus pass rather than each sequence
in place (alpha decays by words seen either way).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.scatter import scatter_add_agg
from .word2vec import _hs_update, _ns_update

Array = jax.Array


def build_corpus_arrays(seqs: List[np.ndarray],
                        chunk: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Flatten per-sequence index arrays into (corpus, sent_id) padded to
    a multiple of ``chunk``.  Padding positions get sent_id -1 so the
    same-sentence mask kills any pair touching them."""
    n = sum(s.size for s in seqs)
    npad = max(chunk, ((n + chunk - 1) // chunk) * chunk)
    corpus = np.zeros(npad, np.int32)
    sent = np.full(npad, -1, np.int32)
    pos = 0
    for i, s in enumerate(seqs):
        corpus[pos:pos + s.size] = s
        sent[pos:pos + s.size] = i
        pos += s.size
    return corpus, sent, n


def keep_probabilities(vocab, sampling: float) -> Optional[np.ndarray]:
    """Per-word subsampling keep probability (word2vec: keep prob
    min(1, sqrt(t/f') + t/f') with f' = freq/(sample*total)); None when
    subsampling is off."""
    if sampling <= 0:
        return None
    words = vocab.vocab_words()
    keep = np.ones(len(words), np.float32)
    total = vocab.total_word_count
    for w in words:
        ratio = sampling * total / max(w.element_frequency, 1.0)
        keep[w.index] = min(1.0, np.sqrt(ratio) + ratio)
    return keep


def window_offsets(window: int) -> np.ndarray:
    """The +-window offset row of the pair grid (0 excluded)."""
    return np.concatenate(
        [np.arange(-window, 0), np.arange(1, window + 1)]).astype(np.int32)


def pair_grid(corpus: Array, sent: Array, n_valid, start, shrink: Array,
              window: int, chunk: int):
    """(inputs, targets, pair_mask) for one chunk of center positions.

    For center position p with per-center window win = W - shrink[p]
    (word2vec's dynamic shrink), the grid row covers offsets
    [-W..-1, 1..W]; a cell is live iff |offset| <= win, both positions
    are in [0, n_valid), and the neighbor is in the same sentence.
    Shapes are static (chunk x 2W flattened) so the scan body compiles
    once.  Testable standalone against a brute-force host reference."""
    npad = corpus.shape[0]
    offsets = jnp.asarray(window_offsets(window))
    pos = start + jnp.arange(chunk)
    centers = jax.lax.dynamic_slice(corpus, (start,), (chunk,))
    csent = jax.lax.dynamic_slice(sent, (start,), (chunk,))
    win = window - shrink                           # in [1..W]
    nbr = pos[:, None] + offsets[None, :]           # (B, 2W)
    inb = (nbr >= 0) & (nbr < n_valid) & (pos < n_valid)[:, None]
    nbr_c = jnp.clip(nbr, 0, npad - 1)
    words = corpus[nbr_c]
    wsent = sent[nbr_c]
    pmask = (inb & (wsent == csent[:, None])
             & (jnp.abs(offsets)[None, :] <= win[:, None]))
    P = chunk * 2 * window
    inputs = words.reshape(P)                       # context = syn0 row
    targets = jnp.broadcast_to(
        centers[:, None], (chunk, 2 * window)).reshape(P)
    return inputs, targets, pmask.reshape(P).astype(jnp.float32)


def pair_grid_shaped(corpus_pad: Array, sent_pad: Array, start,
                     shrink: Array, window: int, span: int):
    """Gather-free pair grid over W-padded arrays (sentinel sent_id -1
    at both ends), kept in (span, 2W) grid shape: one dynamic_slice
    pulls the span's (span + 2W) region, then each window offset is a
    STATIC shifted slice of that region.  The random-gather formulation
    profiled at ~2 ms/span (65k scalar gathers); shifts are pure vector
    moves.  Center position p of the span maps to padded index
    start + W + p.  The sentinel handles every boundary: corpus ends,
    sentence ends, subsampling's compacted tail — a cell is live iff
    the center's sentence id is >= 0, the neighbor's matches it, and
    the offset is within the shrunk window.  Returns
    (words (span, 2W), centers (span,), pmask (span, 2W) f32)."""
    offsets = window_offsets(window)
    region_c = jax.lax.dynamic_slice(corpus_pad, (start,),
                                     (span + 2 * window,))
    region_s = jax.lax.dynamic_slice(sent_pad, (start,),
                                     (span + 2 * window,))
    centers = jax.lax.slice(region_c, (window,), (window + span,))
    csent = jax.lax.slice(region_s, (window,), (window + span,))
    words = jnp.stack(
        [jax.lax.slice(region_c, (window + int(o),),
                       (window + int(o) + span,)) for o in offsets],
        axis=1)                                       # (span, 2W)
    wsent = jnp.stack(
        [jax.lax.slice(region_s, (window + int(o),),
                       (window + int(o) + span,)) for o in offsets],
        axis=1)
    win = window - shrink
    pmask = ((csent >= 0)[:, None] & (wsent == csent[:, None])
             & (jnp.abs(jnp.asarray(offsets))[None, :] <= win[:, None]))
    return words, centers, pmask.astype(jnp.float32)


def pair_grid_shifted(corpus_pad: Array, sent_pad: Array, start,
                      shrink: Array, window: int, span: int):
    """Flattened view of :func:`pair_grid_shaped` matching
    :func:`pair_grid`'s (inputs, targets, pair_mask) contract —
    equivalence with the gather-based reference grid is test-asserted."""
    words, centers, pmask = pair_grid_shaped(
        corpus_pad, sent_pad, start, shrink, window, span)
    P = span * 2 * window
    inputs = words.reshape(P)
    targets = jnp.broadcast_to(
        centers[:, None], (span, 2 * window)).reshape(P)
    return inputs, targets, pmask.reshape(P)


def pad_with_sentinels(corpus: Array, sent: Array, window: int):
    """W sentinel entries (word 0, sent_id -1) on each side, for
    :func:`pair_grid_shifted`."""
    zc = jnp.zeros((window,), corpus.dtype)
    zs = jnp.full((window,), -1, sent.dtype)
    return (jnp.concatenate([zc, corpus, zc]),
            jnp.concatenate([zs, sent, zs]))


def compact_dest(keep: Array, npad: int) -> Array:
    """Scatter destinations for stable keep-compaction: kept position i
    goes to cumsum(keep)[i]-1, dropped positions to the out-of-range
    sentinel (mode='drop' discards them).  The ONE source of truth for
    the compaction scheme — corpus, sentence ids, and any aligned
    side-array (PV-DM's per-position labels) must all compact with the
    SAME dest or positions and labels drift apart."""
    tgt = jnp.cumsum(keep) - 1
    return jnp.where(keep, tgt, npad)


def compact_with(arr: Array, dest: Array, fill) -> Array:
    """Apply :func:`compact_dest` destinations to one aligned array."""
    return jnp.full_like(arr, fill).at[dest].set(arr, mode="drop")


def subsample_compact(corpus: Array, sent: Array, keep: Array):
    """Compact (corpus, sent) down to the kept positions (windows close
    up over removed words — word2vec.c subsampling semantics); dropped
    tail gets sentinel sent_id -1.  Returns (corpus, sent, n_valid)."""
    dest = compact_dest(keep, corpus.shape[0])
    return (compact_with(corpus, dest, 0), compact_with(sent, dest, -1),
            jnp.sum(keep))


def block_negative_table(table: np.ndarray, k: int,
                         seed: int) -> np.ndarray:
    """Shuffle the word2vec unigram table ONCE on host and fold it into
    (table_size // k, k) blocks.  The raw table is built in long
    per-word runs, so un-shuffled blocks would hold k copies of one
    word; after shuffling, every block is k draws-without-replacement
    from the unigram^0.75 multiset — statistically equivalent to
    word2vec's with-replacement draws for table_size >> k."""
    table = np.random.RandomState(seed).permutation(table)
    n = (table.size // k) * k
    return table[:n].reshape(-1, k).astype(np.int32)


def lcg_negatives(seed: Array, rows: int, k: int, table_2d: Array):
    """(rows, k) negative draws: one 32-bit LCG draw per row (the
    word2vec.c sampler family — ``next_random = next_random *
    25214903917 + 11`` there; Numerical-Recipes constants here, 32-bit
    for the TPU's native integer width) selecting one ROW of the
    pre-shuffled block table (:func:`block_negative_table`).

    Why this shape: per-element table gathers profiled at ~7 µs per 1k
    elements on this chip (123k gathers/span = 0.86 ms — comparable to
    the update kernel itself), threefry per-step draws cost ~0.3 ms
    more, and a vmap(dynamic_slice) contiguous-window formulation
    lowered to a 29 ms/span catastrophe.  Row gathers of a 2-D table
    are the embedding-lookup pattern the TPU does well.  Residual
    collisions with the positive are masked by the caller, exactly as
    word2vec skips target==positive draws."""
    state = (seed + jnp.arange(rows, dtype=jnp.uint32)
             * jnp.uint32(2654435761))           # Knuth hash spread
    state = state * jnp.uint32(1664525) + jnp.uint32(1013904223)
    n_blocks = table_2d.shape[0]
    base = ((state >> jnp.uint32(4))
            % jnp.uint32(n_blocks)).astype(jnp.int32)
    return table_2d[base]


@functools.lru_cache(maxsize=16)
def _epoch_fn(window: int, negative: int, use_hs: bool, span: int,
              seg_spans: int, total_spans: int, subsample: bool,
              npad: int, algorithm: str = "skipgram",
              has_labels: bool = False):
    """Build + jit the one-pass scan.  All shape-determining config is
    in the cache key; arrays are traced arguments.

    Structure per scan step (one SPAN of ``span`` center positions):
    draw window shrinks -> (span, 2W) pair grid -> ONE center-aggregated
    fused update.  The aggregation is the load-bearing trick: a center's
    2W grid cells all train the SAME target rows (its Huffman path / its
    negative draws), so their syn1-side contributions are summed with an
    einsum over the cell axis BEFORE the scatter — span*(1+K) (or
    span*L) scatter rows instead of span*2W*(1+K).  Scatter rows, not
    FLOPs, are what the TPU pays for here (profiled ~7M scatter
    rows/s vs ~100 MFLOP of einsum ≈ nothing), so this is ~2W x less
    scatter on the syn1 side; dead grid cells cost only MXU flops.
    Divergences from the per-pair host kernels, both documented and
    quality-tested: negatives are drawn per CENTER (shared by its <=2W
    pairs) rather than per pair — same expected gradient, slightly
    correlated draws within one center; and each center's cells see the
    center's syn1 rows at the span's start value (the same
    stale-read-within-batch compromise every batched scatter update in
    this module already makes)."""
    K = negative
    if has_labels and algorithm != "cbow":
        raise ValueError("label columns require the cbow branch (PV-DM)")

    def epoch(syn0, syn1, syn1neg, corpus, sent, pos_label, n_words,
              keep_prob, neg_table, hs_points, hs_codes, hs_cmask,
              alphas, key, span_offset):
        if subsample:
            key, sub = jax.random.split(key)
            r = jax.random.uniform(sub, corpus.shape)
            live = jnp.arange(npad) < n_words
            keep = (r < keep_prob[corpus]) & live
            corpus, sent, _ = subsample_compact(corpus, sent, keep)
            if has_labels:
                # labels compact with the SAME dest so each kept
                # position keeps its document's label row
                pos_label = compact_with(
                    pos_label, compact_dest(keep, npad), -1)
        corpus_pad, sent_pad = pad_with_sentinels(corpus, sent, window)
        span_keys = jax.random.split(key, seg_spans)

        cbow = algorithm == "cbow"

        def body(carry, xs):
            syn0, syn1, syn1neg, pair_count, loss_sum = carry
            c, alpha, ckey = xs
            kb, kn = jax.random.split(ckey)
            shrink = jax.random.randint(kb, (span,), 0, window)
            words, centers, pmask = pair_grid_shaped(
                corpus_pad, sent_pad, c * span, shrink, window, span)
            if has_labels:
                # PV-DM: the document label joins every window as one
                # extra always-live column (reference DM.java — label
                # appended to the context; a center whose window is
                # otherwise empty still trains from the label alone,
                # the host path's single-word-document fallback)
                labs = jax.lax.dynamic_slice(pos_label, (c * span,),
                                             (span,))
                words = jnp.concatenate(
                    [words, jnp.maximum(labs, 0)[:, None]], axis=1)
                pmask = jnp.concatenate(
                    [pmask,
                     (labs >= 0).astype(jnp.float32)[:, None]], axis=1)
            # segment overrun guard: a ragged final segment's extra span
            # indices get start-clamped slices of REAL tail data; the
            # validity mask turns them into no-ops
            pmask = pmask * (c < total_spans).astype(jnp.float32)
            hc = syn0[words]                       # (b, 2W[+1], d)
            if cbow:
                # CBOW: ONE example per center — h is the masked MEAN
                # of the window's vectors; the input-side gradient dh
                # goes to every context word un-divided
                # (word2vec.c / reference AggregateCBOW semantics,
                # host twin ``_cbow_hs_step``/``_cbow_ns_step``).
                counts = jnp.sum(pmask, axis=1)
                exmask = (counts > 0).astype(jnp.float32)   # (b,)
                h = (jnp.einsum("bcd,bc->bd", hc, pmask)
                     / jnp.maximum(counts, 1.0)[:, None])   # (b, d)
            loss = jnp.float32(0.0)
            d_syn0 = None
            if use_hs:
                pts = hs_points[centers]           # (b, L)
                cds = hs_codes[centers]
                cmk = hs_cmask[centers]
                w = syn1[pts]                      # (b, L, d)
                if cbow:
                    logits = jnp.einsum("bd,bld->bl", h, w)
                    g = ((1.0 - cds - jax.nn.sigmoid(logits))
                         * cmk * exmask[:, None] * alpha)
                    syn1 = scatter_add_agg(
                        syn1, pts, g[:, :, None] * h[:, None, :])
                    d_syn0 = jnp.einsum("bl,bld->bd", g, w)
                    loss = loss - jnp.sum(
                        jax.nn.log_sigmoid((1.0 - 2.0 * cds) * logits)
                        * cmk * exmask[:, None])
                else:
                    logits = jnp.einsum("bcd,bld->bcl", hc, w)
                    g = ((1.0 - cds[:, None, :] - jax.nn.sigmoid(logits))
                         * cmk[:, None, :] * pmask[:, :, None] * alpha)
                    syn1 = scatter_add_agg(
                        syn1, pts, jnp.einsum("bcl,bcd->bld", g, hc))
                    d_syn0 = jnp.einsum("bcl,bld->bcd", g, w)
                    loss = loss - jnp.sum(
                        jax.nn.log_sigmoid((1.0 - 2.0 * cds[:, None, :])
                                           * logits)
                        * cmk[:, None, :] * pmask[:, :, None])
            if K > 0:
                seed = jax.random.bits(kn, (), jnp.uint32)
                negs = lcg_negatives(seed, span, K, neg_table)
                tgt = jnp.concatenate([centers[:, None], negs], axis=1)
                tmask = jnp.concatenate(
                    [jnp.ones((span, 1), jnp.float32),
                     (negs != centers[:, None]).astype(jnp.float32)],
                    axis=1)                        # (b, 1+K)
                lbl = jnp.concatenate(
                    [jnp.ones((1,), jnp.float32),
                     jnp.zeros((K,), jnp.float32)])
                w = syn1neg[tgt]                   # (b, 1+K, d)
                if cbow:
                    logits = jnp.einsum("bd,bkd->bk", h, w)
                    g = ((lbl[None, :] - jax.nn.sigmoid(logits))
                         * tmask * exmask[:, None] * alpha)
                    syn1neg = scatter_add_agg(
                        syn1neg, tgt, g[:, :, None] * h[:, None, :])
                    dns = jnp.einsum("bk,bkd->bd", g, w)
                    d_syn0 = dns if d_syn0 is None else d_syn0 + dns
                    loss = loss - jnp.sum(
                        jax.nn.log_sigmoid(
                            jnp.where(lbl[None, :] > 0, logits, -logits))
                        * tmask * exmask[:, None])
                else:
                    logits = jnp.einsum("bcd,bkd->bck", hc, w)
                    g = ((lbl[None, None, :] - jax.nn.sigmoid(logits))
                         * tmask[:, None, :] * pmask[:, :, None] * alpha)
                    syn1neg = scatter_add_agg(
                        syn1neg, tgt, jnp.einsum("bck,bcd->bkd", g, hc))
                    dns = jnp.einsum("bck,bkd->bcd", g, w)
                    d_syn0 = dns if d_syn0 is None else d_syn0 + dns
                    loss = loss - jnp.sum(
                        jax.nn.log_sigmoid(
                            jnp.where(lbl[None, None, :] > 0, logits,
                                      -logits))
                        * tmask[:, None, :] * pmask[:, :, None])
            if cbow:
                # the (b, d) example gradient fans out to every live
                # context cell (un-divided — word2vec.c neu1e semantics)
                syn0 = scatter_add_agg(
                    syn0, words, d_syn0[:, None, :] * pmask[:, :, None])
                trained = jnp.sum(exmask)
            else:
                syn0 = scatter_add_agg(syn0, words, d_syn0)
                trained = jnp.sum(pmask)
            return (syn0, syn1, syn1neg, pair_count + trained,
                    loss_sum + loss), None

        init = (syn0, syn1, syn1neg, jnp.float32(0.0), jnp.float32(0.0))
        xs = (span_offset + jnp.arange(seg_spans), alphas, span_keys)
        (syn0, syn1, syn1neg, pairs, loss), _ = jax.lax.scan(
            body, init, xs)
        return syn0, syn1, syn1neg, pairs, loss

    return jax.jit(epoch, donate_argnums=(0, 1, 2))


def _trainer_tables(sv):
    """Device-resident sampling/Huffman tables shared by every corpus
    trainer (skip-gram/CBOW spans, PV-DBOW label pairs): subsample
    keep-probs, the block negative table, HS path arrays — with the
    same disabled-placeholder shapes everywhere."""
    keep = keep_probabilities(sv.vocab, sv.sampling)
    keep_prob = (jnp.asarray(keep) if keep is not None
                 else jnp.ones((1,), jnp.float32))
    if sv.negative > 0:
        neg_table = jnp.asarray(block_negative_table(
            sv.lookup_table.negative_table(), int(sv.negative), sv.seed))
    else:
        neg_table = jnp.zeros((1, 1), jnp.int32)
    if sv.use_hs:
        hs_points, hs_codes, hs_cmask = sv._code_arrays
    else:
        hs_points = jnp.zeros((1, 1), jnp.int32)
        hs_codes = jnp.zeros((1, 1))
        hs_cmask = jnp.zeros((1, 1))
    return keep_prob, neg_table, hs_points, hs_codes, hs_cmask


class _TrainerCounters:
    """Lazy pass counters + lifetime RNG shared by the device trainers:
    keys derive from the LIFETIME pass count so a cached pipeline
    re-fit never replays the first fit's draws; counters fetch (the
    device barrier) only in finish() so passes dispatch back-to-back
    and totals accumulate across fits."""

    def __init__(self):
        self.pairs_trained = 0.0
        self.loss_sum = 0.0
        self._pending: List = []
        self._passes_run = 0

    def _next_key(self, seed: int):
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 self._passes_run)
        self._passes_run += 1
        return key

    def finish(self) -> Tuple[float, float]:
        for pairs, loss in self._pending:
            self.pairs_trained += float(np.asarray(pairs))
            self.loss_sum += float(np.asarray(loss))
        self._pending = []
        return self.pairs_trained, self.loss_sum


def build_interleaved_label_arrays(seqs: List[np.ndarray],
                                   label_rows: List[int], chunk: int):
    """(corpus, pos_label, n) for the label-pair trainer, with document
    positions INTERLEAVED round-robin (doc0[0], doc1[0], ..., doc0[1],
    ...).  Contiguous layout would put one document's every position —
    all scattering into the SAME label row — inside one update chunk: a
    2000-word document at chunk 2048 is an effective ~2000x lr on its
    label (the duplicate-scatter divergence mechanism).  Interleaving
    bounds label duplicates per chunk at ~ceil(chunk / n_docs)."""
    lengths = np.array([s.size for s in seqs], np.int64)
    n = int(lengths.sum())
    if n == 0:
        corpus = np.zeros(chunk, np.int32)
        return corpus, np.full(chunk, -1, np.int32), 0
    # stable sort by depth-in-document == round-robin over documents,
    # O(n log n) time and O(n) memory (a dense (n_docs, max_len) matrix
    # is O(n_docs x longest_doc) — 40 GB for 100k short docs + one 50k-
    # word document)
    depth = np.concatenate([np.arange(sz) for sz in lengths])
    order = np.argsort(depth, kind="stable")
    flat_w = np.concatenate(seqs)[order]
    flat_l = np.repeat(np.asarray(label_rows, np.int64), lengths)[order]
    npad = max(chunk, ((n + chunk - 1) // chunk) * chunk)
    corpus = np.zeros(npad, np.int32)
    pos_label = np.full(npad, -1, np.int32)
    corpus[:n] = flat_w
    pos_label[:n] = flat_l
    return corpus, pos_label, n



class DeviceSkipGram(_TrainerCounters):
    """Device-resident corpus pipeline bound to a ``SequenceVectors``
    instance (skip-gram and CBOW element-learning algorithms; with
    ``label_rows`` the CBOW branch becomes PV-DM — each document's
    label row joins every window as an extra always-live column)."""

    def __init__(self, sv, seqs: List[np.ndarray],
                 label_rows: Optional[List[int]] = None,
                 algorithm: Optional[str] = None):
        self.sv = sv
        self.algorithm = algorithm or sv.algorithm
        if label_rows is not None and self.algorithm != "cbow":
            raise ValueError("label_rows requires the cbow branch")
        W = sv.window_size
        # Span sized so EXPECTED live examples per update step track
        # the host path's divergence clamp (``_effective_batch``).
        # Skip-gram: the dynamic shrink leaves ~(W+1)/2W of the grid
        # live, so span = eff / (live_frac * 2W) puts ~eff real pairs
        # in each batched scatter — the regime the host path was
        # stabilized for (sentence boundaries only lower occupancy).
        # CBOW trains ONE example per center, so span = eff directly.
        eff = max(64, sv._effective_batch())
        if self.algorithm == "cbow":
            self.span = max(16, eff)
        else:
            live_frac = (W + 1) / (2 * W)
            self.span = max(16, int(eff / (live_frac * 2 * W)))
        corpus, sent, n = build_corpus_arrays(seqs, self.span)
        self.n_words = n
        self.npad = corpus.shape[0]
        self.n_spans = self.npad // self.span
        self.corpus = jnp.asarray(corpus)
        self.sent = jnp.asarray(sent)
        if label_rows is not None:
            # per-position label row, aligned with build_corpus_arrays'
            # contiguous layout (padding/tail stay -1 via the sent ids)
            pos_label = np.where(
                sent >= 0,
                np.asarray(label_rows + [0], np.int32)[
                    np.maximum(sent, 0)],
                np.int32(-1))
            self.pos_label = jnp.asarray(pos_label.astype(np.int32))
        else:
            self.pos_label = jnp.zeros((self.npad,), jnp.int32)
        (self.keep_prob, self.neg_table, self.hs_points, self.hs_codes,
         self.hs_cmask) = _trainer_tables(sv)
        self._has_labels = label_rows is not None
        _TrainerCounters.__init__(self)

    def _seg_fn(self, seg_spans: int):
        sv = self.sv
        return _epoch_fn(sv.window_size, int(sv.negative), sv.use_hs,
                         self.span, seg_spans, self.n_spans,
                         sv.sampling > 0, self.npad, self.algorithm,
                         self._has_labels)

    def run_pass(self, pass_idx: int, total_words: int,
                 n_segments: int = 1) -> None:
        """One full corpus pass (epoch x iteration), optionally split
        into ``n_segments`` scan dispatches so a caller can INTERLEAVE
        several pipelines within a pass (ParagraphVectors: coarse
        word-then-label sequencing saturates the predictive tables
        before the label side sees a gradient).  Schedule, masking, and
        update math are identical at any segmentation; the final ragged
        segment's overrun spans are no-ops via the validity mask."""
        for seg in range(n_segments):
            self.run_segment(pass_idx, total_words, seg, n_segments)

    def run_segment(self, pass_idx: int, total_words: int, seg: int,
                    n_segments: int) -> None:
        sv = self.sv
        seg_spans = -(-self.n_spans // n_segments)
        lo = seg * seg_spans
        if lo >= self.n_spans:
            return
        seen0 = pass_idx * self.n_words
        starts = seen0 + (lo + np.arange(seg_spans)) * self.span
        alphas = np.maximum(
            sv.min_learning_rate,
            sv.learning_rate * (1.0 - starts / max(total_words + 1, 1)))
        key = self._next_key(sv.seed)
        lt = sv.lookup_table
        syn1 = lt.syn1 if sv.use_hs else jnp.zeros((1, 1), jnp.float32)
        syn1neg = (lt.syn1neg if sv.negative > 0
                   else jnp.zeros((1, 1), jnp.float32))
        syn0, syn1, syn1neg, pairs, loss = self._seg_fn(seg_spans)(
            lt.syn0, syn1, syn1neg, self.corpus, self.sent,
            self.pos_label, jnp.int32(self.n_words), self.keep_prob,
            self.neg_table, self.hs_points, self.hs_codes,
            self.hs_cmask, jnp.asarray(alphas.astype(np.float32)), key,
            jnp.int32(lo))
        lt.syn0 = syn0
        if sv.use_hs:
            lt.syn1 = syn1
        if sv.negative > 0:
            lt.syn1neg = syn1neg
        self._pending.append((pairs, loss))


@functools.lru_cache(maxsize=16)
def _labelpair_epoch_fn(negative: int, use_hs: bool, chunk: int,
                        seg_chunks: int, total_chunks: int,
                        subsample: bool):
    """PV-DBOW label->word training as one scan per corpus pass: each
    position contributes ONE (document label, word) pair (reference
    ``DBOW.java`` — no windowing), so the pipeline is the word2vec
    corpus scan minus the grid: per-position subsample draw, LCG
    negatives, and the shared HS/NS update math with the label row as
    the input vector."""
    K = negative
    labels_vec = jnp.asarray(np.concatenate(
        [[1.0], np.zeros(K)]).astype(np.float32)) if K > 0 else None

    def epoch(syn0, syn1, syn1neg, corpus, pos_label, keep_prob,
              neg_table, hs_points, hs_codes, hs_cmask, alphas, key,
              chunk_offset):
        span_keys = jax.random.split(key, seg_chunks)

        def body(carry, xs):
            syn0, syn1, syn1neg, pair_count, loss_sum = carry
            c, alpha, ckey = xs
            words = jax.lax.dynamic_slice(corpus, (c * chunk,), (chunk,))
            labs = jax.lax.dynamic_slice(pos_label, (c * chunk,),
                                         (chunk,))
            pm = (labs >= 0).astype(jnp.float32)   # -1 pads/OOV docs
            # ragged-final-segment overrun spans are no-ops
            pm = pm * (c < total_chunks).astype(jnp.float32)
            if subsample:
                kb, kn = jax.random.split(ckey)
                r = jax.random.uniform(kb, (chunk,))
                pm = pm * (r < keep_prob[words]).astype(jnp.float32)
            else:
                kn = ckey
            inputs = jnp.maximum(labs, 0)
            loss = jnp.float32(0.0)
            if use_hs:
                syn0, syn1, l_hs = _hs_update(
                    syn0, syn1, inputs, hs_points[words],
                    hs_codes[words], hs_cmask[words], pm, alpha)
                loss = loss + l_hs
            if K > 0:
                seed = jax.random.bits(kn, (), jnp.uint32)
                negs = lcg_negatives(seed, chunk, K, neg_table)
                tgt = jnp.concatenate([words[:, None], negs], axis=1)
                tmask = jnp.concatenate(
                    [jnp.ones((chunk, 1), jnp.float32),
                     (negs != words[:, None]).astype(jnp.float32)],
                    axis=1)
                syn0, syn1neg, l_ns = _ns_update(
                    syn0, syn1neg, inputs, tgt, labels_vec, tmask, pm,
                    alpha)
                loss = loss + l_ns
            return (syn0, syn1, syn1neg, pair_count + jnp.sum(pm),
                    loss_sum + loss), None

        init = (syn0, syn1, syn1neg, jnp.float32(0.0), jnp.float32(0.0))
        xs = (chunk_offset + jnp.arange(seg_chunks), alphas, span_keys)
        (syn0, syn1, syn1neg, pairs, loss), _ = jax.lax.scan(
            body, init, xs)
        return syn0, syn1, syn1neg, pairs, loss

    return jax.jit(epoch, donate_argnums=(0, 1, 2))


class DeviceDbowLabels(_TrainerCounters):
    """Device-resident PV-DBOW label-pair trainer bound to a
    ``ParagraphVectors`` instance: corpus words + per-position label
    rows upload once (document positions interleaved — see
    :func:`build_interleaved_label_arrays`); each pass is one scan
    dispatch.  Chunk size additionally clamps to ~4x the document
    count so label-row duplicates per update stay ~4; corpora with a
    handful of documents bottom out at chunk 8 (duplicates <= 8 — the
    interleave cannot help a single-document corpus, where every
    position shares one label row)."""

    def __init__(self, pv, seqs: List[np.ndarray],
                 label_rows: List[int]):
        _TrainerCounters.__init__(self)
        self.pv = pv
        eff = max(64, pv._effective_batch())
        self.chunk = int(min(eff, max(8, 4 * len(seqs))))
        corpus, pos_label, n = build_interleaved_label_arrays(
            seqs, label_rows, self.chunk)
        self.n_words = n
        self.n_chunks = corpus.shape[0] // self.chunk
        self.corpus = jnp.asarray(corpus)
        self.pos_label = jnp.asarray(pos_label)
        (self.keep_prob, self.neg_table, self.hs_points, self.hs_codes,
         self.hs_cmask) = _trainer_tables(pv)

    def _seg_fn(self, seg_chunks: int):
        pv = self.pv
        return _labelpair_epoch_fn(int(pv.negative), pv.use_hs,
                                   self.chunk, seg_chunks,
                                   self.n_chunks, pv.sampling > 0)

    def run_pass(self, pass_idx: int, total_words: int,
                 n_segments: int = 1) -> None:
        for seg in range(n_segments):
            self.run_segment(pass_idx, total_words, seg, n_segments)

    def run_segment(self, pass_idx: int, total_words: int, seg: int,
                    n_segments: int) -> None:
        pv = self.pv
        seg_chunks = -(-self.n_chunks // n_segments)
        lo = seg * seg_chunks
        if lo >= self.n_chunks:
            return
        seen0 = pass_idx * self.n_words
        starts = seen0 + (lo + np.arange(seg_chunks)) * self.chunk
        alphas = np.maximum(
            pv.min_learning_rate,
            pv.learning_rate * (1.0 - starts / max(total_words + 1, 1)))
        key = self._next_key(pv.seed + 7919)
        lt = pv.lookup_table
        syn1 = lt.syn1 if pv.use_hs else jnp.zeros((1, 1), jnp.float32)
        syn1neg = (lt.syn1neg if pv.negative > 0
                   else jnp.zeros((1, 1), jnp.float32))
        syn0, syn1, syn1neg, pairs, loss = self._seg_fn(seg_chunks)(
            lt.syn0, syn1, syn1neg, self.corpus, self.pos_label,
            self.keep_prob, self.neg_table, self.hs_points,
            self.hs_codes, self.hs_cmask,
            jnp.asarray(alphas.astype(np.float32)), key, jnp.int32(lo))
        lt.syn0 = syn0
        if pv.use_hs:
            lt.syn1 = syn1
        if pv.negative > 0:
            lt.syn1neg = syn1neg
        self._pending.append((pairs, loss))
