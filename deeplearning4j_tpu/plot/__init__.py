"""Plot/visualization tier: t-SNE dimensionality reduction.

Reference module: ``deeplearning4j-core/.../plot/`` (``BarnesHutTsne.java``
+ its quadtree/sptree support structures).
"""

from .tsne import Tsne

__all__ = ["Tsne"]
