"""t-SNE embedding (van der Maaten & Hinton 2008).

Reference: ``deeplearning4j-core/.../plot/BarnesHutTsne.java:848`` — the
Builder surface (perplexity, theta, learningRate, maxIter/numIterations,
momentum switch, early exaggeration), per-point sigma search to match the
target perplexity (``computeGaussianPerplexity``), gradient loop with
momentum + per-dimension gains, and ``saveCoordinates`` output.

TPU-first redesign: the reference approximates the repulsive force with a
Barnes-Hut quadtree/sptree (theta > 0) because exact t-SNE is O(N²) on a
CPU.  On TPU the exact N² affinity and gradient are a handful of MXU
matmuls — faster than any host-side tree walk for the N this API is used
at (embedding visualisations, ≤ tens of thousands of points) — so
``theta`` is accepted for surface parity but the computation is always
exact.  The entire optimisation (sigma bisection, P matrix, every
gradient iteration with momentum/gains/exaggeration) runs in ONE jitted
``lax.fori_loop`` program; nothing crosses the host boundary until the
final coordinates.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _sq_dists(x: Array) -> Array:
    n2 = jnp.sum(x * x, axis=1)
    d = n2[:, None] + n2[None, :] - 2.0 * x @ x.T
    return jnp.maximum(d, 0.0)


def _cond_probs(d_row: Array, beta: Array, i_mask: Array) -> Array:
    """p_{j|i} for one precision beta, self-probability masked to 0."""
    p = jnp.exp(-d_row * beta) * i_mask
    return p / jnp.maximum(p.sum(), 1e-12)


def _perplexity_search(d: Array, target_entropy: float,
                       iters: int = 50) -> Array:
    """Vectorised per-point bisection on beta = 1/(2 sigma^2) so each
    row's Shannon entropy matches log(perplexity) (reference
    ``computeGaussianPerplexity`` binary search, all rows at once)."""
    n = d.shape[0]
    eye_mask = 1.0 - jnp.eye(n, dtype=d.dtype)

    def entropy(beta):
        p = jnp.exp(-d * beta[:, None]) * eye_mask
        psum = jnp.maximum(p.sum(1), 1e-12)
        # H = log(sum) + beta * sum(d * p)/sum(p)
        return jnp.log(psum) + beta * jnp.sum(d * p, 1) / psum

    def body(_, state):
        beta, lo, hi = state
        h = entropy(beta)
        too_high = h > target_entropy          # entropy too big -> raise beta
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0,
                      (beta + new_hi) / 2.0),
            (new_lo + beta) / 2.0)
        return new_beta, new_lo, new_hi

    beta0 = jnp.ones(n, d.dtype)
    lo0 = jnp.zeros(n, d.dtype)
    hi0 = jnp.full(n, jnp.inf, d.dtype)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    return beta


# Only shape-determining knobs are jit-static (n_dims, max_iter); the
# scalar hyperparameters stay traced so a perplexity/lr sweep reuses one
# compiled program instead of recompiling the O(N^2) loop per value.
@functools.partial(jax.jit, static_argnums=(2, 4))
def _tsne_run(x: Array, key: Array, n_dims: int, perplexity,
              max_iter: int, learning_rate, switch_momentum,
              stop_lying_iteration, exaggeration):
    """Whole t-SNE optimisation as one XLA program."""
    n = x.shape[0]
    d = _sq_dists(x)
    beta = _perplexity_search(d, jnp.log(perplexity))
    eye_mask = 1.0 - jnp.eye(n, dtype=x.dtype)
    p = jnp.exp(-d * beta[:, None]) * eye_mask
    p = p / jnp.maximum(p.sum(1, keepdims=True), 1e-12)
    p = (p + p.T) / (2.0 * n)                      # symmetrize
    p = jnp.maximum(p, 1e-12)

    y0 = jax.random.normal(key, (n, n_dims), x.dtype) * 1e-2

    def grad_kl(y, p_eff):
        dy = _sq_dists(y)
        num = eye_mask / (1.0 + dy)                # student-t kernel
        q = num / jnp.maximum(num.sum(), 1e-12)
        q = jnp.maximum(q, 1e-12)
        w = (p_eff - q) * num                      # (N, N)
        # dC/dy_i = 4 sum_j w_ij (y_i - y_j)  -> two matmul-shaped ops
        g = 4.0 * (jnp.diag(w.sum(1)) - w) @ y
        kl = jnp.sum(p_eff * jnp.log(p_eff / q))
        return g, kl

    def body(it, state):
        y, vel, gains = state
        momentum = jnp.where(it < switch_momentum, 0.5, 0.8)
        lying = it < stop_lying_iteration
        p_eff = jnp.where(lying, p * exaggeration, p)
        g, _ = grad_kl(y, p_eff)
        # per-dimension gains (reference BarnesHutTsne gains update)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
        gains = jnp.maximum(gains, 0.01)
        vel = momentum * vel - learning_rate * gains * g
        y = y + vel
        y = y - y.mean(0, keepdims=True)           # recenter
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, max_iter, body,
        (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    _, kl = grad_kl(y, p)
    return y, kl


class Tsne:
    """Reference ``BarnesHutTsne`` Builder surface; exact computation
    (``theta`` accepted but ignored — see module docstring)."""

    def __init__(self, n_dims: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 max_iter: int = 1000, switch_momentum_iteration: int = 250,
                 stop_lying_iteration: int = 250,
                 exaggeration: float = 12.0, seed: int = 42,
                 normalize: bool = True):
        self.n_dims = n_dims
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.seed = seed
        self.normalize = normalize
        self.coords: Optional[np.ndarray] = None
        self.kl_divergence: float = float("nan")

    class Builder:
        """Reference ``BarnesHutTsne.Builder`` fluent surface: any
        constructor parameter as a chainable setter (``set_max_iter`` maps
        to ``max_iter``; unknown knobs from the reference surface, e.g.
        ``use_pca``, are accepted and ignored)."""

        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            if name.startswith("_"):
                raise AttributeError(name)

            def setter(value):
                key = name[4:] if name.startswith("set_") else name
                self._kw[key] = value
                return self
            return setter

        def build(self) -> "Tsne":
            import inspect
            valid = set(inspect.signature(Tsne.__init__).parameters)
            return Tsne(**{k: v for k, v in self._kw.items()
                           if k in valid})

    def fit(self, x) -> "Tsne":
        """Embed (reference ``BarnesHutTsne.fit``); coordinates land in
        ``.coords`` / ``get_coordinates()``."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] < 2:
            raise ValueError("fit expects an (n>=2, d) matrix")
        if self.perplexity * 3.0 > x.shape[0] - 1:
            raise ValueError(
                f"perplexity {self.perplexity} too large for n={x.shape[0]}"
                " (need n-1 >= 3*perplexity)")
        if self.normalize:
            x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-12)
        y, kl = _tsne_run(
            jnp.asarray(x), jax.random.PRNGKey(self.seed), self.n_dims,
            jnp.float32(self.perplexity), int(self.max_iter),
            jnp.float32(self.learning_rate),
            jnp.int32(self.switch_momentum_iteration),
            jnp.int32(self.stop_lying_iteration),
            jnp.float32(self.exaggeration))
        self.coords = np.asarray(y)
        self.kl_divergence = float(kl)
        return self

    def fit_transform(self, x) -> np.ndarray:
        return self.fit(x).get_coordinates()

    def get_coordinates(self) -> np.ndarray:
        if self.coords is None:
            raise RuntimeError("call fit() first")
        return self.coords

    def save_coordinates(self, path: str, labels=None) -> None:
        """CSV of embedded coordinates, one row per point with optional
        trailing label (reference ``BarnesHutTsne.saveCoordsForPlot``)."""
        coords = self.get_coordinates()
        with open(path, "w", encoding="utf-8") as f:
            for i in range(coords.shape[0]):
                row = [f"{v:.6f}" for v in coords[i]]
                if labels is not None:
                    row.append(str(labels[i]))
                f.write(",".join(row) + "\n")
