"""t-SNE embedding (van der Maaten & Hinton 2008).

Reference: ``deeplearning4j-core/.../plot/BarnesHutTsne.java:848`` — the
Builder surface (perplexity, theta, learningRate, maxIter/numIterations,
momentum switch, early exaggeration), per-point sigma search to match the
target perplexity (``computeGaussianPerplexity``), gradient loop with
momentum + per-dimension gains, and ``saveCoordinates`` output.

TPU-first redesign: the reference approximates the repulsive force with a
Barnes-Hut quadtree/sptree (theta > 0) because exact t-SNE is O(N²) on a
CPU.  On TPU the exact N² affinity and gradient are a handful of MXU
matmuls — faster than any host-side tree walk at moderate N — so below
``tile_threshold`` points the computation is exact and ``theta`` is
accepted for surface parity only.  Above the threshold, materialising
(N, N) would blow device memory, so the run switches to a tiled program:
the attractive term sparsifies P to the 3·perplexity nearest neighbours
(the same sparsification Barnes-Hut t-SNE applies to P) and the repulsive
term stays EXACT but is computed in (block, N) tiles.  Either way the
entire optimisation (sigma bisection, affinities, every gradient
iteration with momentum/gains/exaggeration) runs as ONE jitted XLA
program; nothing crosses the host boundary until the final coordinates.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _block_sq_dists(xb: Array, x: Array) -> Array:
    """(B, N) squared distances from a row block to all points."""
    n2b = jnp.sum(xb * xb, axis=1)
    n2 = jnp.sum(x * x, axis=1)
    d = n2b[:, None] + n2[None, :] - 2.0 * xb @ x.T
    return jnp.maximum(d, 0.0)


def _sq_dists(x: Array) -> Array:
    return _block_sq_dists(x, x)


def _opt_step(it, y, vel, gains, g, learning_rate, switch_momentum):
    """One shared gradient-descent step: momentum switch + per-dimension
    gains (reference BarnesHutTsne gains update) — used by both the exact
    and tiled paths so they cannot drift apart."""
    momentum = jnp.where(it < switch_momentum, 0.5, 0.8)
    same_sign = jnp.sign(g) == jnp.sign(vel)
    gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    gains = jnp.maximum(gains, 0.01)
    vel = momentum * vel - learning_rate * gains * g
    return y + vel, vel, gains


def _perplexity_search(d: Array, target_entropy: float,
                       iters: int = 50) -> Array:
    """Vectorised per-point bisection on beta = 1/(2 sigma^2) so each
    row's Shannon entropy matches log(perplexity) (reference
    ``computeGaussianPerplexity`` binary search, all rows at once).

    ``d`` is (N, M): dense N² distances OR (N, k) neighbour distances.
    Entries to exclude (self, padding) must be pre-set to +inf — they get
    zero probability for every beta > 0."""
    n = d.shape[0]
    d_safe = jnp.where(jnp.isfinite(d), d, 0.0)   # inf*0 would NaN the sum

    def entropy(beta):
        p = jnp.exp(-d * beta[:, None])
        psum = jnp.maximum(p.sum(1), 1e-12)
        # H = log(sum) + beta * sum(d * p)/sum(p)
        return jnp.log(psum) + beta * jnp.sum(d_safe * p, 1) / psum

    def body(_, state):
        beta, lo, hi = state
        h = entropy(beta)
        too_high = h > target_entropy          # entropy too big -> raise beta
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0,
                      (beta + new_hi) / 2.0),
            (new_lo + beta) / 2.0)
        return new_beta, new_lo, new_hi

    beta0 = jnp.ones(n, d.dtype)
    lo0 = jnp.zeros(n, d.dtype)
    hi0 = jnp.full(n, jnp.inf, d.dtype)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    return beta


# Only shape-determining knobs are jit-static (n_dims, max_iter); the
# scalar hyperparameters stay traced so a perplexity/lr sweep reuses one
# compiled program instead of recompiling the O(N^2) loop per value.
@functools.partial(jax.jit, static_argnums=(2, 4))
def _tsne_run(x: Array, key: Array, n_dims: int, perplexity,
              max_iter: int, learning_rate, switch_momentum,
              stop_lying_iteration, exaggeration):
    """Whole t-SNE optimisation as one XLA program."""
    n = x.shape[0]
    eye_mask = 1.0 - jnp.eye(n, dtype=x.dtype)
    d = jnp.where(eye_mask > 0, _sq_dists(x), jnp.inf)
    beta = _perplexity_search(d, jnp.log(perplexity))
    p = jnp.exp(-jnp.where(eye_mask > 0, d, 0.0) * beta[:, None]) * eye_mask
    p = p / jnp.maximum(p.sum(1, keepdims=True), 1e-12)
    p = (p + p.T) / (2.0 * n)                      # symmetrize
    p = jnp.maximum(p, 1e-12)

    y0 = jax.random.normal(key, (n, n_dims), x.dtype) * 1e-2

    def grad_kl(y, p_eff):
        dy = _sq_dists(y)
        num = eye_mask / (1.0 + dy)                # student-t kernel
        q = num / jnp.maximum(num.sum(), 1e-12)
        q = jnp.maximum(q, 1e-12)
        w = (p_eff - q) * num                      # (N, N)
        # dC/dy_i = 4 sum_j w_ij (y_i - y_j)  -> two matmul-shaped ops
        g = 4.0 * (jnp.diag(w.sum(1)) - w) @ y
        kl = jnp.sum(p_eff * jnp.log(p_eff / q))
        return g, kl

    def body(it, state):
        y, vel, gains = state
        lying = it < stop_lying_iteration
        p_eff = jnp.where(lying, p * exaggeration, p)
        g, _ = grad_kl(y, p_eff)
        y, vel, gains = _opt_step(it, y, vel, gains, g, learning_rate,
                                  switch_momentum)
        y = y - y.mean(0, keepdims=True)           # recenter
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, max_iter, body,
        (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    _, kl = grad_kl(y, p)
    return y, kl


@functools.partial(jax.jit, static_argnums=(2, 3))
def _knn(x: Array, mask: Array, k: int, block: int):
    """Blocked k-nearest-neighbour pass: (N, k) distances + indices without
    ever materialising (N, N).  Self and padding columns are pushed to +inf
    so they never make the top-k."""
    n = x.shape[0]
    idx_all = jnp.arange(n)
    inf = jnp.asarray(jnp.inf, x.dtype)

    def one_block(start):
        rows = start + jnp.arange(block)
        d = _block_sq_dists(jax.lax.dynamic_slice_in_dim(x, start, block), x)
        d = jnp.where(idx_all[None, :] == rows[:, None], inf, d)
        d = jnp.where(mask[None, :] > 0, d, inf)
        neg_d, idx = jax.lax.top_k(-d, k)
        return -neg_d, idx

    starts = jnp.arange(0, n, block)
    dists, idxs = jax.lax.map(one_block, starts)
    return dists.reshape(n, k), idxs.reshape(n, k)


@functools.partial(jax.jit, static_argnums=(3, 6, 11, 12))
def _tsne_run_tiled(x: Array, mask: Array, key: Array, n_dims: int,
                    n_real, perplexity, max_iter: int, learning_rate,
                    switch_momentum, stop_lying_iteration, exaggeration,
                    k: int, block: int):
    """Large-N t-SNE: kNN-sparse attractive term (k = 3*perplexity
    neighbours — the same sparsification Barnes-Hut t-SNE uses for P, see
    reference ``BarnesHutTsne.java:848`` / van der Maaten 2014) plus an
    EXACT repulsive term computed in (block, N) tiles.  Peak device memory
    is O(N*k + block*N) instead of O(N²); the whole optimisation is still
    one XLA program."""
    n = x.shape[0]
    knn_d, knn_idx = _knn(x, mask, k, block)
    beta = _perplexity_search(knn_d, jnp.log(perplexity))
    p = jnp.exp(-knn_d * beta[:, None]) * mask[:, None]
    p = p / jnp.maximum(p.sum(1, keepdims=True), 1e-12)
    p = p * mask[:, None]                     # pad rows contribute nothing
    # Symmetrised sparse P is handled edge-wise: every directed edge
    # (i -> knn_idx[i,l], p[i,l]) contributes p/2N to BOTH endpoints'
    # attractive force, which is exactly (P + P^T)/2N without building the
    # union sparsity pattern.
    src = jnp.repeat(jnp.arange(n), k)
    dst = knn_idx.reshape(-1)
    pval = p.reshape(-1) / (2.0 * n_real)

    y0 = jax.random.normal(key, (n, n_dims), x.dtype) * 1e-2
    idx_all = jnp.arange(n)

    def repulsion(y):
        """Tiled exact repulsion: returns (sum_j num_ij^2 (y_i - y_j), Z)."""
        def one_block(start):
            rows = start + jnp.arange(block)
            yb = jax.lax.dynamic_slice_in_dim(y, start, block)
            dy = _block_sq_dists(yb, y)
            num = 1.0 / (1.0 + dy)
            num = jnp.where(idx_all[None, :] == rows[:, None], 0.0, num)
            num = num * mask[None, :] * mask[rows][:, None]
            z_part = num.sum()
            n2 = num * num
            rep = n2.sum(1, keepdims=True) * yb - n2 @ y
            return rep, z_part

        starts = jnp.arange(0, n, block)
        reps, z_parts = jax.lax.map(one_block, starts)
        return reps.reshape(n, n_dims), z_parts.sum()

    def grad_kl(y, exagger):
        ys, yd = y[src], y[dst]
        w = 1.0 / (1.0 + jnp.sum((ys - yd) ** 2, axis=1))
        pe = pval * exagger
        attr_edge = (pe * w)[:, None] * (ys - yd)
        attr = (jnp.zeros_like(y).at[src].add(attr_edge)
                .at[dst].add(-attr_edge))
        rep, z = repulsion(y)
        g = 4.0 * (attr - rep / jnp.maximum(z, 1e-12))
        # KL over the sparse support (standard BH-t-SNE reporting).  The
        # exact path sums p_sym log(p_sym/q) over ALL ordered pairs with
        # total P mass 1; here each undirected edge is (usually) seen from
        # both endpoints with half the symmetrized mass, so 2*pval is the
        # p_sym estimate per directed edge and the total mass is ~1 —
        # keeping kl_divergence on the same scale across tile_threshold.
        q = jnp.maximum(w / jnp.maximum(z, 1e-12), 1e-12)
        p_sym = 2.0 * pval
        kl = jnp.sum(p_sym * jnp.log(jnp.maximum(p_sym, 1e-12) / q))
        return g, kl

    def body(it, state):
        y, vel, gains = state
        exagger = jnp.where(it < stop_lying_iteration, exaggeration, 1.0)
        g, _ = grad_kl(y, exagger)
        y, vel, gains = _opt_step(it, y, vel, gains, g, learning_rate,
                                  switch_momentum)
        mean = (jnp.sum(y * mask[:, None], 0, keepdims=True) / n_real)
        y = (y - mean) * mask[:, None]
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, max_iter, body, (y0 * mask[:, None], jnp.zeros_like(y0),
                            jnp.ones_like(y0)))
    _, kl = grad_kl(y, jnp.asarray(1.0, x.dtype))
    return y, kl


class Tsne:
    """Reference ``BarnesHutTsne`` Builder surface.  Below
    ``tile_threshold`` points the computation is exact and one-shot (see
    module docstring); above it, it switches to the tiled path
    (``_tsne_run_tiled``) so device memory stays O(N*k + block*N) instead
    of the exact path's O(N²) — the TPU-native answer to the reference's
    Barnes-Hut tree."""

    def __init__(self, n_dims: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 max_iter: int = 1000, switch_momentum_iteration: int = 250,
                 stop_lying_iteration: int = 250,
                 exaggeration: float = 12.0, seed: int = 42,
                 normalize: bool = True, tile_threshold: int = 4096,
                 block_size: int = 1024):
        self.n_dims = n_dims
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.seed = seed
        self.normalize = normalize
        self.tile_threshold = int(tile_threshold)
        self.block_size = int(block_size)
        self.coords: Optional[np.ndarray] = None
        self.kl_divergence: float = float("nan")

    class Builder:
        """Reference ``BarnesHutTsne.Builder`` fluent surface: any
        constructor parameter as a chainable setter (``set_max_iter`` maps
        to ``max_iter``; unknown knobs from the reference surface, e.g.
        ``use_pca``, are accepted and ignored)."""

        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            if name.startswith("_"):
                raise AttributeError(name)

            def setter(value):
                key = name[4:] if name.startswith("set_") else name
                self._kw[key] = value
                return self
            return setter

        def build(self) -> "Tsne":
            import inspect
            valid = set(inspect.signature(Tsne.__init__).parameters)
            return Tsne(**{k: v for k, v in self._kw.items()
                           if k in valid})

    def fit(self, x) -> "Tsne":
        """Embed (reference ``BarnesHutTsne.fit``); coordinates land in
        ``.coords`` / ``get_coordinates()``."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] < 2:
            raise ValueError("fit expects an (n>=2, d) matrix")
        if self.perplexity * 3.0 > x.shape[0] - 1:
            raise ValueError(
                f"perplexity {self.perplexity} too large for n={x.shape[0]}"
                " (need n-1 >= 3*perplexity)")
        if self.normalize:
            x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-12)
        n = x.shape[0]
        if n <= self.tile_threshold:
            y, kl = _tsne_run(
                jnp.asarray(x), jax.random.PRNGKey(self.seed), self.n_dims,
                jnp.float32(self.perplexity), int(self.max_iter),
                jnp.float32(self.learning_rate),
                jnp.int32(self.switch_momentum_iteration),
                jnp.int32(self.stop_lying_iteration),
                jnp.float32(self.exaggeration))
            self.coords = np.asarray(y)
        else:
            block = min(self.block_size, n)
            n_pad = ((n + block - 1) // block) * block
            xp = np.concatenate(
                [x, np.zeros((n_pad - n, x.shape[1]), x.dtype)])
            mask = np.concatenate(
                [np.ones(n, np.float32), np.zeros(n_pad - n, np.float32)])
            k = min(int(3 * self.perplexity), n - 1)
            y, kl = _tsne_run_tiled(
                jnp.asarray(xp), jnp.asarray(mask),
                jax.random.PRNGKey(self.seed), self.n_dims,
                jnp.float32(n), jnp.float32(self.perplexity),
                int(self.max_iter), jnp.float32(self.learning_rate),
                jnp.int32(self.switch_momentum_iteration),
                jnp.int32(self.stop_lying_iteration),
                jnp.float32(self.exaggeration), k, block)
            self.coords = np.asarray(y)[:n]
        self.kl_divergence = float(kl)
        return self

    def fit_transform(self, x) -> np.ndarray:
        return self.fit(x).get_coordinates()

    def get_coordinates(self) -> np.ndarray:
        if self.coords is None:
            raise RuntimeError("call fit() first")
        return self.coords

    def save_coordinates(self, path: str, labels=None) -> None:
        """CSV of embedded coordinates, one row per point with optional
        trailing label (reference ``BarnesHutTsne.saveCoordsForPlot``)."""
        coords = self.get_coordinates()
        with open(path, "w", encoding="utf-8") as f:
            for i in range(coords.shape[0]):
                row = [f"{v:.6f}" for v in coords[i]]
                if labels is not None:
                    row.append(str(labels[i]))
                f.write(",".join(row) + "\n")
