"""Pallas flash attention — the hot-op kernel tier.

The reference's hot ops live in native cuDNN helpers
(``deeplearning4j-cuda/``); this build's equivalents are XLA lowerings
(``ops/convolution.py``) plus, where fusion beyond XLA pays, hand-written
Pallas TPU kernels.  Attention is the canonical case: materializing the
(T, T) score matrix is HBM-bandwidth-bound, while the flash formulation
keeps score tiles in VMEM with streaming-softmax accumulators and only
ever writes the (T, d) output.

:func:`flash_attention` — blockwise attention over (batch, T, heads, d):
grid (batch*heads, q_blocks, k_blocks), with the innermost k-block loop
accumulating into VMEM scratch (running max / denominator / weighted
sum — the same log-sum-exp stream ``parallel/sequence.ring_attention``
runs ACROSS chips; this kernel is the within-chip tier of the same
algorithm).  f32 accumulation regardless of input dtype; causal masking
by global block position; off-TPU (tests, CPU mesh) runs in Pallas
interpret mode.

Backward: a ``jax.custom_vjp`` recomputes gradients through the pure-XLA
reference formulation (`parallel/sequence._full_attention`) — exact
gradients at XLA-path memory cost; a fused backward kernel is the
remaining optimization headroom.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool, block_q: int,
                  block_k: int, seq_len: int, num_k_blocks: int,
                  precision):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    # Causal: a k block strictly above this q block's diagonal contributes
    # nothing — skip its compute entirely (halves causal FLOPs).
    needed = (ki * block_k <= qi * block_q + block_q - 1) \
        if causal else (ki >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)           # (block_q, d)
        k = k_ref[0].astype(jnp.float32)           # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision) * sm_scale
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos < seq_len, s, _NEG_INF)    # T padding
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # (block_q, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alive = m_new > _NEG_INF / 2
        p = jnp.where(alive, jnp.exp(s - m_new), 0.0)
        correction = jnp.where(alive, jnp.exp(m_prev - m_new), 0.0)
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _pad_to(x: Array, axis: int, multiple: int) -> Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q: Array, k: Array, v: Array, causal: bool,
                   sm_scale: float, block_q: int, block_k: int,
                   interpret: bool, precision) -> Array:
    B, T, H, D = q.shape
    bh = B * H

    import math

    def to_bhd(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(bh, T, D)
        # lcm, not max: both block sizes must divide the padded T or
        # floor-divided block counts silently drop trailing blocks
        x = _pad_to(x, 1, math.lcm(block_q, block_k))
        return _pad_to(x, 2, 128)      # lane-width padding; zeros are
        #                                inert in q.k^T and p@v

    qt, kt, vt = to_bhd(q), to_bhd(k), to_bhd(v)
    Tp, Dp = qt.shape[1], qt.shape[2]
    nq, nk = Tp // block_q, Tp // block_k

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=T, num_k_blocks=nk, precision=precision)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, Tp, Dp), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dp),
                               lambda b, qi, ki: (b, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, Dp), jnp.float32),    # weighted sum
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :T, :D].reshape(B, H, T, D)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                precision):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, precision)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret,
               precision):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret, precision)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, precision,
               res, g):
    from ..parallel.sequence import _full_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _full_attention(q, k, v, causal=causal,
                                        sm_scale=sm_scale), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: Array, k: Array, v: Array, *, causal: bool = False,
                    sm_scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None,
                    precision: Optional[jax.lax.Precision] = None) -> Array:
    """Flash attention over (batch, T, heads, d_head) q/k/v.

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, Pallas
    interpret mode elsewhere (slow but exact — the CPU-mesh test path).
    ``precision``: MXU precision for the two dots — default matches
    XLA's fast-f32 path (bf16 passes, ~1e-3 abs error at randn scale);
    ``jax.lax.Precision.HIGHEST`` gives ~1e-6 at 3x the MXU work.
    Differentiable via custom VJP (see module docstring)."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} "
                         f"{v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected (batch, T, heads, d), got {q.shape}")
    scale = (float(sm_scale) if sm_scale is not None
             else 1.0 / float(np.sqrt(q.shape[-1])))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    T = q.shape[1]
    # clamp to the sequence, rounded UP to the f32 sublane tile (8):
    # Mosaic cannot tile a (1, block, d) BlockSpec whose sublane dim
    # isn't a multiple of 8; padding covers block > T
    block_q = -(-min(block_q, max(8, T)) // 8) * 8
    block_k = -(-min(block_k, max(8, T)) // 8) * 8
    return _flash_core(q, k, v, causal, scale, block_q, block_k,
                       bool(interpret), precision)
