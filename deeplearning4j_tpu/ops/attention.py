"""Pallas flash attention — the hot-op kernel tier.

The reference's hot ops live in native cuDNN helpers
(``deeplearning4j-cuda/``); this build's equivalents are XLA lowerings
(``ops/convolution.py``) plus, where fusion beyond XLA pays, hand-written
Pallas TPU kernels.  Attention is the canonical case: materializing the
(T, T) score matrix is HBM-bandwidth-bound, while the flash formulation
keeps score tiles in VMEM with streaming-softmax accumulators and only
ever writes the (T, d) output.

:func:`flash_attention` — blockwise attention over (batch, T, heads, d):
grid (batch*heads, q_blocks, k_blocks), with the innermost k-block loop
accumulating into VMEM scratch (running max / denominator / weighted
sum — the same log-sum-exp stream ``parallel/sequence.ring_attention``
runs ACROSS chips; this kernel is the within-chip tier of the same
algorithm).  f32 accumulation regardless of input dtype; causal masking
by block position; off-TPU (tests, CPU mesh) runs in Pallas interpret
mode.

:func:`flash_attention_partial` — the same kernel emitting UNNORMALIZED
(acc, m, l) partials so callers can fold in blocks computed elsewhere;
``parallel/sequence.ring_flash_attention`` builds on it.

Backward: by default a FUSED two-pass Pallas backward (dK/dV then dQ)
rebuilds P tiles in VMEM from the forward's saved per-row logsumexp —
O(T·d) memory end to end, so full training steps run at T=16384 where
the XLA attention path cannot even compile its forward.
``fused_backward=False`` falls back to recomputing through the XLA
formulation (`parallel/sequence._full_attention`).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_NEG_INF = -1e30


def _make_flash_kernel(*, mode: str, sm_scale: float,
                       causal: bool, block_q: int, block_k: int,
                       k_len: int, num_k_blocks: int, precision):
    """ONE streaming-softmax kernel body for all forward variants —
    ``mode``: "normalized" (out), "partials" (unnormalized acc + m + l),
    or "normalized_lse" (out + per-row logsumexp, the fused-backward
    forward).  Only the finalize step differs, so the numerically
    delicate core cannot drift between them."""
    if mode not in ("normalized", "partials", "normalized_lse"):
        raise ValueError(f"unknown kernel mode {mode!r}")

    def kernel(q_ref, k_ref, v_ref, *refs):
        if mode == "partials":
            o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
        elif mode == "normalized_lse":
            (o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
            m_ref = l_ref = None
        else:
            (o_ref, m_scr, l_scr, acc_scr), m_ref, l_ref = refs, None, None
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr[:], _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr[:])
            acc_scr[:] = jnp.zeros_like(acc_scr[:])

        # Causal: a k block strictly above this q block's diagonal
        # contributes nothing — skip its compute (halves causal FLOPs).
        needed = (ki * block_k <= qi * block_q + block_q - 1) \
            if causal else (ki >= 0)

        @pl.when(needed)
        def _compute():
            q = q_ref[0].astype(jnp.float32)       # (block_q, d)
            k = k_ref[0].astype(jnp.float32)       # (block_k, d)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=precision) * sm_scale
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos < k_len, s, _NEG_INF)   # T padding
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

            m_prev = m_scr[:, :1]                  # (block_q, 1)
            l_prev = l_scr[:, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            alive = m_new > _NEG_INF / 2
            p = jnp.where(alive, jnp.exp(s - m_new), 0.0)
            correction = jnp.where(alive, jnp.exp(m_prev - m_new), 0.0)
            l_new = l_prev * correction + jnp.sum(p, axis=-1,
                                                  keepdims=True)
            acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
                p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
            l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

        @pl.when(ki == num_k_blocks - 1)
        def _finalize():
            if mode == "partials":
                o_ref[0] = acc_scr[:]
                m_ref[0] = m_scr[:]
                l_ref[0] = l_scr[:]
            else:
                denom = jnp.maximum(l_scr[:, :1], 1e-30)
                o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
                if mode == "normalized_lse":
                    lse = m_scr[:, :1] + jnp.log(denom)
                    lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)

    return kernel


# ----------------------------------------------------------- shared plumbing
def _pad_to(x: Array, axis: int, multiple: int) -> Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _sds(shape, dtype, like: Array) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying ``like``'s shard_map varying-axes tag
    (required for pallas_call under shard_map with vma checking)."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _clamp_block(block: int, t: int) -> int:
    """Clamp to the sequence, rounded UP to the f32 sublane tile (8):
    Mosaic cannot tile a (1, block, d) BlockSpec whose sublane dim isn't
    a multiple of 8; padding covers block > t."""
    return -(-min(block, max(8, t)) // 8) * 8


def _auto_block(t: int) -> int:
    """Default block size for sequence length ``t``: the largest tuned
    tile whose padding overhead (T rounds up to a block multiple; padded
    rows are masked but still computed) stays under 12.5%.  512 is the
    measured v5e optimum at large T (2.6x over 128 at T=8192 — bigger
    tiles amortize the logsumexp bookkeeping over more MXU work; 1024
    regresses, 2048 exceeds VMEM); odd lengths degrade gracefully
    (e.g. T=640 -> 128, zero padding) instead of paying up to 2.5x
    padded FLOPs."""
    for b in (512, 256, 128):
        if -(-t // b) * b <= t * 1.125:
            return b
    return 128


def _to_bhd(x: Array, block: int) -> Array:
    """(B, T, H, D) -> (B*H, T_padded, D_padded): T padded to the block
    multiple, D to the 128 lane width (zero padding is inert in q.k^T
    and p@v)."""
    B, T, H, D = x.shape
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, T, D)
    return _pad_to(_pad_to(x, 1, block), 2, 128)


def _validate_qkv(q: Array, k: Array, v: Array,
                  same_t: bool) -> None:
    if q.ndim != 4:
        raise ValueError(f"expected (batch, T, heads, d), got {q.shape}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if same_t and q.shape != k.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} "
                         f"{v.shape}")
    if (q.shape[0], q.shape[2], q.shape[3]) != \
            (k.shape[0], k.shape[2], k.shape[3]):
        raise ValueError(
            f"q and k/v disagree on batch/heads/d: {q.shape} vs {k.shape}")


# ----------------------------------------------------------------- forward
def _flash_forward(q: Array, k: Array, v: Array, causal: bool,
                   sm_scale: float, block_q: int, block_k: int,
                   interpret: bool, precision,
                   with_lse: bool = False):
    B, T, H, D = q.shape
    bh = B * H
    # lcm, not max: both block sizes must divide the padded T or
    # floor-divided block counts silently drop trailing blocks
    pad_mult = math.lcm(block_q, block_k)
    qt = _to_bhd(q, pad_mult)
    kt, vt = _to_bhd(k, pad_mult), _to_bhd(v, pad_mult)
    Tp, Dp = qt.shape[1], qt.shape[2]
    nq, nk = Tp // block_q, Tp // block_k

    kernel = _make_flash_kernel(
        mode="normalized_lse" if with_lse else "normalized",
        sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, k_len=T, num_k_blocks=nk, precision=precision)
    out_shapes = [_sds((bh, Tp, Dp), q.dtype, qt)]
    out_specs = [pl.BlockSpec((1, block_q, Dp),
                              lambda b, qi, ki: (b, qi, 0))]
    if with_lse:
        out_shapes.append(_sds((bh, Tp, 128), jnp.float32, qt))
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda b, qi, ki: (b, qi, 0)))
    result = pl.pallas_call(
        kernel,
        out_shape=out_shapes if with_lse else out_shapes[0],
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, Dp), jnp.float32),    # weighted sum
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = result[0] if with_lse else result

    def back(x, d_keep):
        x = x[:, :T, :d_keep].reshape(B, H, T, d_keep)
        return jnp.transpose(x, (0, 2, 1, 3))

    if with_lse:
        return back(out, D), back(result[1], 1)[..., 0]   # (B,T,H) lse
    return back(out, D)


def flash_attention_partial(q: Array, k: Array, v: Array, *,
                            causal: bool = False,
                            sm_scale: Optional[float] = None,
                            block_q: int = 128, block_k: int = 128,
                            interpret: Optional[bool] = None,
                            precision=None):
    """Unnormalized blockwise attention of ``q`` against ONE K/V segment
    (``k``/``v`` may have a different T than ``q``).

    Returns ``(acc, m, l)`` with ``acc`` (batch, Tq, heads, d) f32 —
    the exp-weighted value sum — and ``m``/``l`` (batch, Tq, heads) f32
    running max / denominator.  Partials from different K/V segments
    (e.g. ring-rotated shards) merge exactly via the log-sum-exp
    combination (see ``parallel/sequence.ring_flash_attention``); the
    final output is ``acc / l``.  ``causal`` masks by LOCAL positions —
    correct for the diagonal ring step where q and kv shards share their
    global offset.  Padded q rows are trimmed post-hoc, not masked
    in-kernel (their partials are garbage but never returned).  Not
    differentiable; callers own the VJP.
    """
    _validate_qkv(q, k, v, same_t=False)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = (float(sm_scale) if sm_scale is not None
             else 1.0 / float(np.sqrt(D)))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q = _clamp_block(block_q, Tq)
    block_k = _clamp_block(block_k, Tk)
    bh = B * H

    qt = _to_bhd(q, block_q)
    kt, vt = _to_bhd(k, block_k), _to_bhd(v, block_k)
    Tqp, Dp = qt.shape[1], qt.shape[2]
    nq, nk = Tqp // block_q, kt.shape[1] // block_k

    kernel = _make_flash_kernel(
        mode="partials", sm_scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, k_len=Tk, num_k_blocks=nk,
        precision=precision)
    acc, m, l = pl.pallas_call(
        kernel,
        out_shape=[
            _sds((bh, Tqp, Dp), jnp.float32, qt),
            _sds((bh, Tqp, 128), jnp.float32, qt),
            _sds((bh, Tqp, 128), jnp.float32, qt),
        ],
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, Dp), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)

    def back(x, d_keep):
        x = x[:, :Tq, :d_keep].reshape(B, H, Tq, d_keep)
        return jnp.transpose(x, (0, 2, 1, 3))

    return back(acc, D), back(m, 1)[..., 0], back(l, 1)[..., 0]


# ------------------------------------------------------- fused backward
def _bwd_tile(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, qi, ki, *,
              sm_scale, causal, block_q, block_k, q_len, k_len,
              precision):
    """The shared P-rebuild tile math of BOTH backward kernels: returns
    (q, k, do, p, ds) for one (q-block, k-block) tile.  One body so the
    numerically delicate core cannot drift between dK/dV and dQ (the
    same invariant the forward keeps via _make_flash_kernel)."""
    q = q_ref[0].astype(jnp.float32)           # (block_q, d)
    k = k_ref[0].astype(jnp.float32)           # (block_k, d)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision) * sm_scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = (k_pos < k_len) & (q_pos < q_len)
    if causal:
        mask &= q_pos >= k_pos
    L = L_ref[0][:, :1]                        # (block_q, 1) logsumexp
    p = jnp.where(mask, jnp.exp(s - L), 0.0)
    do = do_ref[0].astype(jnp.float32)         # (block_q, d)
    dp = jax.lax.dot_general(
        do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    D = D_ref[0][:, :1]
    ds = p * (dp - D) * sm_scale
    return q, k, do, p, ds


def _make_dkdv_kernel(*, num_q_blocks: int, precision, **tile_kw):
    """Grid (bh, k_blocks, q_blocks): accumulate dK/dV for one k-block
    across all q-blocks, rebuilding P tiles from the saved logsumexp —
    no (T, T) materialization."""
    causal = tile_kw["causal"]
    block_q, block_k = tile_kw["block_q"], tile_kw["block_k"]

    def kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, dk_ref, dv_ref,
               dk_scr, dv_scr):
        ki = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_scr[:] = jnp.zeros_like(dk_scr[:])
            dv_scr[:] = jnp.zeros_like(dv_scr[:])

        needed = (qi * block_q + block_q - 1 >= ki * block_k) \
            if causal else (qi >= 0)

        @pl.when(needed)
        def _compute():
            q, _, do, p, ds = _bwd_tile(
                q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, qi, ki,
                precision=precision, **tile_kw)
            dv_scr[:] += jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)
            dk_scr[:] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)

        @pl.when(qi == num_q_blocks - 1)
        def _finalize():
            dk_ref[0] = dk_scr[:]
            dv_ref[0] = dv_scr[:]

    return kernel


def _make_dq_kernel(*, num_k_blocks: int, precision, **tile_kw):
    """Grid (bh, q_blocks, k_blocks): accumulate dQ for one q-block."""
    causal = tile_kw["causal"]
    block_q, block_k = tile_kw["block_q"], tile_kw["block_k"]

    def kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, dq_ref, dq_scr):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            dq_scr[:] = jnp.zeros_like(dq_scr[:])

        needed = (ki * block_k <= qi * block_q + block_q - 1) \
            if causal else (ki >= 0)

        @pl.when(needed)
        def _compute():
            _, k, _, _, ds = _bwd_tile(
                q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, qi, ki,
                precision=precision, **tile_kw)
            dq_scr[:] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)

        @pl.when(ki == num_k_blocks - 1)
        def _finalize():
            dq_ref[0] = dq_scr[:]

    return kernel


def _row_stat_to_bhd(x: Array, block: int) -> Array:
    """(B, T, H) per-row statistic -> (B*H, T_padded, 128) lane-broadcast
    layout the backward kernels read as ``ref[0][:, :1]``."""
    B, T, H = x.shape
    x = jnp.transpose(x, (0, 2, 1)).reshape(B * H, T)
    x = _pad_to(x, 1, block)
    return jnp.broadcast_to(x[:, :, None], x.shape + (128,))


def flash_attention_bwd(q: Array, k: Array, v: Array, out: Array,
                        L: Array, g: Array, *, causal: bool,
                        sm_scale: float, block_q: int = 128,
                        block_k: int = 128,
                        interpret: Optional[bool] = None,
                        precision=None, D_row: Optional[Array] = None):
    """Fused flash backward: (dq, dk, dv) from the forward residuals
    ``out`` and the per-row logsumexp ``L = m + log(l)`` — two Pallas
    passes (dK/dV then dQ), O(T·d) memory, no (T, T) tensors.

    ``k``/``v`` may carry a different T than ``q`` (one K/V SEGMENT of a
    larger sequence): with a GLOBAL ``L``/``D_row``, the returned grads
    are this segment's exact contribution, and contributions from
    different segments SUM — the property the ring backward in
    ``parallel/sequence`` is built on.  ``D_row`` (rowsum(dO·out) per q
    row) defaults to being computed from ``out``/``g``; segment callers
    pass the global value."""
    if out is None and D_row is None:
        raise ValueError("flash_attention_bwd needs `out` (to derive "
                         "D = rowsum(dO*out)) or an explicit `D_row`")
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q = _clamp_block(block_q, Tq)
    block_k = _clamp_block(block_k, Tk)
    bh = B * H

    qt = _to_bhd(q, block_q)
    kt, vt = _to_bhd(k, block_k), _to_bhd(v, block_k)
    dot = _to_bhd(g.astype(jnp.float32), block_q)
    Tqp, Dp = qt.shape[1], qt.shape[2]
    nq, nk = Tqp // block_q, kt.shape[1] // block_k

    # D_i = rowsum(dO * O): cheap elementwise, stays in XLA
    Drow = (D_row if D_row is not None
            else jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                         axis=-1))                            # (B, Tq, H)
    Lt = _row_stat_to_bhd(L, block_q)
    Dt = _row_stat_to_bhd(Drow, block_q)

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, q_len=Tq, k_len=Tk, precision=precision)
    Tkp = kt.shape[1]
    dk, dv = pl.pallas_call(
        _make_dkdv_kernel(num_q_blocks=nq, **common),
        out_shape=[_sds((bh, Tkp, Dp), jnp.float32, qt)] * 2,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_q, Dp), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, Dp), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, ki, qi: (b, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, Dp), jnp.float32),
            pltpu.VMEM((block_k, Dp), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, Lt, Dt)

    dq = pl.pallas_call(
        _make_dq_kernel(num_k_blocks=nk, **common),
        out_shape=_sds((bh, Tqp, Dp), jnp.float32, qt),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_q, Dp), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dp),
                               lambda b, qi, ki: (b, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, Dp), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, Lt, Dt)

    def back(x, t):
        x = x[:, :t, :D].reshape(B, H, t, D)
        return jnp.transpose(x, (0, 2, 1, 3))

    # f32 out: segment callers (the ring backward) SUM contributions, and
    # rounding each one to a low input dtype first would compound n-fold;
    # the VJP boundary casts once
    return back(dq, Tq), back(dk, Tk), back(dv, Tk)


# --------------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_core(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                precision, fused_backward):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret, precision)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret,
               precision, fused_backward):
    if not fused_backward:
        out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                             interpret, precision)
        return out, (q, k, v, None, None)
    # normalized_lse mode: the kernel finalizes out in-VMEM and emits
    # only the one per-row logsumexp residual the backward needs.
    out, L = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret, precision, with_lse=True)
    return out, (q, k, v, out, L)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, precision,
               fused_backward, res, g):
    q, k, v, out, L = res
    if fused_backward:
        dq, dk, dv = flash_attention_bwd(
            q, k, v, out, L, g, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            precision=precision)
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))
    from ..parallel.sequence import _full_attention
    _, vjp = jax.vjp(
        lambda q, k, v: _full_attention(q, k, v, causal=causal,
                                        sm_scale=sm_scale), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: Array, k: Array, v: Array, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    precision: Optional[jax.lax.Precision] = None,
                    fused_backward: bool = True) -> Array:
    """Flash attention over (batch, T, heads, d_head) q/k/v.

    ``block_q``/``block_k`` default to an auto-tuned size (see
    ``_auto_block``: 512 at large T — measured 2.6x over 128 for
    fwd+fused-bwd at T=8192 on v5e — smaller when T would pad
    wastefully).

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, Pallas
    interpret mode elsewhere (slow but exact — the CPU-mesh test path).
    ``precision``: MXU precision for the two dots — default matches
    XLA's fast-f32 path (bf16 passes, ~1e-3 abs error at randn scale);
    ``jax.lax.Precision.HIGHEST`` gives ~1e-6 at 3x the MXU work.
    ``fused_backward=True`` (default) differentiates through two fused
    Pallas passes (dK/dV then dQ) rebuilding P tiles from the saved
    logsumexp — O(T·d) backward memory; ``False`` falls back to
    recomputing through the XLA formulation (O(T²) scores under grad)."""
    _validate_qkv(q, k, v, same_t=True)
    scale = (float(sm_scale) if sm_scale is not None
             else 1.0 / float(np.sqrt(q.shape[-1])))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    T = q.shape[1]
    block_q = _clamp_block(block_q if block_q is not None
                           else _auto_block(T), T)
    block_k = _clamp_block(block_k if block_k is not None
                           else _auto_block(T), T)
    return _flash_core(q, k, v, causal, scale, block_q, block_k,
                       bool(interpret), precision, bool(fused_backward))


# --------------------------------------------------------------------------
# KV-cache ring decode: the inference twin of flash_attention.
#
# Autoregressive serving keeps per-session K/V projections resident on
# device in fixed-capacity (batch, heads, cache_len, d) buffers plus an
# int32 write cursor; each decode step writes the new token's K/V at the
# cursor via ``lax.dynamic_update_slice`` INSIDE the compiled program (the
# cache never crosses the wire) and attends the new queries against the
# whole ring with exact cursor masking.
#
# Parity contract (the bit-match the serving tests assert): slots at
# positions > cursor + t are masked with ``_NEG_INF``; ``exp`` of those
# scores underflows to EXACTLY 0.0, so masked slots contribute exact
# additive/multiplicative zeros to the softmax denominator and the P·V
# reduction.  Adding structural zeros never re-pairs the surviving terms
# of a reduction, so the result is bitwise independent of the ring
# capacity — decoding one token at a time against a 32-slot ring matches
# the full-sequence forward against a 128-slot ring to the last ulp
# (``tests/test_decode.py`` pins this at float64).


def kv_ring_update(k_cache: Array, v_cache: Array, cursor,
                   k_new: Array, v_new: Array):
    """Write (batch, heads, T, d) new keys/values into the ring at the
    cursor.  ``cursor`` may be a traced int32 scalar — the write happens
    inside the compiled step, in place when XLA can alias the buffers.
    Callers guarantee ``cursor + T <= cache_len`` (``dynamic_update_slice``
    clamps out-of-range starts, which would silently overwrite the
    newest history — ``serving.sessions`` hops to a larger bucket
    first)."""
    zero = jnp.zeros((), jnp.int32)
    cursor = jnp.asarray(cursor, jnp.int32)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_new.astype(k_cache.dtype), (zero, zero, cursor, zero))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_new.astype(v_cache.dtype), (zero, zero, cursor, zero))
    return k_cache, v_cache


def kv_ring_attention(q: Array, k_cache: Array, v_cache: Array, cursor, *,
                      sm_scale: Optional[float] = None) -> Array:
    """Dense masked attention of (batch, T, heads, d) queries against a
    (batch, heads, cache_len, d) KV ring whose slot ``c`` is visible to
    query ``t`` iff ``c <= cursor + t`` (causality within the chunk plus
    unwritten/stale-slot masking in one predicate).

    Softmax runs in f32 (f64 under float64 inputs — the parity-test
    dtype); the context comes back in the query dtype.  O(T·cache_len)
    — the right tier for T=1 decode steps, where the score "matrix" is
    a single row and flash tiling has nothing to save."""
    if q.ndim != 4 or k_cache.ndim != 4:
        raise ValueError(
            f"kv_ring_attention wants (B,T,H,d) q and (B,H,C,d) cache, "
            f"got q {q.shape}, k {k_cache.shape}")
    scale = (float(sm_scale) if sm_scale is not None
             else 1.0 / float(np.sqrt(q.shape[-1])))
    acc = jnp.promote_types(q.dtype, jnp.float32)
    cap = k_cache.shape[2]
    t = q.shape[1]
    cursor = jnp.asarray(cursor, jnp.int32)
    # (B,T,H,d) x (B,H,C,d) -> (B,H,T,C), f32/f64 accumulation
    s = jnp.einsum("bthd,bhcd->bhtc", q.astype(acc),
                   k_cache.astype(acc)) * jnp.asarray(scale, acc)
    valid = (jnp.arange(cap, dtype=jnp.int32)[None, :]
             <= cursor + jnp.arange(t, dtype=jnp.int32)[:, None])
    s = jnp.where(valid[None, None], s, jnp.asarray(_NEG_INF, acc))
    # every query sees at least its own key, so the row max is finite
    # and masked slots exp to exactly 0.0
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    ctx = jnp.einsum("bhtc,bhcd->bthd", p, v_cache.astype(acc))
    return ctx.astype(q.dtype)
